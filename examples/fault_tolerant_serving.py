"""Fault-tolerant serving of the sharded LM — the engine demo.

Runs the full ISSUE-1 story on the CPU backend with deterministic
fault injection: an `InferenceEngine` over a (data x model) mesh
survives a transient mid-decode failure (retry → byte-identical),
quarantines a poisoned request without hurting its batch peers, sheds
a deadline-blown request while the batch completes, trips + recovers
its circuit breaker, and hot-reloads weights from a checkpoint
directory — printing health() along the way.

ISSUE-2 addendum: everything publishes into ONE observability
registry (engine counters/histograms, a PerformanceListener's
training series, an AsyncDataSetIterator's prefetch gauges), a
`MetricsServer` exports it, and the demo ends by fetching and
printing a real curl-able `/metrics` sample.

ISSUE-6 addendum: the same exporter now also serves `/debugz`, `/slo`
and `/timeline.json` — the demo prints the quarantined request's
flight-recorder trace (retry -> preempted -> quarantined, the
per-request "why"), the windowed TTFT/TPOT/goodput SLO report, and
where to load the Perfetto slot timeline.

On a TPU slice this uses all chips; elsewhere:
  JAX_PLATFORMS=cpu python examples/fault_tolerant_serving.py
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main() -> None:
    import jax
    from jax._src import xla_bridge as _xb

    n_dev = 4
    # bootstrap BEFORE the first backend touch: on jax<0.6 a live CPU
    # client cannot be resized (no jax_num_cpu_devices), so querying
    # jax.devices() first would lock in a 1-device mesh
    if not _xb.backends_are_initialized():
        from __graft_entry__ import _force_virtual_cpu_mesh
        try:
            _force_virtual_cpu_mesh(n_dev)
        except Exception:
            pass              # fall through to whatever mesh exists

    from deeplearning4j_tpu import observability as obs
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       init_params)
    from deeplearning4j_tpu.parallel.failure import ServingFaultInjector
    from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
    from deeplearning4j_tpu.serving import (DeadlineExceeded,
                                            EngineConfig,
                                            InferenceEngine,
                                            OverloadError,
                                            RequestQuarantined)
    from deeplearning4j_tpu.train.listeners import PerformanceListener
    from deeplearning4j_tpu.util.checkpointing import CheckpointManager

    cfg = TransformerConfig(vocab_size=64, d_model=64, n_heads=4,
                            n_layers=2, max_len=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    if len(jax.devices()) >= n_dev:
        mesh = make_mesh(MeshSpec(data=2, model=2))
    else:                     # unresizable 1-device client (old jax)
        mesh = make_mesh(MeshSpec(data=1, model=1))
    prompt = np.arange(16, dtype=np.int32)

    # one shared registry: engine + training listener + prefetch all
    # publish into it, and the exporter serves it
    registry = obs.default_registry()
    inj = ServingFaultInjector(fail_at=[1])      # one transient fault
    eng = InferenceEngine(
        cfg, mesh, params,
        EngineConfig(decode_chunk=4, max_new_tokens=16,
                     backoff_base_s=0.001, breaker_failure_threshold=3,
                     breaker_cooldown_s=0.2),
        fault_injector=inj, registry=registry)
    eng.set_listeners(PerformanceListener(frequency=1, report=False,
                                          registry=registry))
    exporter = obs.MetricsServer(registry, port=0, health=eng.health,
                                 ready=eng.ready, debug=eng.debugz,
                                 slo=eng.slo_report,
                                 timeline=eng.timeline)
    print(f"[metrics] exporter at {exporter.url}/metrics "
          "(healthz/readyz/debugz/slo/timeline.json wired to the "
          "engine)")

    # 1. transient fault: retried, completes
    h = eng.submit(prompt)
    eng.run_pending()
    print(f"[transient] completed after {eng.stats['retries']} retry; "
          f"tokens={h.result().shape[0]}")

    # 2. poisoned request quarantined; co-batched peer completes
    bad = eng.submit(prompt)
    good = eng.submit(prompt)
    inj.poison_requests.add(bad.rid)
    eng.run_pending()
    try:
        bad.result()
    except RequestQuarantined as e:
        print(f"[quarantine] {e}")
    print(f"[quarantine] peer status={good.status}")
    # the flight recorder kept the per-request forensics: the
    # quarantined request's own lifecycle, ready for /debugz
    print(f"[trace] bad request lifecycle: {bad.trace.kinds()}")
    print(f"[trace] peer lifecycle:        {good.trace.kinds()}")

    # 3. deadline shed mid-decode (injected host stall)
    inj.delay_at[eng._step_counter + 1] = 0.1
    doomed = eng.submit(prompt, deadline_s=0.05)
    peer = eng.submit(prompt)
    eng.run_pending()
    try:
        doomed.result()
    except DeadlineExceeded as e:
        print(f"[deadline] {e}")
    print(f"[deadline] peer decoded {peer.result().shape[0] - 16} "
          "tokens")

    # 4. load shedding + breaker
    try:
        for _ in range(200):
            eng.submit(prompt)
    except OverloadError as e:
        print(f"[overload] {e}")
    eng.run_pending()
    print(f"[health] {eng.health()}")

    # 5. hot weight reload from a checkpoint directory
    ckpt = tempfile.mkdtemp(prefix="serving_ckpt_")
    mgr = CheckpointManager(ckpt, use_orbax=False)
    mgr.save_tree(params, step=7)
    step = eng.reload_weights(mgr)
    print(f"[reload] weights hot-reloaded from step {step}; "
          f"ready={eng.ready()}")

    # 6. input pipeline: a few batches through AsyncDataSetIterator
    # publish prefetch_* series into the SAME registry the engine and
    # listener already feed
    from deeplearning4j_tpu.datasets.iterators import (
        AsyncDataSetIterator, DataSet, ExistingDataSetIterator)
    batches = [DataSet(np.zeros((4, 8), np.float32),
                       np.zeros((4, 2), np.float32)) for _ in range(6)]
    n = sum(1 for _ in AsyncDataSetIterator(
        ExistingDataSetIterator(batches), queue_size=2,
        registry=registry))
    print(f"[prefetch] {n} batches through the async prefetcher")

    # 7. scrape the exporter exactly like `curl <url>/metrics` would:
    # one end-to-end run produced serving, training, AND prefetch
    # series on one endpoint
    from urllib.request import urlopen
    text = urlopen(f"{exporter.url}/metrics", timeout=5).read().decode()
    lines = text.splitlines()
    keep = ("serving_requests", "serving_decode_step_seconds_count",
            "serving_batch_size_count", "training_", "prefetch_")
    sample = [l for l in lines
              if not l.startswith("#") and l.startswith(keep)]
    print(f"[metrics] GET /metrics -> {len(lines)} lines; sample:")
    for line in sample:
        print(f"  {line}")

    # 8. the serving introspection endpoints (ISSUE-6): the windowed
    # SLO report and the Perfetto-loadable slot timeline
    import json
    rep = json.loads(urlopen(f"{exporter.url}/slo",
                             timeout=5).read().decode())
    print(f"[slo] window={rep['window']} goodput={rep['goodput']:.2f} "
          f"ttft_p50={rep['ttft_p50_ms']}ms "
          f"ttft_p99={rep['ttft_p99_ms']}ms "
          f"tpot_p99={rep['tpot_p99_ms']}ms")
    tl = json.loads(urlopen(f"{exporter.url}/timeline.json",
                            timeout=5).read().decode())
    print(f"[timeline] GET /timeline.json -> "
          f"{len(tl['traceEvents'])} trace events (load in "
          "https://ui.perfetto.dev: one lane per slot + queue lane)")
    dbg = json.loads(urlopen(f"{exporter.url}/debugz",
                             timeout=5).read().decode())
    print(f"[debugz] breaker={dbg['breaker']} "
          f"queue_depth={dbg['queue_depth']} "
          f"recent_events={dbg['recorder_events']}")
    exporter.stop()


if __name__ == "__main__":
    main()
