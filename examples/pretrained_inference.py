"""Pretrained-model inference with prediction decoding — the
reference's TrainedModels flow (TrainedModels.java:
model -> preprocess -> output -> decodePredictions) end to end.

Loads a locally provided Keras HDF5 model (the reference downloads
DL4J-converted VGG16 weights; zero-egress hosts supply their own
checkpoint — the repo's trained test fixture works out of the box),
runs inference, and decodes predictions with the ImageNet-labels
machinery (`modelimport/labels.py`): `get_predicted_classes` (argmax
API), `top_k` (structured), and `decode_predictions` (the reference's
exact string format). A custom class-index JSON stands in for
ImageNet's when the model isn't 1000-way.

Run: python examples/pretrained_inference.py \
         [--model tests/fixtures/real_vgg16_trained.h5]
"""
import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    root = os.path.join(os.path.dirname(__file__), "..")
    ap.add_argument("--model", default=os.path.join(
        root, "tests", "fixtures", "real_vgg16_trained.h5"))
    ap.add_argument("--labels", default=None,
                    help="class-index JSON (Keras schema); defaults "
                    "to a digits table matching the fixture model")
    args = ap.parse_args()

    from deeplearning4j_tpu.modelimport import (ImageNetLabels,
                                                decode_predictions,
                                                get_predicted_classes,
                                                load_vgg16, top_k)

    if not os.path.exists(args.model):
        sys.exit(f"model {args.model} not found — generate fixtures "
                 "with tests/fixtures/generate_keras_fixtures.py or "
                 "pass --model")

    default_model = os.path.abspath(ap.get_default("model"))
    labels_path = args.labels
    if labels_path is None and os.path.abspath(args.model) == \
            default_model:
        # the DEFAULT fixture model classifies sklearn digits (10
        # classes) — a digits table stands in for ImageNet's. A
        # user-supplied --model keeps labels.py's normal resolution
        # chain (explicit/env/keras-cache/download) instead
        idx = {str(i): [f"n{i:08d}", name] for i, name in enumerate(
            ["zero", "one", "two", "three", "four", "five", "six",
             "seven", "eight", "nine"])}
        labels_path = os.path.join(tempfile.mkdtemp(), "idx.json")
        with open(labels_path, "w") as f:
            json.dump(idx, f)
    if labels_path is not None:
        os.environ["DL4JTPU_IMAGENET_INDEX"] = labels_path
        ImageNetLabels._labels = None  # re-resolve against the env var

    net = load_vgg16(args.model)
    golden = os.path.splitext(args.model)[0] + "_golden.npz"
    gdata = dict(np.load(golden)) if os.path.exists(golden) else {}
    if "x" in gdata:
        x = gdata["x"]
    elif gdata:
        sys.exit(f"{golden} has inputs {sorted(gdata)} — multi-input "
                 "models aren't covered by this single-input example")
    else:
        itype = getattr(net.conf, "input_type", None)
        shape = (tuple(itype.array_shape(4)) if itype is not None
                 else (4, 32, 32, 3))
        x = np.random.default_rng(0).random(shape, np.float32)
    out = net.output(x)
    if isinstance(out, (list, tuple)):   # ComputationGraph: [outputs]
        out = out[0]
    out = np.asarray(out)

    classes = get_predicted_classes(out)
    print("predicted classes:", classes.tolist())
    for row in top_k(out[:2], k=3):
        print("top-3:", [(lbl, round(p, 3)) for _, lbl, p in row])
    print(decode_predictions(out[:1], top=3))


if __name__ == "__main__":
    main()
