"""Tensor+data-parallel generation — sharded serving of the flagship LM.

Net-new vs the reference, whose serving story is single-process
`MultiLayerNetwork.output`/`rnnTimeStep`: here autoregressive KV-cache
decode runs SPMD over a (data x model) mesh — megatron-sharded
heads/MLP, per-device cache shards, one psum per step
(parallel/serving.py). Greedy parallel decode reproduces the
single-chip `models/transformer.generate` token-for-token; sampled
decode carries the full single-chip surface (temperature / top-k /
nucleus) and matches token-for-token on TP-only meshes (r5).

On a TPU slice this uses all chips; elsewhere:
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python examples/sharded_serving.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


import jax
import jax.numpy as jnp

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   init_params)
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.parallel.serving import (make_parallel_generate,
                                                 shard_serving_params)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", type=int, default=2)
    ap.add_argument("--model", type=int, default=2)
    ap.add_argument("--new-tokens", type=int, default=32)
    ap.add_argument("--temperature", type=float, default=1.0)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--top-p", type=float, default=1.0)
    args = ap.parse_args()

    n_dev = args.data * args.model
    try:
        have = len(jax.devices())
    except Exception:
        have = 0          # unreachable tunnel: fall back to CPU mesh
    if have < n_dev:
        from __graft_entry__ import _force_virtual_cpu_mesh
        _force_virtual_cpu_mesh(n_dev)
    mesh = make_mesh(MeshSpec(data=args.data, model=args.model))
    cfg = TransformerConfig(vocab_size=256, d_model=128, n_heads=8,
                            n_layers=4, max_len=256)
    params = shard_serving_params(
        init_params(cfg, jax.random.PRNGKey(0)), cfg, mesh)
    pgen = make_parallel_generate(cfg, mesh,
                                  max_new_tokens=args.new_tokens,
                                  top_k=args.top_k, top_p=args.top_p,
                                  temperature=args.temperature)
    prompt = jnp.tile(jnp.arange(16, dtype=jnp.int32)[None],
                      (2 * args.data, 1))
    out = pgen(params, prompt, jax.random.PRNGKey(7))
    print(f"mesh data={args.data} model={args.model}; generated "
          f"{out.shape[0]}x{out.shape[1]} tokens")
    print("first row:", list(map(int, out[0])))


if __name__ == "__main__":
    main()
