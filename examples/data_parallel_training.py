"""Data-parallel training over a device mesh (the reference's
ParallelWrapper / SparkDl4jMultiLayer examples).

On a TPU slice this uses all chips; elsewhere set
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
for a virtual mesh. Multi-host: launch one copy per host with
JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID set and
call initialize_multihost() first (parallel/multihost.py).
"""
import jax

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from deeplearning4j_tpu.datasets.impl import MnistDataSetIterator
from deeplearning4j_tpu.models.zoo import mlp_mnist
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
from deeplearning4j_tpu.scaleout import (ParameterAveragingTrainingMaster,
                                         SparkDl4jMultiLayer)


def main() -> None:
    n = len(jax.devices())
    print(f"{n} device(s): {jax.devices()}")

    net = MultiLayerNetwork(mlp_mnist()).init()
    # direct wrapper (reference: ParallelWrapper)
    pw = ParallelWrapper(net, workers=n)
    pw.fit(MnistDataSetIterator(batch_size=64 * n, num_examples=6400))
    print("wrapper-trained score:", float(net.score_value))

    # TrainingMaster facade (reference: SparkDl4jMultiLayer)
    net2 = MultiLayerNetwork(mlp_mnist(seed=9)).init()
    tm = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=64)
          .workers(n).collect_training_stats(True).build())
    sp = SparkDl4jMultiLayer(net2, tm)
    sp.fit(MnistDataSetIterator(batch_size=64 * n, num_examples=6400))
    print("facade-trained score:", float(net2.score_value))
    sp.stats.export_stats_html("/tmp/training_stats.html")
    print("phase stats:", sp.stats.as_dict())


if __name__ == "__main__":
    main()
