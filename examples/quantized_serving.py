"""Quantized continuous-batching serving — int8 weights + int8 slot KV.

Round-10 subsystem (docs/quantization.md): the engine quantizes the
weight tree ON LOAD (per-output-channel symmetric absmax int8 via
`quant.model.quantize_params`) and runs the slot-pool KV cache as int8
rows + per-row float32 scales (`quant/kv.py`) — ~4x fewer at-rest
bytes on both axes, which on the slot-bound continuous-batching path
means ~4x the concurrent slots per HBM byte. `quantize="fp8"` requests
the e4m3 variant and falls back to int8 off-TPU (`resolve_mode`).

The example serves one burst of mixed-length prompts through a float
engine and an int8/int8 engine over the SAME params and mesh, then
prints both engines' HBM accounting (the `serving_param_bytes` /
`serving_kv_*` pull gauges surfaced via health()) and the served
tokens side by side.

On a TPU slice this uses all chips; elsewhere:
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python examples/quantized_serving.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


import jax
import numpy as np

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   init_params)
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.serving.engine import (EngineConfig,
                                               InferenceEngine)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", type=int, default=2)
    ap.add_argument("--model", type=int, default=2)
    ap.add_argument("--quantize", default="int8",
                    choices=["int8", "fp8"])
    ap.add_argument("--new-tokens", type=int, default=16)
    ap.add_argument("--requests", type=int, default=6)
    args = ap.parse_args()

    n_dev = args.data * args.model
    try:
        have = len(jax.devices())
    except Exception:
        have = 0          # unreachable tunnel: fall back to CPU mesh
    if have < n_dev:
        from __graft_entry__ import _force_virtual_cpu_mesh
        _force_virtual_cpu_mesh(n_dev)
    mesh = make_mesh(MeshSpec(data=args.data, model=args.model))
    cfg = TransformerConfig(vocab_size=256, d_model=128, n_heads=8,
                            n_layers=4, max_len=256)
    params = init_params(cfg, jax.random.PRNGKey(0))
    econf = EngineConfig(max_batch_size=4, max_new_tokens=args.new_tokens,
                         decode_chunk=4)

    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size,
                            int(rng.integers(8, 33))).astype(np.int32)
               for _ in range(args.requests)]

    engines = {
        "float32": InferenceEngine(cfg, mesh, params, econf),
        args.quantize: InferenceEngine(cfg, mesh, params, econf,
                                       quantize=args.quantize,
                                       kv_quantize=args.quantize),
    }
    results = {}
    for name, eng in engines.items():
        hs = [eng.submit(p) for p in prompts]
        eng.run_pending()
        results[name] = [h.result(5.0) for h in hs]
        h = eng.health()
        print(f"[{name:>7}] quantize={h['quantize']} "
              f"kv={h['kv_quantize']}  "
              f"param_bytes={h['param_bytes']:>10,}  "
              f"kv_pool_bytes={h['kv_pool_bytes']:>10,}  "
              f"kv_bytes/slot={h['kv_bytes_per_slot']:>9,}")

    fbytes = engines["float32"].health()
    qbytes = engines[args.quantize].health()
    resident_f = fbytes["param_bytes"] + fbytes["kv_pool_bytes"]
    resident_q = qbytes["param_bytes"] + qbytes["kv_pool_bytes"]
    print(f"resident weight+KV bytes: {resident_f:,} -> {resident_q:,} "
          f"({100 * (1 - resident_q / resident_f):.1f}% smaller)")

    names = list(results)
    match = np.mean([
        float(np.mean(a[p.shape[0]:] == b[p.shape[0]:]))
        for p, a, b in zip(prompts, results[names[0]],
                           results[names[1]])])
    print(f"greedy token agreement ({names[0]} vs {names[1]}): "
          f"{100 * match:.1f}%")
    first = results[names[1]][0]
    print(f"sample continuation (quantized, request 0): "
          f"{first[prompts[0].shape[0]:].tolist()}")


if __name__ == "__main__":
    main()
