"""Word2Vec training + similarity queries (the reference's
Word2VecRawTextExample flow).

Run: python examples/word2vec_basic.py [--corpus path]
(no --corpus → small built-in corpus)
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


from deeplearning4j_tpu.nlp import (BasicLineIterator,
                                    CollectionSentenceIterator,
                                    CommonPreprocessor,
                                    DefaultTokenizerFactory, Word2Vec,
                                    WordVectorSerializer)

BUILTIN = [
    "the quick brown fox jumps over the lazy dog",
    "the lazy dog sleeps while the quick fox runs",
    "a king rules the kingdom and a queen rules beside the king",
    "the queen and the king host a feast in the kingdom",
    "day turns to night and night turns to day",
] * 50


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--corpus", default=None)
    ap.add_argument("--out", default="/tmp/word_vectors.txt")
    args = ap.parse_args()

    iterator = (BasicLineIterator(args.corpus) if args.corpus
                else CollectionSentenceIterator(BUILTIN))
    tf = DefaultTokenizerFactory(CommonPreprocessor())
    w2v = (Word2Vec.builder()
           .iterate(iterator)
           .tokenizer_factory(tf)
           .layer_size(64).window_size(5)
           .min_word_frequency(2).negative_sample(5)
           .epochs(3).seed(42).build())
    w2v.fit()

    for a, b in [("king", "queen"), ("day", "night"), ("king", "dog")]:
        if w2v.has_word(a) and w2v.has_word(b):
            print(f"similarity({a}, {b}) = {w2v.similarity(a, b):.3f}")
    if w2v.has_word("king"):
        print("nearest to 'king':", w2v.words_nearest("king", 5))
    WordVectorSerializer.write_word_vectors(w2v, args.out)
    print("vectors written to", args.out)


if __name__ == "__main__":
    main()
