"""Grammar-constrained decoding demo: schema-valid JSON from a fleet.

Builds a 2-replica in-process fleet behind the health-aware `Router`
(deeplearning4j_tpu/serving/fleet.py) and submits requests whose
outputs MUST satisfy a JSON schema — `submit(constrain=...)` compiles
the schema into a token-level DFA (`serving/constrain.py`) whose
allow-masks gate every sampling step as pure runtime data, so the
engine's compiled-program set stays closed. The demo shows:

- every constrained request decodes to bytes that `json.loads`
  accepts and that match the declared property set — 100% of them,
  by construction, not by luck;
- a regex-constrained request alongside, truncated at its grammar's
  terminal state (early completion before max_new_tokens);
- unconstrained requests sharing the same slots, token-identical to
  a constrain-free engine;
- the `serving_constrained_*` scrape rows (requests, grammar
  compiles, terminal completions, live DFA-table rows) and a typed
  `ConstraintError` rejection for an unsupported pattern.

Run: JAX_PLATFORMS=cpu python examples/constrained_serving.py
"""
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402

from deeplearning4j_tpu.models.transformer import (  # noqa: E402
    TransformerConfig, init_params)
from deeplearning4j_tpu.observability.export import (  # noqa: E402
    prometheus_text)
from deeplearning4j_tpu.parallel.mesh import (  # noqa: E402
    MeshSpec, make_mesh)
from deeplearning4j_tpu.serving import (  # noqa: E402
    ConstraintError, EngineConfig, FleetConfig, Router)

#: The constrained token map is byte-level: token id i <-> bytes([i])
#: for ids below 256, so decoded outputs ARE the UTF-8 text.
VOCAB = 256

SCHEMA = {
    "type": "object",
    "properties": {
        "status": {"enum": ["ok", "retry", "dead"]},
        "attempts": {"type": "integer"},
        "fatal": {"type": "boolean"},
    },
}


def main() -> None:
    cfg = TransformerConfig(vocab_size=VOCAB, d_model=64, n_heads=4,
                            n_layers=2, max_len=128)
    mesh = make_mesh(MeshSpec(data=1, model=1))
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)

    router = Router(cfg=cfg, mesh=mesh, params=params, num_replicas=2,
                    engine_config=EngineConfig(
                        max_batch_size=4, max_new_tokens=48,
                        decode_chunk=4, backoff_base_s=0.0),
                    config=FleetConfig(restart_backoff_base_s=0.05))
    try:
        prompts = [rng.integers(0, VOCAB, 8).astype(np.int32)
                   for _ in range(6)]
        print("submitting 4 schema-constrained + 1 regex-constrained "
              "+ 1 unconstrained request...\n")
        schema_hs = [router.submit(
            p, max_new_tokens=48,
            constrain={"type": "json_schema", "schema": SCHEMA})
            for p in prompts[:4]]
        regex_h = router.submit(prompts[4], max_new_tokens=48,
                                constrain="(GET|PUT) /[a-z]{1,8}")
        free_h = router.submit(prompts[5], max_new_tokens=12)
        router.run_pending()

        valid = 0
        for i, h in enumerate(schema_hs):
            gen = h.result(0)[prompts[i].shape[0]:]
            text = bytes(int(t) for t in gen).decode()
            doc = json.loads(text)          # raises if not valid JSON
            assert set(doc) <= set(SCHEMA["properties"])
            valid += 1
            print(f"  schema[{i}]: {text}")
        print(f"\nschema-valid outputs: {valid}/{len(schema_hs)} "
              "(json.loads + property check)")

        gen = regex_h.result(0)[prompts[4].shape[0]:]
        print(f"  regex : {bytes(int(t) for t in gen).decode()!r} "
              f"({gen.shape[0]} tokens — terminal-truncated)")
        gen = free_h.result(0)[prompts[5].shape[0]:]
        print(f"  free  : {gen.tolist()} (unconstrained, "
              "token-identical to a constrain-free engine)")

        try:
            router.submit(prompts[0], constrain="(?<=x)y")
        except ConstraintError as e:
            print(f"\nrejected at submit (reason={e.reason}): {e}")

        print("\nconstrained scrape rows (replica 0):")
        eng = router._ctls[0].replica.engine
        for line in prometheus_text(eng.registry).splitlines():
            if line.startswith("serving_constrained"):
                print(f"  {line}")
    finally:
        router.close()


if __name__ == "__main__":
    main()
