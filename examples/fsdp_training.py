"""Fully-sharded data parallelism (FSDP/ZeRO) on the transformer LM.

Net-new vs the reference, whose data-parallel modes replicate the whole
model per worker (ParallelWrapper.java:603, Spark params broadcast):
here parameters, gradients, AND Adam state are sharded over the mesh's
'data' axis, and GSPMD inserts just-in-time weight all_gathers and
gradient reduce_scatters on ICI (parallel/fsdp.py).

On a TPU slice this uses all chips; elsewhere:
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python examples/fsdp_training.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


import jax
import jax.numpy as jnp

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   init_params)
from deeplearning4j_tpu.parallel import (init_fsdp_adam_state,
                                         make_fsdp_train_step,
                                         shard_params_fsdp)
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    n = len(jax.devices())
    mesh = make_mesh(MeshSpec(data=n))
    cfg = TransformerConfig(vocab_size=256, d_model=args.d_model,
                            n_heads=4, n_layers=args.layers,
                            max_len=args.seq_len)
    params = shard_params_fsdp(init_params(cfg, jax.random.PRNGKey(0)),
                               mesh)
    opt = init_fsdp_adam_state(params)
    step = make_fsdp_train_step(cfg, mesh, learning_rate=3e-3)

    wq = params["blocks"]["Wq"]
    print(f"{n} device(s); Wq global {wq.shape}, per-device shard "
          f"{wq.addressable_shards[0].data.shape} "
          f"(model+opt memory / device ~1/{n})")

    tok = jax.random.randint(jax.random.PRNGKey(1),
                             (args.batch, args.seq_len), 0, cfg.vocab_size,
                             dtype=jnp.int32)
    tgt = jnp.roll(tok, -1, axis=1)
    for i in range(args.steps):
        params, opt, loss = step(params, opt, tok, tgt)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
