"""Replicated serving fleet demo: kill a replica mid-trace, lose nothing.

Builds a 3-replica in-process fleet behind the health-aware `Router`
(deeplearning4j_tpu/serving/fleet.py), serves a mixed burst of
requests, KILLS one replica while its requests are mid-decode, and
shows:

- every request still completes (failover resumes each one from its
  committed prefix on a survivor — token-exact, as the fleet test
  suite asserts bit-for-bit);
- the fleet table (`/debugz` body) with the dead replica's supervised
  restart and recovery time;
- a rolling weight reload across the fleet with zero dropped
  requests;
- ONE stitched distributed trace for a failed-over request — both
  hops, the re-prefill, and the derived queue/decode spans on one
  aligned timeline (`router.distributed_trace`, ISSUE-13);
- the FEDERATED `/metrics` scrape: every replica's registry merged
  under `tier=`/`replica=` labels, counters summed, served from the
  router's own port (`MetricsServer(snapshot=router.federate)`).

Run: JAX_PLATFORMS=cpu python examples/fleet_serving.py
"""
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402

from deeplearning4j_tpu.models.transformer import (  # noqa: E402
    TransformerConfig, init_params)
from deeplearning4j_tpu.observability.export import (  # noqa: E402
    MetricsServer, prometheus_text)
from deeplearning4j_tpu.parallel.failure import (  # noqa: E402
    FleetFaultInjector)
from deeplearning4j_tpu.parallel.mesh import (  # noqa: E402
    MeshSpec, make_mesh)
from deeplearning4j_tpu.serving import (  # noqa: E402
    EngineConfig, FleetConfig, Router)
from deeplearning4j_tpu.util.checkpointing import (  # noqa: E402
    CheckpointManager)


def main() -> None:
    cfg = TransformerConfig(vocab_size=64, d_model=64, n_heads=4,
                            n_layers=2, max_len=96)
    mesh = make_mesh(MeshSpec(data=1, model=1))
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)

    # kill replica 1 at scheduling tick 4 — mid-decode for its slots;
    # the supervised restart brings it back with a small backoff
    injector = FleetFaultInjector(kill_at={4: 1})
    router = Router(cfg=cfg, mesh=mesh, params=params, num_replicas=3,
                    engine_config=EngineConfig(
                        max_batch_size=4, max_new_tokens=24,
                        decode_chunk=4, backoff_base_s=0.0),
                    fault_injector=injector,
                    config=FleetConfig(restart_backoff_base_s=0.05))
    server = MetricsServer(router.registry, port=0,
                           health=router.health, ready=router.ready,
                           debug=router.debugz, slo=router.slo_report,
                           snapshot=router.federate)

    print(f"fleet of 3 replicas up; router metrics at {server.url}")
    print("submitting 12 requests, then killing replica 1 "
          "mid-trace...\n")
    tenants = ["acme", "globex", "initech"]
    handles = [router.submit(
        rng.integers(0, cfg.vocab_size,
                     int(rng.integers(6, 20))).astype(np.int32),
        max_new_tokens=24, tenant=tenants[i % 3])
        for i in range(12)]
    t0 = time.perf_counter()
    router.run_pending()
    elapsed = time.perf_counter() - t0

    done = sum(h.status == "completed" for h in handles)
    st = router.stats
    print(f"completed {done}/12 in {elapsed:.2f}s — "
          f"{st['failovers']} failover(s), 0 lost")
    for h in handles:
        kinds = h.trace.kinds()
        if "failover" in kinds:
            ev = [e for e in h.trace.events if e.kind == "failover"][0]
            print(f"  request {h.rid}: replica {ev.data['from']} died "
                  f"with {ev.data['committed']} tokens committed -> "
                  f"resumed on replica {ev.data['to']}; trace "
                  f"{kinds}")

    # let the supervised restart land, then show the fleet table
    deadline = time.monotonic() + 10
    while router.stats["restarts"] < 1 and time.monotonic() < deadline:
        router.tick()
        time.sleep(0.005)
    print("\nfleet table (/debugz):")
    for row in router.debugz()["replicas"]:
        print(f"  replica {row['replica']}: {row['state']}, "
              f"capacity {row['capacity']}, "
              f"crashes {row['consec_crashes']}, "
              f"restarts {row['restarts']}")
    rec = router.registry.get("serving_fleet_recovery_seconds")
    _, total, count = rec.labels().snapshot()
    if count:
        print(f"  recovery-to-ready: {total / count * 1e3:.0f} ms")

    # rolling weight reload: one replica drains at a time, traffic
    # keeps flowing, nothing is shed
    ckpt_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "_fleet_ckpt")
    mgr = CheckpointManager(ckpt_dir, use_orbax=False)
    mgr.save_tree(params, 42)
    more = [router.submit(
        rng.integers(0, cfg.vocab_size, 8).astype(np.int32),
        max_new_tokens=8) for _ in range(6)]
    loaded = router.rolling_reload(mgr)
    router.run_pending()
    print(f"\nrolling reload: every replica now on step {loaded}; "
          f"{sum(h.status == 'completed' for h in more)}/6 requests "
          "served through the rollout, 0 shed")

    # the stitched kill-and-failover trace (ISSUE-13): both hops, the
    # failover, and the derived spans on one aligned timeline
    failed_over = [h for h in handles
                   if "failover" in h.trace.kinds()]
    if failed_over:
        dt = router.distributed_trace(failed_over[0].rid)
        print(f"\nstitched distributed trace of request {dt['rid']} "
              "(the failed-over one):")
        print("  hops: " + " -> ".join(
            f"replica {h['replica']} ({h['status']}, "
            f"{h['n_events']} events)" for h in dt["hops"]))
        t0 = dt["events"][0]["ts"]
        for s in dt["spans"]:
            print(f"  span {s['name']:<8} "
                  f"+{(s['t0'] - t0) * 1e3:8.1f} ms  "
                  f"dur {(s['t1'] - s['t0']) * 1e3:8.1f} ms")
    rep = router.slo_report()
    print(f"\nfleet SLO (stitched: queue time included): "
          f"ttft_p50 {rep['ttft_p50_ms']} ms, "
          f"e2e_p99 {rep['e2e_p99_ms']} ms, "
          f"goodput {rep['goodput']:.2f}")

    print("\nFEDERATED fleet scrape (router + every replica, one "
          "port; counters summed, gauges per-replica):")
    shown = 0
    for line in router.federated_text().splitlines():
        if line.startswith(("serving_fleet_requests",
                            "serving_requests_completed",
                            "serving_queue_depth")) \
                and "_bucket" not in line:
            print(f"  {line}")
            shown += 1
            if shown >= 12:
                break

    # the fleet-wide per-tenant bill (ISSUE-15): analytic FLOPs/bytes
    # each tenant's traffic cost, federated across every replica —
    # failovers bill their recompute to the same tenant
    cr = router.cost_report()
    print("\nfleet cost report (per-tenant analytic bill, "
          "failover recompute included):")
    for t, row in cr["tenants"].items():
        print(f"  {t:<8} {row['flops'] / 1e6:8.1f} MFLOPs  "
              f"{row['bytes'] / 1e6:8.1f} MB  "
              f"prefill {row['prefill_tokens']:>4} tok  "
              f"decode {row['decode_tokens']:>4} tok")
    print(f"  fleet total: {cr['total_flops'] / 1e6:.1f} MFLOPs")

    server.stop()
    router.close()
    import shutil
    shutil.rmtree(ckpt_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
