"""Character-level LSTM language model (the reference's
GravesLSTMCharModellingExample): train on a text corpus with truncated
BPTT, then sample.

Run: python examples/char_rnn.py [--text path] [--epochs 3]
(no --text → trains on this script's own source code)
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


import numpy as np

from deeplearning4j_tpu.models.zoo import char_rnn_lstm
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--text", default=__file__)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--hidden", type=int, default=128)
    args = ap.parse_args()

    text = open(args.text, encoding="utf-8").read()
    chars = sorted(set(text))
    vocab = {c: i for i, c in enumerate(chars)}
    ids = np.array([vocab[c] for c in text], np.int32)
    V = len(chars)
    T = args.seq_len

    n_seq = (len(ids) - 1) // T
    x_ids = ids[:n_seq * T].reshape(n_seq, T)
    y_ids = ids[1:n_seq * T + 1].reshape(n_seq, T)
    eye = np.eye(V, dtype=np.float32)
    x, y = eye[x_ids], eye[y_ids]

    net = MultiLayerNetwork(char_rnn_lstm(V, hidden=args.hidden,
                                          tbptt_length=min(50, T))).init()
    for epoch in range(args.epochs):
        for s in range(0, n_seq, args.batch):
            net.fit(x[s:s + args.batch], y[s:s + args.batch])
        print(f"epoch {epoch}: score {net.score_value:.4f}")

    # sample: stateful streaming inference (reference: rnnTimeStep)
    rng = np.random.default_rng(0)
    cur = eye[[vocab[text[0]]]][:, None, :]   # [1, 1, V]
    out_chars = [text[0]]
    for _ in range(200):
        probs = np.asarray(net.rnn_time_step(cur))[0, -1]
        probs = probs / probs.sum()
        nxt = int(rng.choice(V, p=probs))
        out_chars.append(chars[nxt])
        cur = eye[[nxt]][:, None, :]
    net.rnn_clear_previous_state()
    print("sample:", "".join(out_chars))


if __name__ == "__main__":
    main()
