"""LeNet on MNIST — the canonical training example (the reference's
LenetMnistExample flow: MnistDataSetIterator → MultiLayerNetwork.fit →
Evaluation).

Run: python examples/lenet_mnist.py [--epochs N] [--batch 128]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


from deeplearning4j_tpu.datasets.impl import MnistDataSetIterator
from deeplearning4j_tpu.models.zoo import lenet_mnist
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train.listeners import (PerformanceListener,
                                                ScoreIterationListener)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--examples", type=int, default=10000)
    args = ap.parse_args()

    net = MultiLayerNetwork(lenet_mnist(dtype="bfloat16")).init()
    net.set_listeners(ScoreIterationListener(10), PerformanceListener(10))
    train = MnistDataSetIterator(args.batch, train=True,
                                 num_examples=args.examples)
    for epoch in range(args.epochs):
        net.fit(train)
        print(f"epoch {epoch}: score {net.score_value:.4f}")
    test = MnistDataSetIterator(args.batch, train=False,
                                num_examples=args.examples // 5)
    ev = net.evaluate(test)
    print(ev.stats())


if __name__ == "__main__":
    main()
