"""Composite-parallel transformer LM training — the net-new capability
layer the reference lacks (SURVEY.md §5.7: its only long-sequence tool
is truncated BPTT; there is no attention, no tensor/pipeline/sequence/
expert parallelism).

Trains a small decoder-only LM on this script's own bytes over a device
mesh combining data, megatron tensor, pipeline (GPipe or 1F1B) and ring-attention
sequence parallelism — one shard_mapped XLA program, collectives over
ICI. On a CPU host this runs on a forced virtual mesh; on a TPU slice
the same code uses the real chips.

Run: python examples/transformer_lm.py [--dp 2 --tp 2 --pp 1 --sp 2]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _ensure_devices(n_dev: int):
    """Use the real backend when it can hold the mesh, else a virtual
    CPU mesh (the multi-chip test story, SURVEY.md §4) via the ONE
    canonical bootstrap (__graft_entry__._force_virtual_cpu_mesh —
    it also handles a backend that sitecustomize already
    initialized, which env vars alone cannot resize)."""
    import jax
    try:
        if len(jax.devices()) >= n_dev:
            return jax
    except Exception:
        pass
    from __graft_entry__ import _force_virtual_cpu_mesh
    _force_virtual_cpu_mesh(n_dev)
    return jax


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument("--schedule", default="gpipe",
                    choices=["gpipe", "1f1b"],
                    help="pipeline microbatch schedule (1f1b: O(S) "
                         "activation store instead of O(M))")
    ap.add_argument("--sp", type=int, default=2)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=64)
    args = ap.parse_args()

    n_dev = args.dp * args.tp * args.pp * args.sp
    jax = _ensure_devices(n_dev)
    import numpy as np

    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       init_params)
    from deeplearning4j_tpu.parallel.megatron import (
        init_adam_state, make_parallel_train_step, shard_params)
    from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh

    text = open(__file__, "rb").read()
    ids = np.frombuffer(text, np.uint8).astype(np.int32)
    T = args.seq_len
    n_seq = (len(ids) - 1) // T
    x = ids[:n_seq * T].reshape(n_seq, T)
    y = ids[1:n_seq * T + 1].reshape(n_seq, T)

    mesh = make_mesh(MeshSpec(data=args.dp, model=args.tp, pipe=args.pp,
                              seq=args.sp))
    cfg = TransformerConfig(vocab_size=256, d_model=64, n_heads=4,
                            n_layers=4, max_len=T)
    params = shard_params(init_params(cfg, jax.random.PRNGKey(0)), cfg,
                          mesh)
    opt = init_adam_state(params)
    step = make_parallel_train_step(cfg, mesh, learning_rate=3e-3,
                                    pipeline_schedule=args.schedule)
    if args.pp > 1:
        from deeplearning4j_tpu.parallel.megatron import \
            pipeline_bubble_fraction
        print(f"pipeline schedule {args.schedule}: bubble "
              f"{pipeline_bubble_fraction(args.schedule, args.pp, args.pp):.3f}")

    rng = np.random.default_rng(0)
    for i in range(args.steps):
        idx = rng.integers(0, n_seq, args.batch)
        params, opt, loss = step(params, opt, x[idx], y[idx])
        print(f"step {i:3d}  loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
