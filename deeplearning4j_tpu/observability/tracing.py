"""Nestable trace spans: wall-time histograms + XLA profile annotations.

`span(name)` is the one tracing primitive: it records the block's wall
time (monotonic `perf_counter`) into the `trace_span_seconds{span=...}`
histogram of a registry, under the slash-joined qualified name of the
enclosing span stack ("fit" inside "epoch" records as "epoch/fit"), and
— when the jax profiler is importable — forwards the same qualified
name to `jax.profiler.TraceAnnotation`, so host-side spans line up with
device activity in TensorBoard/xprof traces captured by
`train.listeners.ProfilerListener`.

The span stack is thread-local: concurrent threads (the serving
engine's background worker, async prefetch producers) nest
independently.
"""
from __future__ import annotations

import functools
import threading
import time
from contextlib import contextmanager
from typing import Optional

from deeplearning4j_tpu.observability.metrics import default_registry

_now = time.perf_counter
_tls = threading.local()

_SPAN_HELP = ("Wall time of observability.tracing spans, labeled by "
              "slash-qualified span name")


def current_span() -> Optional[str]:
    """Qualified name of the innermost active span on this thread."""
    stack = getattr(_tls, "stack", None)
    return "/".join(stack) if stack else None


def _trace_annotation(name: str):
    """A jax.profiler.TraceAnnotation for `name`, or None when the
    profiler isn't importable (jax-free callers, stripped builds)."""
    try:
        import jax
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return None


@contextmanager
def span(name: str, registry=None):
    """Time a block into `trace_span_seconds{span=<qualified name>}`.

    Nestable; yields the qualified name. `registry=None` publishes to
    the process default registry; pass a `MetricsRegistry` for
    isolation or `NULL_REGISTRY` to disable recording (the annotation
    still fires so XLA profiles keep their span markers).
    """
    reg = registry if registry is not None else default_registry()
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(str(name))
    qual = "/".join(stack)
    annot = _trace_annotation(qual)
    if annot is not None:
        try:
            annot.__enter__()
        except Exception:
            annot = None             # profiler backends can refuse
    t0 = _now()
    try:
        yield qual
    finally:
        dt = _now() - t0
        if annot is not None:
            try:
                annot.__exit__(None, None, None)
            except Exception:
                pass
        stack.pop()
        reg.histogram("trace_span_seconds", _SPAN_HELP,
                      labelnames=("span",)).labels(qual).observe(dt)


def traced(name: Optional[str] = None, registry=None):
    """Decorator form of `span` (span name defaults to the function's
    qualified name)."""
    def deco(fn):
        span_name = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(span_name, registry=registry):
                return fn(*args, **kwargs)
        return wrapper
    return deco
