"""Metrics federation: N per-replica registries, ONE fleet scrape.

Every engine replica owns a private `MetricsRegistry` (per-engine
counts stay exact), which made "how is the fleet doing" a question
answered by scraping N ports by hand. `merge_snapshots` (ISSUE-13)
folds per-replica JSON snapshots (`export.json_snapshot` for
in-process replicas, the worker's ``/metrics.json`` body for
subprocess ones — the schema is identical by construction) into one
snapshot a router serves from its own ``/metrics``:

- **counters** are SUMMED across replicas under an added ``tier``
  label — the federated ``serving_requests_completed_total{tier=
  "decode"}`` equals the sum of the decode replicas' counters, row for
  row, which is what a fleet-level alert should fire on;
- **histograms** merge bucket-exact: identical bucket edges (same
  code, same buckets) sum cumulative-count-wise — cumulative sums are
  linear — plus summed ``_sum``/``_count``; a replica exposing
  DIFFERENT edges for the same family is skipped with a warning
  rather than silently mis-merged;
- **gauges** stay PER-REPLICA under added ``tier`` + ``replica``
  labels: summing slot-occupancy fractions across replicas is
  meaningless, and the per-replica values are exactly what capacity
  debugging needs.

The label conventions (``tier=`` on everything, ``replica=`` on
gauges only) keep the federated exposition lint-clean and
duplicate-free: merged counter/histogram rows are unique by
(labels + tier), gauge rows by (labels + tier + replica). A kind
mismatch between parts (version-skewed replica) keeps the first
kind and skips the offender — federation must degrade, never take the
fleet scrape down. `check_cardinality` is the guard that fails a
scrape whose label combinations exceed a sane budget before a
downstream Prometheus does. Stdlib-only.
"""
from __future__ import annotations

import logging
from typing import Dict, Iterable, List, Tuple

log = logging.getLogger("deeplearning4j_tpu")

#: default per-family series budget for `check_cardinality`: generous
#: for a fleet of tens of replicas x a handful of label values, tight
#: enough that an unbounded label (request ids, raw prompts) trips it
DEFAULT_SERIES_BUDGET = 256


def merge_snapshots(parts: Iterable[Tuple[dict, dict]],
                    tier_label: str = "tier",
                    replica_label: str = "replica"
                    ) -> Dict[str, dict]:
    """Merge ``(meta, snapshot)`` parts into one federated snapshot.

    ``meta`` carries the part's identity labels, e.g.
    ``{"tier": "decode", "replica": 3}``; ``snapshot`` is the
    `json_snapshot` schema (``{name: {kind, help, samples}}``).
    Returns the same schema, ready for
    `export.snapshot_prometheus_text` or a ``/metrics.json`` body.
    """
    out: Dict[str, dict] = {}
    index: Dict[str, dict] = {}
    for meta, snap in parts:
        tier = str(meta.get(tier_label, "fleet"))
        rep = str(meta.get(replica_label, ""))
        for name, fam in (snap or {}).items():
            kind = fam.get("kind", "untyped")
            dst = out.get(name)
            if dst is None:
                dst = out[name] = {"kind": kind,
                                   "help": fam.get("help", ""),
                                   "samples": []}
                index[name] = {}
            elif dst["kind"] != kind:
                log.warning(
                    "federation: %s is %s here but %s from "
                    "tier=%s replica=%s — skipping the mismatched "
                    "part", name, dst["kind"], kind, tier, rep)
                continue
            idx = index[name]
            for s in fam.get("samples", ()):
                labels = dict(s.get("labels") or {})
                # never clobber a label the series already carries
                # (the router's own serving_tier_* gauges are tier-
                # labeled at the source): the source's value is the
                # truthful one
                labels.setdefault(tier_label, tier)
                if kind == "gauge":
                    labels.setdefault(replica_label, rep)
                    dst["samples"].append(
                        {"labels": labels,
                         "value": float(s.get("value", 0.0))})
                    continue
                key = tuple(sorted(labels.items()))
                cur = idx.get(key)
                if kind == "histogram":
                    bk = dict(s.get("buckets") or {})
                    if cur is None:
                        cur = {"labels": labels, "buckets": bk,
                               "sum": float(s.get("sum", 0.0)),
                               "count": int(s.get("count", 0))}
                        idx[key] = cur
                        dst["samples"].append(cur)
                    elif list(cur["buckets"]) != list(bk):
                        log.warning(
                            "federation: %s bucket edges differ at "
                            "tier=%s replica=%s — skipping that "
                            "replica's cell", name, tier, rep)
                    else:
                        for edge, c in bk.items():
                            cur["buckets"][edge] += c
                        cur["sum"] += float(s.get("sum", 0.0))
                        cur["count"] += int(s.get("count", 0))
                else:                        # counter (and untyped)
                    if cur is None:
                        cur = {"labels": labels, "value": 0.0}
                        idx[key] = cur
                        dst["samples"].append(cur)
                    cur["value"] += float(s.get("value", 0.0))
    return out


def series_cardinality(snap: Dict[str, dict]) -> Dict[str, int]:
    """Label-combination count per family of a snapshot."""
    return {name: len(fam.get("samples", ()))
            for name, fam in snap.items()}


def check_cardinality(snap: Dict[str, dict],
                      budget: int = DEFAULT_SERIES_BUDGET
                      ) -> List[str]:
    """Raise ``ValueError`` when any family's series count exceeds
    ``budget`` — the fleet-scrape guard against an unbounded label
    sneaking into a hot family. Returns the checked family names."""
    offenders = {n: c for n, c in series_cardinality(snap).items()
                 if c > budget}
    if offenders:
        raise ValueError(
            "federated series over the cardinality budget "
            f"({budget}): " + ", ".join(
                f"{n}={c}" for n, c in sorted(offenders.items())))
    return sorted(snap)
