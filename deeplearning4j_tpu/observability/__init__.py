"""Unified observability: metrics registry + trace spans + exposition.

Every layer of the system previously self-reported in a different
dialect — `InferenceEngine.health()`'s ad-hoc dict, train listeners
printing to the log, `ui/stats.py` and `scaleout/stats.py` keeping
private timing state — and nothing was scrapeable. This package is the
one substrate they all publish into:

- `metrics` — thread-safe `MetricsRegistry` of labeled
  `Counter`/`Gauge`/`Histogram` (fixed buckets, per-cell locks,
  monotonic `perf_counter` timers); a process default registry plus
  injectable instances; `NULL_REGISTRY` to disable by injection.
- `tracing` — nestable `span(name)` context managers recording
  wall-time histograms and forwarding to
  `jax.profiler.TraceAnnotation` so spans land in XLA profiles.
- `export` — Prometheus text exposition + JSON snapshot, served by the
  stdlib `MetricsServer` (`/metrics`, `/healthz`, `/readyz` with
  pluggable health callables, plus `/debugz`, `/slo`,
  `/timeline.json` when the serving introspection callables are
  wired) and mountable on the training dashboard
  (`ui.server.UIServer.attach_metrics`).
- `events` — the per-request flight recorder (ISSUE-6): a bounded
  thread-safe ring of typed lifecycle events plus `RequestTrace`
  (exposed as `RequestHandle.trace`); `NULL_RECORDER` disables by
  injection.
- `slo` — `SLOTracker`: TTFT / TPOT / e2e / queue-age histograms and
  goodput derived from the traces, with a windowed `report()`.
- `timeline` — Chrome/Perfetto `trace_event` JSON export of the
  recorder: one lane per serving slot plus a queue lane.
- `stitch` — distributed-trace stitching (ISSUE-13): merge a fleet
  router's trace with the per-hop replica traces (clock-offset
  aligned) into one `StitchedTrace` of events + queue/prefill/
  decode/handoff spans, plus the fleet-wide Perfetto export with one
  process lane group per replica per tier.
- `federation` — metrics federation (ISSUE-13): merge per-replica
  registry snapshots into ONE fleet scrape (counters summed and
  histograms bucket-merged under `tier=`, gauges kept per-replica
  under `tier=`/`replica=`), with a series-cardinality guard.
- `profiling` — continuous profiling & cost attribution (ISSUE-15):
  `EngineProfiler` (per-program XLA cost table, per-tick device-time
  attribution, live `serving_mfu`, roofline classification),
  `TenantMeter` (per-tenant analytic FLOP/byte metering with a
  top-N + "other" cardinality bound), and `ProfileCapture`
  (single-flight `/profilez?seconds=N` jax.profiler capture).

Publishers: `serving.InferenceEngine` (queue/batch/shed/quarantine/
retry/breaker/decode-latency; `health()` is registry-backed),
`train.listeners.{PerformanceListener,ScoreIterationListener}`,
`scaleout.stats.SparkTrainingStats` + `scaleout.parallel_trainer`
spans, and `datasets.iterators.AsyncDataSetIterator` prefetch gauges.
Lifecycle, naming conventions and a scrape walkthrough:
docs/observability.md.
"""
from deeplearning4j_tpu.observability.metrics import (  # noqa: F401
    DECODE_LATENCY_BUCKETS, DEFAULT_BUCKETS, Counter, Gauge, Histogram,
    MetricsRegistry, NULL_REGISTRY, NullRegistry, default_registry)
from deeplearning4j_tpu.observability.tracing import (  # noqa: F401
    current_span, span, traced)
from deeplearning4j_tpu.observability.export import (  # noqa: F401
    CONTENT_TYPE_LATEST, MetricsServer, json_snapshot, probe_response,
    prometheus_text, snapshot_prometheus_text)
from deeplearning4j_tpu.observability.events import (  # noqa: F401
    EVENT_KINDS, Event, FlightRecorder, NULL_RECORDER, NULL_TRACE,
    NullRecorder, RequestTrace, TERMINAL_KINDS)
from deeplearning4j_tpu.observability.slo import (  # noqa: F401
    NULL_SLO, SLOTracker, TPOT_BUCKETS)
from deeplearning4j_tpu.observability.timeline import (  # noqa: F401
    timeline_json, trace_events)
from deeplearning4j_tpu.observability.stitch import (  # noqa: F401
    SPAN_NAMES, StitchedTrace, fleet_timeline_json, router_lane_events,
    stitch)
from deeplearning4j_tpu.observability.federation import (  # noqa: F401
    DEFAULT_SERIES_BUDGET, check_cardinality, merge_snapshots,
    series_cardinality)
from deeplearning4j_tpu.observability.profiling import (  # noqa: F401
    DEFAULT_TENANT, EngineProfiler, NULL_PROFILER, NullProfiler,
    OTHER_TENANT, ProfileCapture, TenantMeter, cost_from_compiled,
    roofline)
