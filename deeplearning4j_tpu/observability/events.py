"""Per-request flight recorder: typed lifecycle events in a ring buffer.

The metrics layer (observability/metrics.py) answers *how much* —
counts, rates, latency distributions — but when ONE request is slow or
shed, aggregates explain nothing. The flight recorder is the other
half (ISSUE-6): every request carries a `RequestTrace` of typed,
monotonically-timestamped lifecycle events
(``submit → queued → admitted{slot,bucket} → prefill_done →
decode_chunk{tokens}* → finished`` on the happy path; ``retry``,
``preempted``, ``quarantined``, ``shed{reason}`` on the others), and a
`FlightRecorder` keeps the last N events of the whole engine in a
bounded thread-safe ring — the raw material for `/debugz`, the SLO
layer (observability/slo.py), and the Perfetto timeline export
(observability/timeline.py).

Design constraints, mirroring the metrics substrate:

- **Near-zero hot-path cost.** Recording one event is a perf_counter
  read, a tuple construction, and two GIL-atomic appends (~1 µs); the
  engine adds a handful per request per chunk against
  milliseconds-to-seconds of compiled decode. `NULL_RECORDER` /
  `NULL_TRACE` mirror `NULL_REGISTRY`: disabling is injection, not
  if-guards — the "off" arm of the `engine_slo` benchmark.
- **Bounded memory.** The global ring is a `deque(maxlen=capacity)`;
  per-request traces are bounded by the request's own lifetime
  (≤ max_new_tokens/chunk decode events) and die with the handle.
- **Monotonic timestamps.** `time.perf_counter`, never `time.time` —
  event deltas survive wall-clock steps; exports re-base to t=0.
- **Typed kinds.** An unknown kind raises: two subsystems silently
  inventing dialects is the drift this catches (the same reason
  `MetricsRegistry` hard-errors on kind mismatch).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Iterable, List, NamedTuple, Optional, Tuple

_now = time.perf_counter

#: The request-lifecycle event vocabulary (docs/observability.md has
#: the per-kind payload schema). Engine code MUST use these exact
#: names; `RequestTrace.add` rejects anything else.
EVENT_KINDS = frozenset({
    "submit",        # handle created, admission checks passed
    "queued",        # appended to the bounded admission queue
    "admitted",      # seated: {slot, bucket} (continuous) /
    #                  {batch_size} (batch mode) / {scratch: True}
    #                  (solo isolation re-run); chunked-prefill
    #                  engines add {prefill_chunk}
    "prefill_done",  # prompt prefilled, first token committed {tokens}
    "decode_chunk",  # one decode chunk committed {tokens, slot}
    #                  (speculative engines add {drafted, accepted};
    #                  chunked-prefill engines add {prefill_chunk} —
    #                  prompt tokens co-scheduled in the same tick)
    "draft_rejected",  # a speculative round's drafts were ALL
    #                  rejected by verification {step, drafted,
    #                  poisoned} — the forensic marker for injected
    #                  draft poisoning and for adaptive-K backoff
    "preempted",     # evicted from its slot {reason: isolation|
    #                  reload|priority} — priority preemptions add
    #                  {by: preemptor rid, slot} (ISSUE-16)
    "qos",           # QoS control-plane action (rid 0, fleet-wide):
    #                  admission rejection {action: reject, tenant,
    #                  reason: rate|concurrency} or an overload-
    #                  controller transition {action: degrade|restore,
    #                  level, step: spec_off|chunk_shrink|shed_low|
    #                  none} — the degradation ladder's audit trail
    #                  (ISSUE-16)
    "dispatched",    # fleet router: handed to a replica {replica,
    #                  hedge} — the router-hop span opener (ISSUE-9)
    "failover",      # fleet router: re-dispatched onto a survivor
    #                  after a replica loss {from, to, committed}
    "hedge",         # fleet router: hedged pair resolved {winner,
    #                  loser, outcome: primary_won|hedge_won}
    "handoff",       # tiered router: committed prefill KV moved from
    #                  a prefill-tier replica toward a decode-tier
    #                  one {from, tokens, outcome: ok|fallback|failed}
    #                  — outcome "fallback"/"failed" means the decode
    #                  dispatch re-prefills instead (ISSUE-11)
    "autoscale",     # tiered router (rid 0, fleet-wide): a tier's
    #                  replica count changed {tier, direction: up|down,
    #                  replicas} — the occupancy-driven policy's
    #                  audit trail (ISSUE-11)
    "kv_migration",  # fleet router: a cached prefix chain moved
    #                  across replicas ahead of a dispatch {from, to,
    #                  tokens, bytes, outcome: ok|stale|failed} —
    #                  "stale" means the advertised chain was evicted
    #                  before export, "failed" an export error; both
    #                  degrade to a normal prefill (ISSUE-14).
    #                  Proactive pushes at autoscale-up add
    #                  {proactive: True} (ISSUE-17)
    "kvwire",        # KV wire transport (ISSUE-17): one kvwire frame
    #                  crossed (or failed to cross) a process boundary
    #                  {direction: export|adopt|seed|control, outcome:
    #                  ok|magic|version|crc|truncated|type|error,
    #                  bytes, seconds} — every failure outcome
    #                  degrades to the re-prefill path, never a lost
    #                  request
    "elastic",       # elastic training membership/sync transition
    #                  (rid 0, fleet-wide; ISSUE-18): {action: join|
    #                  leave|kill_detected|resize|replay|loose_enter|
    #                  resync|evict, worker, step, ...} — the elastic
    #                  coordinator's audit trail (resize adds
    #                  {workers, reason}; loose_enter/resync add
    #                  {pending}; replay adds {from_step, to_step})
    "constraint",    # grammar-constrained decoding (ISSUE-20): the
    #                  request's DFA reached a terminal accepting
    #                  state {terminal: True, state} — the EOS-forcing
    #                  audit mark; only constrained requests ever
    #                  record it, so constrain-off traces are
    #                  byte-unchanged
    "retry",         # a compiled call containing it failed and is
    #                  being retried {step, attempt, prefill}
    "quarantined",   # terminal: failed persistently after solo retries
    "finished",      # terminal: completed {tokens, partial}
    "shed",          # terminal: rejected/abandoned {reason}
})

#: Terminal kinds — exactly one of these ends a complete trace.
TERMINAL_KINDS = frozenset({"finished", "shed", "quarantined"})


class Event(NamedTuple):
    """One lifecycle event: monotonic timestamp, kind, request id, and
    a small JSON-serializable payload dict."""
    ts: float
    kind: str
    rid: int
    data: dict

    def as_dict(self) -> dict:
        return {"ts": self.ts, "kind": self.kind, "rid": self.rid,
                **self.data}


class RequestTrace:
    """The per-request event list, exposed as `RequestHandle.trace`.

    `add()` stamps the event once and appends it to BOTH this trace
    and the owning recorder's ring, so the per-request view and the
    engine-wide view can never disagree.

    ``ctx`` is the distributed-tracing hop context (ISSUE-13): a small
    dict (``{"fleet_rid": ..., "hop": ..., "tier": ...}``) stamped by
    a fleet router at dispatch and merged into EVERY event this trace
    records, so a replica's local ring events stay attributable to the
    fleet request that caused them — the raw material
    `observability/stitch.py` reassembles into one distributed trace.
    Explicit per-event data wins over ctx keys on collision."""

    __slots__ = ("rid", "ctx", "_recorder", "_events", "_lock")

    def __init__(self, rid: int, recorder: "FlightRecorder" = None,
                 ctx: Optional[dict] = None):
        self.rid = int(rid)
        self.ctx = dict(ctx) if ctx else None
        self._recorder = recorder
        self._events: List[Event] = []
        self._lock = threading.Lock()

    def add(self, kind: str, **data) -> Event:
        if kind not in EVENT_KINDS:
            raise ValueError(f"unknown event kind {kind!r}; "
                             f"valid: {sorted(EVENT_KINDS)}")
        if self.ctx:
            data = {**self.ctx, **data}
        rec = self._recorder
        ev = Event(rec.now() if rec is not None else _now(),
                   kind, self.rid, data)
        with self._lock:
            self._events.append(ev)
        if rec is not None:
            rec._push(ev)
        return ev

    @property
    def events(self) -> Tuple[Event, ...]:
        with self._lock:
            return tuple(self._events)

    def kinds(self) -> List[str]:
        return [e.kind for e in self.events]

    def first_ts(self, kind: str) -> Optional[float]:
        for e in self.events:
            if e.kind == kind:
                return e.ts
        return None

    def last_ts(self, kind: str) -> Optional[float]:
        ts = None
        for e in self.events:
            if e.kind == kind:
                ts = e.ts
        return ts

    def complete(self) -> bool:
        """True when the trace reached a terminal event."""
        evs = self.events
        return bool(evs) and evs[-1].kind in TERMINAL_KINDS

    def as_dicts(self) -> List[dict]:
        return [e.as_dict() for e in self.events]

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


class FlightRecorder:
    """Thread-safe bounded ring of lifecycle events plus the
    `RequestTrace` factory. One recorder per engine (the engine's
    `recorder=` kwarg), or share one across engines the way a
    registry is shared."""

    enabled = True

    def __init__(self, capacity: int = 4096,
                 clock: Callable[[], float] = _now):
        self.capacity = int(capacity)
        # long-soak fleet stitching needs DEEPER rings (ISSUE-13
        # satellite: EngineConfig.recorder_capacity / the Router's
        # recorder_capacity kwarg size this); a non-positive ring
        # cannot hold a single lifecycle and is always a config bug
        if self.capacity < 1:
            raise ValueError(
                f"recorder capacity must be >= 1, got {capacity}")
        self._clock = clock
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()

    def now(self) -> float:
        return self._clock()

    def start_trace(self, rid: int,
                    ctx: Optional[dict] = None) -> RequestTrace:
        return RequestTrace(rid, self, ctx=ctx)

    def record(self, kind: str, rid: int = 0, **data) -> Event:
        """Ring-only event (no per-request trace) — engine-scope
        happenings that belong to no single request."""
        if kind not in EVENT_KINDS:
            raise ValueError(f"unknown event kind {kind!r}")
        ev = Event(self.now(), kind, int(rid), data)
        self._push(ev)
        return ev

    def _push(self, ev: Event) -> None:
        with self._lock:
            self._ring.append(ev)

    def recent(self, n: Optional[int] = None,
               kind: Optional[str] = None,
               rid: Optional[int] = None) -> List[Event]:
        """The last ``n`` ring events (oldest first), optionally
        filtered by kind and/or request id."""
        with self._lock:
            evs: Iterable[Event] = tuple(self._ring)
        if kind is not None:
            evs = [e for e in evs if e.kind == kind]
        if rid is not None:
            evs = [e for e in evs if e.rid == rid]
        evs = list(evs)
        return evs[-n:] if n is not None else evs

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


_NULL_EVENT = Event(0.0, "shed", 0, {})


class NullTrace:
    """No-op trace: `add` costs one call and returns a constant."""

    __slots__ = ()
    rid = 0
    ctx = None
    events: Tuple[Event, ...] = ()

    def add(self, kind: str, **data) -> Event:
        return _NULL_EVENT

    def kinds(self) -> list:
        return []

    def first_ts(self, kind: str) -> None:
        return None

    def last_ts(self, kind: str) -> None:
        return None

    def complete(self) -> bool:
        return False

    def as_dicts(self) -> list:
        return []

    def __len__(self) -> int:
        return 0


NULL_TRACE = NullTrace()


class NullRecorder:
    """Recorder whose traces record nothing — the flight recorder can
    be disabled by injection (mirroring `NULL_REGISTRY`) instead of by
    `if` guards at every engine call site."""

    enabled = False
    capacity = 0

    def now(self) -> float:
        return _now()

    def start_trace(self, rid: int,
                    ctx: Optional[dict] = None) -> NullTrace:
        return NULL_TRACE

    def record(self, kind: str, rid: int = 0, **data) -> Event:
        return _NULL_EVENT

    def recent(self, n=None, kind=None, rid=None) -> list:
        return []

    def clear(self) -> None:
        pass

    def __len__(self) -> int:
        return 0


NULL_RECORDER = NullRecorder()
