"""Serving SLO layer: TTFT / TPOT / e2e / queue-age / goodput.

Production LLM serving is judged on time-to-first-token and
inter-token latency under load (PAPERS.md: Orca-style continuous
batching), not on aggregate tokens/sec — a pool that streams 10k tok/s
while one request waits 30 s for its first token is failing its SLO.
`SLOTracker` derives the per-request numbers from the flight
recorder's traces (observability/events.py) and publishes them twice:

- as registry histograms with serving-appropriate buckets, so an
  external scraper gets the full distributions
  (``serving_ttft_seconds``, ``serving_tpot_seconds``,
  ``serving_e2e_seconds``, ``serving_queue_age_seconds``,
  ``serving_slo_requests_total{outcome}``, ``serving_goodput_ratio``);
- as a windowed `report()` dict (p50/p95/p99 over the last N terminal
  requests) — the `/slo` endpoint's body and the `engine_slo`
  benchmark's output.

Definitions (all from monotonic trace timestamps):

- **TTFT**: submit → first generated token committed (continuous mode:
  the admission prefill's sampled token; batch mode: the first decode
  chunk — both modes record it, so batch-mode TTFT is honest too).
- **TPOT** (inter-token latency): (t_last_token − t_first_token) /
  (n_generated − 1); undefined for single-token requests.
- **e2e**: submit → terminal event (finished/shed/quarantined).
- **queue-age**: wait before (re-)admission — last ``admitted`` minus
  the later of ``submit`` and the last ``preempted`` (a reload-
  preempted request re-queues; its second wait is a real wait).
- **goodput**: fraction of terminal requests that FINISHED within
  their deadline (no deadline = within). ``late`` = completed partial
  past deadline; ``shed``/``quarantined`` are never good.

Stdlib-only, like the rest of observability/. `NULL_SLO` mirrors
`NULL_REGISTRY`/`NULL_RECORDER`: disable by injection.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

from deeplearning4j_tpu.observability.events import RequestTrace
from deeplearning4j_tpu.observability.metrics import (
    DECODE_LATENCY_BUCKETS, default_registry)

#: Inter-token latency buckets (seconds): a decode chunk amortizes one
#: compiled call over `chunk` tokens, so per-token cadence sits well
#: below DECODE_LATENCY_BUCKETS' compiled-call range — these reach
#: down to 0.1 ms while keeping a multi-second overload tail.
TPOT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)

_OUTCOMES = ("ok", "late", "shed", "quarantined")


def _pct(sorted_vals: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1,
            int(round(q / 100.0 * (len(sorted_vals) - 1))))
    return sorted_vals[i]


class SLOTracker:
    """Per-request SLO accounting over flight-recorder traces.

    The engine calls `admitted(trace)` when a request is seated,
    `first_token(trace, ts)` when its first generated token commits,
    and `finished(trace)` at the terminal transition; everything else
    (timestamps, token counts, outcome) is derived from the trace so
    the tracker stays decoupled from engine internals.

    ``prefix`` names the metric families: the default ``"serving"``
    keeps the round-11 engine series; a fleet router passes
    ``"serving_fleet"`` so its STITCHED-trace rollup (ISSUE-13 — TTFT
    and e2e that include router queue time and cross-tier handoff
    time) publishes as ``serving_fleet_ttft_seconds`` etc. without
    colliding with the per-replica engine series it federates."""

    def __init__(self, registry=None, window: int = 512,
                 prefix: str = "serving"):
        reg = registry if registry is not None else default_registry()
        self._ttft = reg.histogram(
            f"{prefix}_ttft_seconds",
            "Submit to first generated token (time-to-first-token)",
            buckets=DECODE_LATENCY_BUCKETS)
        self._tpot = reg.histogram(
            f"{prefix}_tpot_seconds",
            "Inter-token latency: decode span / (tokens - 1)",
            buckets=TPOT_BUCKETS)
        self._e2e = reg.histogram(
            f"{prefix}_e2e_seconds",
            "Submit to terminal event (end-to-end request latency)",
            buckets=DECODE_LATENCY_BUCKETS)
        self._qage = reg.histogram(
            f"{prefix}_queue_age_seconds",
            "Wait between enqueue (submit or preemption) and admission"
            if prefix == "serving" else
            "Router-queue wait between (re-)enqueue and dispatch",
            buckets=DECODE_LATENCY_BUCKETS)
        self._outcomes = reg.counter(
            f"{prefix}_slo_requests",
            "Terminal requests by SLO outcome", labelnames=("outcome",))
        self._outcome_cells = {o: self._outcomes.labels(o)
                               for o in _OUTCOMES}
        reg.gauge(
            f"{prefix}_goodput_ratio",
            "Fraction of windowed terminal requests finished within "
            "deadline (1.0 when the window is empty)"
        ).set_function(self.goodput)
        self._lock = threading.Lock()
        self._window: deque = deque(maxlen=int(window))

    # -- engine-side hooks ---------------------------------------------
    def admitted(self, trace: RequestTrace) -> None:
        t_adm = trace.last_ts("admitted")
        if t_adm is None:
            return
        t_from = trace.first_ts("submit")
        t_pre = trace.last_ts("preempted")
        if t_pre is not None and (t_from is None or t_pre > t_from):
            t_from = t_pre
        if t_from is not None:
            self._qage.observe(max(0.0, t_adm - t_from))

    def first_token(self, trace: RequestTrace, ts: float) -> None:
        t_sub = trace.first_ts("submit")
        if t_sub is not None:
            self._ttft.observe(max(0.0, ts - t_sub))

    def finished(self, trace: RequestTrace) -> None:
        """Terminal accounting; expects the terminal event (finished /
        shed / quarantined) to already be the trace's last event."""
        evs = trace.events
        if not evs:
            return
        term = evs[-1]
        t_sub = trace.first_ts("submit")
        rec = {"rid": trace.rid, "outcome": self._outcome(term),
               "e2e": None, "ttft": None, "tpot": None,
               "queue_age": None}
        if t_sub is not None:
            rec["e2e"] = max(0.0, term.ts - t_sub)
            self._e2e.observe(rec["e2e"])
        tok_evs = [e for e in evs
                   if e.kind in ("prefill_done", "decode_chunk")
                   and e.data.get("tokens")]
        if tok_evs and t_sub is not None:
            rec["ttft"] = max(0.0, tok_evs[0].ts - t_sub)
        n_tok = sum(int(e.data["tokens"]) for e in tok_evs)
        if n_tok > 1:
            span = tok_evs[-1].ts - tok_evs[0].ts
            rec["tpot"] = max(0.0, span / (n_tok - 1))
            self._tpot.observe(rec["tpot"])
        t_adm = trace.first_ts("admitted")
        if t_adm is not None and t_sub is not None:
            rec["queue_age"] = max(0.0, t_adm - t_sub)
        self._outcome_cells[rec["outcome"]].inc()
        with self._lock:
            self._window.append(rec)

    @staticmethod
    def _outcome(term) -> str:
        if term.kind == "finished":
            return "late" if term.data.get("partial") else "ok"
        if term.kind == "shed":
            return "shed"
        return "quarantined"

    # -- read side -----------------------------------------------------
    def goodput(self) -> float:
        with self._lock:
            recs = list(self._window)
        if not recs:
            return 1.0
        return sum(r["outcome"] == "ok" for r in recs) / len(recs)

    def report(self) -> Dict[str, object]:
        """Windowed SLO report over the last ``window`` terminal
        requests: flat p50/p95/p99 milliseconds per dimension, goodput,
        and outcome counts — the `/slo` endpoint body."""
        with self._lock:
            recs = list(self._window)
        out: Dict[str, object] = {
            "window": len(recs),
            "goodput": (sum(r["outcome"] == "ok" for r in recs)
                        / len(recs)) if recs else 1.0,
            "outcomes": {o: sum(r["outcome"] == o for r in recs)
                         for o in _OUTCOMES},
        }
        for dim in ("ttft", "tpot", "e2e", "queue_age"):
            vals = sorted(r[dim] for r in recs if r[dim] is not None)
            for q in (50, 95, 99):
                v = _pct(vals, q)
                out[f"{dim}_p{q}_ms"] = (round(v * 1e3, 3)
                                         if v is not None else None)
        return out


class NullSLOTracker:
    """No-op SLO tracker (injection-disable, mirroring NULL_REGISTRY)."""

    def admitted(self, trace) -> None:
        pass

    def first_token(self, trace, ts) -> None:
        pass

    def finished(self, trace) -> None:
        pass

    def goodput(self) -> float:
        return 1.0

    def report(self) -> dict:
        return {}


NULL_SLO = NullSLOTracker()
