"""Chrome/Perfetto ``trace_event`` export of the flight recorder.

`timeline_json(recorder)` turns the engine's recent lifecycle events
into the Trace Event JSON any chrome://tracing / https://ui.perfetto.dev
build renders: one lane (``tid``) per continuous-batching slot plus a
**queue lane**, so a slot-pool schedule gap — a slot idle while the
queue is non-empty, a long request pinning a lane, a preemption storm
after a weight reload — is *visible* instead of inferred from
histograms. This is the `/timeline.json` endpoint's body.

Mapping (the JSON object format: ``{"traceEvents": [...]}``):

- lane ``queue``: one complete event (``ph:"X"``) per wait — submit →
  admitted, and preempted → re-admitted (reload requeues).
- lane ``slot <i>``: one complete event per residency — admitted on
  slot *i* → the request's next preempted/terminal event; decode
  chunks and prefill completions ride as instant events (``ph:"i"``)
  with their token counts in ``args``; retries likewise.
- lanes ``scratch`` / ``pool``: solo-isolation re-runs and batch-mode
  residencies (batch mode has no slots — the whole batch is one lane).
- ``ph:"M"`` metadata names every lane (``thread_name``) and orders
  them (``thread_sort_index``: queue first, then slots).

Timestamps are the recorder's monotonic perf_counter values re-based
to the first exported event and scaled to microseconds (the
trace_event unit). Stdlib-only.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Union

from deeplearning4j_tpu.observability.events import (Event,
                                                     FlightRecorder,
                                                     TERMINAL_KINDS)

_PID = 0
_QUEUE_TID = 0


def _lane_of(ev: Event, num_slots: int) -> int:
    """tid for the residency an ``admitted`` event starts."""
    if ev.data.get("scratch"):
        return num_slots + 1
    slot = ev.data.get("slot")
    if slot is None:                       # batch mode: one shared lane
        return num_slots + 2
    return int(slot) + 1


def trace_events(events: Iterable[Event],
                 num_slots: Optional[int] = None,
                 pid: int = _PID,
                 process_name: str = "serving engine",
                 base: Optional[float] = None) -> List[dict]:
    """Render lifecycle events as a ``traceEvents`` list. ``events``
    must be in chronological order (the recorder's ring is).

    ``pid``/``process_name``/``base`` exist for the FLEET timeline
    (ISSUE-13, observability/stitch.py): each replica renders as its
    own process lane group, and every group re-bases to one shared
    fleet-wide t=0 so the lanes align in Perfetto."""
    evs = [e for e in events]
    out: List[dict] = []
    if num_slots is None:
        num_slots = 1 + max(
            [int(e.data["slot"]) for e in evs
             if e.data.get("slot") is not None and not e.data.get(
                 "scratch")] or [-1])
    if base is None:
        base = evs[0].ts if evs else 0.0
    us = lambda t: round((t - base) * 1e6, 3)      # noqa: E731

    lanes: Dict[int, str] = {_QUEUE_TID: "queue"}
    for s in range(num_slots):
        lanes[s + 1] = f"slot {s}"

    # per-request open spans: rid -> (start_ts, tid, phase)
    open_span: Dict[int, tuple] = {}

    def close(rid: int, end_ts: float, status: str) -> None:
        start_ts, tid, phase = open_span.pop(rid)
        out.append({"name": f"r{rid} {phase}", "ph": "X", "pid": pid,
                    "tid": tid, "ts": us(start_ts),
                    "dur": max(0.0, round((end_ts - start_ts) * 1e6,
                                          3)),
                    "args": {"rid": rid, "status": status}})

    for ev in evs:
        rid = ev.rid
        if ev.kind == "submit":
            open_span[rid] = (ev.ts, _QUEUE_TID, "wait")
        elif ev.kind == "admitted":
            if rid in open_span:
                close(rid, ev.ts, "admitted")
            tid = _lane_of(ev, num_slots)
            if tid == num_slots + 1:
                lanes.setdefault(tid, "scratch")
            elif tid == num_slots + 2:
                lanes.setdefault(tid, "pool")
            open_span[rid] = (ev.ts, tid, "decode")
        elif ev.kind == "preempted":
            if rid in open_span:
                close(rid, ev.ts, "preempted")
            open_span[rid] = (ev.ts, _QUEUE_TID, "wait")
        elif ev.kind in TERMINAL_KINDS:
            if rid in open_span:
                close(rid, ev.ts, ev.kind)
        elif ev.kind in ("prefill_done", "decode_chunk", "retry",
                         "queued"):
            tid = (open_span[rid][1] if rid in open_span
                   else _QUEUE_TID)
            out.append({"name": f"{ev.kind} r{rid}", "ph": "i",
                        "pid": pid, "tid": tid, "ts": us(ev.ts),
                        "s": "t", "args": {"rid": rid, **ev.data}})

    # still-running requests: close their span at the last known time
    if evs:
        for rid in list(open_span):
            close(rid, evs[-1].ts, "running")

    meta: List[dict] = [{"name": "process_name", "ph": "M",
                         "pid": pid, "tid": 0,
                         "args": {"name": process_name}},
                        {"name": "process_sort_index", "ph": "M",
                         "pid": pid, "tid": 0,
                         "args": {"sort_index": pid}}]
    for tid in sorted(lanes):
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": lanes[tid]}})
        meta.append({"name": "thread_sort_index", "ph": "M",
                     "pid": pid, "tid": tid,
                     "args": {"sort_index": tid}})
    return meta + out


def timeline_json(source: Union[FlightRecorder, Iterable[Event]],
                  num_slots: Optional[int] = None,
                  n: Optional[int] = None) -> dict:
    """The Trace Event JSON *object* form Perfetto/chrome://tracing
    load directly. ``source`` is a FlightRecorder (its last ``n`` ring
    events) or any chronological Event iterable."""
    events = (source.recent(n) if hasattr(source, "recent")
              else list(source))
    return {"traceEvents": trace_events(events, num_slots=num_slots),
            "displayTimeUnit": "ms"}
