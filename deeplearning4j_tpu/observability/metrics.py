"""Thread-safe labeled metrics: Counter / Gauge / Histogram + registry.

The one instrumentation substrate every subsystem publishes into
(ISSUE-2; the reference's StatsListener→StatsStorage→UI pipeline plus
the throughput-monitoring emphasis of SparkNet/Dragon-Alpha argue for
a single dialect). Design constraints, in order:

- **Near-zero hot-path cost.** An increment is one dict-free attribute
  walk plus one fine-grained `threading.Lock` around a float add
  (~1 µs); the serving engine's decode path adds a handful of these
  per *batch*, against milliseconds-to-seconds of compiled decode.
  Metrics that would need locking on every read (queue depth, breaker
  state) are pull-model instead: `Gauge.set_function` reads the live
  value only when a scrape/snapshot happens.
- **Exact under concurrency.** Every mutable cell carries its own
  lock, so 8 threads hammering one counter lose no updates
  (tests/test_observability.py hammers exactly that).
- **Monotonic timing.** `Histogram.time()` uses `time.perf_counter`,
  never `time.time`, so latency series survive wall-clock steps.
- **Injectable.** A process-default registry (`default_registry()`)
  for the common one-process case, plus freely constructible
  `MetricsRegistry` instances for per-engine isolation, and
  `NULL_REGISTRY` whose instruments are no-ops — the "bare" arm of the
  instrumented-vs-bare benchmark (flagship.py engine_decode_metrics).

Exposition (Prometheus text / JSON / HTTP) lives in
`observability/export.py`; span-based tracing in
`observability/tracing.py`.
"""
from __future__ import annotations

import bisect
import re
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

_now = time.perf_counter

# Prometheus-style latency buckets (seconds): sub-ms dispatch overheads
# through multi-second compiled programs.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0)

# Compiled-call latency buckets (seconds) for serving prefill / decode
# chunk histograms: those calls run milliseconds (chip) to tens of
# seconds (CPU containers, cold traffic), so DEFAULT_BUCKETS — five of
# whose fourteen edges sit below 10 ms — would pile every observation
# into the top few cells. These trade the sub-ms resolution away for
# an upper range that still separates a 10 s call from a 60 s one.
DECODE_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class _Timer:
    """Context manager timing a block on the monotonic clock into an
    `observe` callback (Histogram.time / NullHistogram.time)."""

    __slots__ = ("_observe", "_t0")

    def __init__(self, observe: Callable[[float], None]):
        self._observe = observe
        self._t0 = 0.0

    def __enter__(self) -> "_Timer":
        self._t0 = _now()
        return self

    def __exit__(self, *exc) -> None:
        self._observe(_now() - self._t0)


class CounterChild:
    """One labeled (or the unlabeled) counter cell."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class GaugeChild:
    """One gauge cell: set/inc/dec, or a pull-model `set_function`
    callback evaluated at read time (zero hot-path cost)."""

    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        self._value = float(value)     # single store: atomic under GIL

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    @property
    def value(self) -> float:
        fn = self._fn
        return float(fn()) if fn is not None else self._value


class HistogramChild:
    """Fixed-bucket histogram cell; bucket bounds are inclusive upper
    edges (Prometheus `le` semantics)."""

    __slots__ = ("_lock", "buckets", "_counts", "_sum", "_count")

    def __init__(self, buckets: Sequence[float]):
        self._lock = threading.Lock()
        self.buckets = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)   # + overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def time(self) -> _Timer:
        return _Timer(self.observe)

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(cumulative bucket counts incl. +Inf, sum, count) — taken
        under the lock so the three are mutually consistent."""
        with self._lock:
            counts = list(self._counts)
            s, c = self._sum, self._count
        cum, acc = [], 0
        for n in counts:
            acc += n
            cum.append(acc)
        return cum, s, c

    @property
    def value(self) -> float:        # uniform read surface: the sum
        return self._sum


class _MetricFamily:
    """Shared labeled-children machinery for the three metric kinds."""

    kind = "untyped"
    _child_args: tuple = ()

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for l in labelnames:
            if not _LABEL_RE.match(l):
                raise ValueError(f"invalid label name {l!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            self._children[()] = self._make_child()

    def _make_child(self):
        raise NotImplementedError

    def labels(self, *values, **kv):
        """Get-or-create the child for one label-value combination
        (positional in `labelnames` order, or by keyword)."""
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by "
                                 "keyword, not both")
            try:
                values = tuple(kv[l] for l in self.labelnames)
            except KeyError as e:
                raise ValueError(f"missing label {e} for {self.name}")
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, got "
                f"{len(values)} value(s)")
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.setdefault(values,
                                                  self._make_child())
        return child

    def _unlabeled(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; call "
                ".labels(...) first")
        return self._children[()]

    def collect(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())


class Counter(_MetricFamily):
    kind = "counter"

    def _make_child(self) -> CounterChild:
        return CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._unlabeled().inc(amount)

    @property
    def value(self) -> float:
        return self._unlabeled().value


class Gauge(_MetricFamily):
    kind = "gauge"

    def _make_child(self) -> GaugeChild:
        return GaugeChild()

    def set(self, value: float) -> None:
        self._unlabeled().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._unlabeled().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._unlabeled().dec(amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._unlabeled().set_function(fn)

    @property
    def value(self) -> float:
        return self._unlabeled().value


class Histogram(_MetricFamily):
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = b
        super().__init__(name, help, labelnames)

    def _make_child(self) -> HistogramChild:
        return HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._unlabeled().observe(value)

    def time(self) -> _Timer:
        return self._unlabeled().time()


class MetricsRegistry:
    """Get-or-create home for metric families. Re-requesting a name is
    idempotent when kind + labelnames match (listeners constructed
    repeatedly against the process default registry must not fight);
    a kind or label mismatch is a hard error — two subsystems silently
    sharing one name with different shapes is the bug this catches."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _MetricFamily] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kw) -> _MetricFamily:
        labelnames = tuple(labelnames)
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, labelnames, **kw)
                self._metrics[name] = m
                return m
        if type(m) is not cls or m.labelnames != labelnames:
            raise ValueError(
                f"metric {name!r} already registered as {m.kind} with "
                f"labels {m.labelnames}; requested {cls.kind} with "
                f"{labelnames}")
        return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_MetricFamily]:
        with self._lock:
            return self._metrics.get(name)

    def collect(self) -> List[_MetricFamily]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]


class _NullInstrument:
    """No-op stand-in for every instrument kind; `labels` returns
    itself so call chains cost one attribute lookup and nothing else."""

    kind = "null"
    labelnames: Tuple[str, ...] = ()
    value = 0.0
    help = ""

    def labels(self, *a, **k) -> "_NullInstrument":
        return self

    def inc(self, amount: float = 1.0) -> None:
        pass

    def dec(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def set_function(self, fn) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    def time(self):
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        pass

    def collect(self):
        return []


_NULL_INSTRUMENT = _NullInstrument()


class NullRegistry:
    """Registry whose instruments do nothing — instrumentation can be
    disabled by injection (the benchmark's "bare" arm) instead of by
    `if` guards at every call site."""

    def counter(self, name, help="", labelnames=()) -> _NullInstrument:
        return _NULL_INSTRUMENT

    def gauge(self, name, help="", labelnames=()) -> _NullInstrument:
        return _NULL_INSTRUMENT

    def histogram(self, name, help="", labelnames=(),
                  buckets=DEFAULT_BUCKETS) -> _NullInstrument:
        return _NULL_INSTRUMENT

    def get(self, name):
        return None

    def collect(self) -> list:
        return []


NULL_REGISTRY = NullRegistry()

_DEFAULT_REGISTRY = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry: what an exporter scrapes when every
    subsystem publishes into the shared substrate."""
    return _DEFAULT_REGISTRY
