"""Continuous profiling & cost attribution for the serving engine.

The serving stack can say how fast it went (SLO layer, round 11) and
where the time went across the fleet (distributed traces, round 18) —
but not how fast it COULD have gone, nor who spent the FLOPs. This
module (ISSUE-15) is that accounting layer, three instruments in one:

- **Per-program device accounting** (`EngineProfiler`). Every compiled
  serving program's XLA cost analysis (FLOPs + bytes accessed per
  invocation — the same un-gameable compiler numbers util/flops.py
  uses for training MFU) lands in a per-engine cost table when the
  program is resolved (jit-compiled, AOT-cache-loaded, or in-memory
  hit — warmup() therefore completes the table before traffic). The
  tick loop attributes each tick's device-busy interval to the
  programs dispatched that tick, proportionally to their analytic
  FLOPs, yielding ``serving_program_device_seconds_total{program}``,
  ``serving_program_flops_total{program}`` /
  ``serving_program_bytes_total{program}``, achieved FLOP/s and
  bytes/s, a live ``serving_mfu`` gauge (windowed achieved FLOP/s over
  the chip's peak — 0 when the chip's peak is unknown, e.g. CPU
  containers), and a per-program ROOFLINE classification: arithmetic
  intensity (FLOPs/byte) against the chip's ridge point
  (peak FLOP/s ÷ peak bytes/s) says whether each program is compute-
  or memory-bound — decode chunks live far left of the ridge, big
  prefill buckets to its right.
- **Per-tenant cost metering** (`TenantMeter`). ``submit(tenant=...)``
  threads a tenant label through the request lifecycle; every token a
  request actually COMPUTES (prefilled prompt tokens — prefix-cache
  hits and migrated chains excluded, the round-19
  serving_prefill_tokens_total semantics — plus committed decode
  tokens) bills ``tokens x the per-token analytic cost`` of the
  program that computed them into
  ``serving_request_cost_flops_total{tenant}`` /
  ``serving_request_cost_bytes_total{tenant}`` /
  ``serving_tenant_tokens_total{tenant,kind}``. The tenant label set
  is CARDINALITY-BOUNDED: the first ``top_n`` distinct tenants get
  their own label, everyone later folds into ``"other"`` — a hostile
  tenant-id stream cannot explode the scrape
  (observability/federation.check_cardinality guards the federated
  merge; tests/test_profiling.py hammers exactly that). Per-request
  bills accumulate on the handle (``handle.cost_flops``), so
  ``sum(per-request bills) == the counter`` by construction — the
  fleet cost report's exactness contract.
- **On-demand capture** (`ProfileCapture`). ``/profilez?seconds=N``
  (observability/export.MetricsServer) starts one bounded
  ``jax.profiler`` trace into a configured directory — single-flight
  (a second capture while one runs gets 503), 503 when unsupported
  (no directory configured, or no jax.profiler) — so "what was the
  device doing during that spike" is one curl away, per replica or
  router-fanned (`serving/fleet.Router.profilez`).

Disable-by-injection mirrors the rest of the observability substrate:
`NULL_PROFILER` makes every call a no-op — the profiling-off arm of
the ``profiling_overhead`` benchmark (≤ 2% bound, BASELINE.md).
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("deeplearning4j_tpu")

_perf = time.perf_counter

#: Tenant label under which every tenant past the top-N bound (and
#: requests submitted without a tenant= when fold_default is set) is
#: aggregated — the scrape-side cardinality backstop.
OTHER_TENANT = "other"

#: Default tenant label for requests submitted without ``tenant=`` —
#: unattributed traffic is still metered, just not per-customer.
DEFAULT_TENANT = "default"


def cost_from_compiled(compiled) -> dict:
    """{'flops': float, 'bytes': float} from a compiled executable's
    XLA cost analysis — {} when the backend offers no estimate (some
    PJRT plugins raise UNIMPLEMENTED; availability over purity, the
    caller's table simply stays rate-less for that program)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):      # older jax returns [dict]
        ca = ca[0] if ca else {}
    if not ca:
        return {}
    out = {}
    f = ca.get("flops")
    b = ca.get("bytes accessed")
    if f is not None and f >= 0:
        out["flops"] = float(f)
    if b is not None and b >= 0:
        out["bytes"] = float(b)
    return out


def roofline(flops: float, bytes_: float,
             peak_flops: Optional[float],
             peak_bytes_per_s: Optional[float]) -> dict:
    """Roofline classification of one program: arithmetic intensity
    (FLOPs per byte accessed) against the chip's ridge point
    (peak FLOP/s ÷ peak bytes/s). Left of the ridge the roofline's
    slanted (bandwidth) roof binds — memory-bound; right of it the
    flat (compute) roof does. "unknown" when either peak is unknown
    (CPU containers) or the program has no byte estimate."""
    intensity = (flops / bytes_) if bytes_ and bytes_ > 0 else None
    ridge = (peak_flops / peak_bytes_per_s
             if peak_flops and peak_bytes_per_s else None)
    if intensity is None or ridge is None:
        bound = "unknown"
    elif intensity >= ridge:
        bound = "compute"
    else:
        bound = "memory"
    return {"intensity_flops_per_byte": (round(intensity, 3)
                                         if intensity is not None
                                         else None),
            "ridge_flops_per_byte": (round(ridge, 3)
                                     if ridge is not None else None),
            "bound": bound}


class TenantMeter:
    """Per-tenant analytic cost counters with a top-N + "other"
    cardinality bound.

    Prometheus counter children are immutable once created, so the
    bound is enforced at label-assignment time: the first ``top_n``
    distinct tenant ids seen get their own label; every later id maps
    to ``"other"``. Host-side per-tenant totals are kept for the SAME
    bounded id set (ranking and reports never resurrect a folded
    tenant), so a hostile stream of unique ids costs one dict entry —
    the "other" row — not one series each.
    """

    def __init__(self, registry, top_n: int = 8):
        self.top_n = max(1, int(top_n))
        self._lock = threading.Lock()
        self._labels: Dict[str, str] = {}
        self._totals: Dict[str, dict] = {}
        self._folded = 0
        self._m_flops = registry.counter(
            "serving_request_cost_flops",
            "Analytic FLOPs billed to requests, by tenant (tokens "
            "actually computed x the per-token XLA cost of the "
            "program that computed them; prefix-cache hits and "
            "migrated KV bill only the tokens recomputed)",
            labelnames=("tenant",))
        self._m_bytes = registry.counter(
            "serving_request_cost_bytes",
            "Analytic bytes accessed billed to requests, by tenant",
            labelnames=("tenant",))
        self._m_tokens = registry.counter(
            "serving_tenant_tokens",
            "Tokens computed for requests, by tenant and kind "
            "(prefill = prompt tokens this engine prefilled, decode "
            "= committed generated tokens)",
            labelnames=("tenant", "kind"))

    def label_for(self, tenant: Optional[str]) -> str:
        t = DEFAULT_TENANT if tenant is None else str(tenant)
        with self._lock:
            lab = self._labels.get(t)
            if lab is None:
                if len(self._labels) < self.top_n:
                    lab = t
                else:
                    lab = OTHER_TENANT
                    self._folded += 1
                self._labels[t] = lab
            return lab

    def bill(self, tenant: Optional[str], flops: float, bytes_: float,
             tokens: int, kind: str) -> str:
        """Record one bill; returns the (bounded) label used."""
        lab = self.label_for(tenant)
        if flops:
            self._m_flops.labels(lab).inc(flops)
        if bytes_:
            self._m_bytes.labels(lab).inc(bytes_)
        if tokens:
            self._m_tokens.labels(lab, kind).inc(tokens)
        with self._lock:
            cell = self._totals.setdefault(
                lab, {"flops": 0.0, "bytes": 0.0,
                      "prefill_tokens": 0, "decode_tokens": 0})
            cell["flops"] += flops
            cell["bytes"] += bytes_
            cell[f"{kind}_tokens"] = (cell.get(f"{kind}_tokens", 0)
                                      + int(tokens))
        return lab

    def report(self) -> dict:
        """Per-tenant bill ranked by FLOPs, plus the fold accounting
        (how many distinct ids landed in "other")."""
        with self._lock:
            totals = {t: dict(v) for t, v in self._totals.items()}
            distinct = len(self._labels)
            folded = self._folded
        ranked = sorted(totals.items(),
                        key=lambda kv: -kv[1]["flops"])
        return {"top_n": self.top_n,
                "distinct_tenants_seen": distinct,
                "bills_folded_to_other": folded,
                "tenants": {t: {
                    "flops": v["flops"], "bytes": v["bytes"],
                    "prefill_tokens": v["prefill_tokens"],
                    "decode_tokens": v["decode_tokens"]}
                    for t, v in ranked}}


class EngineProfiler:
    """Per-engine device accounting: program cost table, per-tick
    device-time attribution, live MFU, roofline report, and the tenant
    meter. One instance per engine (injected like recorder/slo);
    enabled is True — `NULL_PROFILER` is the off switch.

    ``peak_flops`` / ``peak_bytes_per_s`` default to the chip tables
    in util/flops.py (None on CPU → MFU reports 0 and rooflines read
    "unknown"); tests inject synthetic peaks to pin classifications.
    """

    enabled = True

    def __init__(self, registry, *,
                 peak_flops: Optional[float] = None,
                 peak_bytes_per_s: Optional[float] = None,
                 tenant_top_n: int = 8,
                 window_s: float = 60.0):
        from deeplearning4j_tpu.util.flops import (chip_peak_bytes_per_s,
                                                   chip_peak_flops)
        self.registry = registry
        if peak_flops is None:
            try:
                peak_flops = chip_peak_flops()
            except Exception:
                peak_flops = None
        if peak_bytes_per_s is None:
            try:
                peak_bytes_per_s = chip_peak_bytes_per_s()
            except Exception:
                peak_bytes_per_s = None
        self.peak_flops = peak_flops
        self.peak_bytes_per_s = peak_bytes_per_s
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        # program -> {"flops": per-invocation, "bytes": per-invocation,
        #             "tokens": tokens one invocation computes}
        self._table: Dict[str, dict] = {}
        # open tick state: labels dispatched since tick_begin (None =
        # no open tick: resolutions outside the tick loop — warmup,
        # batch mode — are recorded in the table but not attributed);
        # _last_labels backs commit-only drain ticks (see tick_end)
        self._tick_labels: Optional[List[str]] = None
        self._last_labels: List[str] = []
        self._window: deque = deque(maxlen=4096)   # (t, flops, bytes,
        #                                             busy_s)
        self.meter = TenantMeter(registry, top_n=tenant_top_n)
        self._m_invocations = registry.counter(
            "serving_program_invocations",
            "Compiled-program dispatches, by program",
            labelnames=("program",))
        self._m_device_seconds = registry.counter(
            "serving_program_device_seconds",
            "Device-busy seconds attributed to each program "
            "(tick busy intervals split across the tick's dispatches "
            "proportionally to their analytic FLOPs)",
            labelnames=("program",))
        self._m_flops = registry.counter(
            "serving_program_flops",
            "Analytic FLOPs dispatched, by program (XLA cost "
            "analysis x invocations)", labelnames=("program",))
        self._m_bytes = registry.counter(
            "serving_program_bytes",
            "Analytic bytes accessed dispatched, by program",
            labelnames=("program",))
        registry.gauge(
            "serving_mfu",
            "Model-FLOPs utilization over the recent window: achieved "
            "analytic FLOP/s / chip peak (0 when the chip peak is "
            "unknown, e.g. CPU)").set_function(lambda: self.mfu())
        registry.gauge(
            "serving_achieved_flops_per_second",
            "Analytic FLOP/s achieved over the recent window"
            ).set_function(lambda: self.achieved()[0])
        registry.gauge(
            "serving_achieved_bytes_per_second",
            "Analytic bytes/s accessed over the recent window"
            ).set_function(lambda: self.achieved()[1])

    # -- cost table ----------------------------------------------------
    def record_program(self, label: str, cost: Optional[dict],
                       tokens: Optional[int]) -> None:
        """Install (or refresh) one program's per-invocation cost.
        Idempotent; a rate-less entry (backend without cost analysis)
        still counts invocations and device seconds."""
        with self._lock:
            ent = self._table.setdefault(
                label, {"flops": 0.0, "bytes": 0.0, "tokens": 0,
                        "invocations": 0, "device_seconds": 0.0})
            if cost:
                ent["flops"] = float(cost.get("flops", 0.0))
                ent["bytes"] = float(cost.get("bytes", 0.0))
            if tokens:
                ent["tokens"] = int(tokens)

    def has_program(self, label: str) -> bool:
        with self._lock:
            return label in self._table

    def token_cost(self, label: Optional[str]) -> Tuple[float, float]:
        """(flops, bytes) one token costs under ``label``'s program —
        per-invocation cost over the tokens one invocation computes.
        (0, 0) for unknown programs (batch-mode generate has no fixed
        geometry to cost)."""
        if label is None:
            return 0.0, 0.0
        with self._lock:
            ent = self._table.get(label)
            if ent is None or not ent["tokens"]:
                return 0.0, 0.0
            return (ent["flops"] / ent["tokens"],
                    ent["bytes"] / ent["tokens"])

    # -- per-tick attribution ------------------------------------------
    def tick_begin(self) -> None:
        self._tick_labels = []

    def dispatched(self, label: str) -> None:
        """One compiled-call dispatch (the engine's _resolve_program
        funnel). Only attributed when a tick is open — warmup
        resolutions and batch-mode calls update the table, not the
        attribution."""
        if self._tick_labels is not None:
            self._tick_labels.append(label)

    def tick_end(self, busy_s: float) -> None:
        """Close the tick: attribute its device-busy interval across
        the dispatched programs proportionally to their analytic
        FLOPs (equal split when no program has a rate), advance the
        per-program counters, and push the tick into the MFU
        window. A commit-only tick (the pipelined loop's drain tail:
        it syncs the PREVIOUS tick's dispatches without issuing new
        ones) attributes its busy interval to the previous tick's
        label mix — attribution conserves the engine's busy total."""
        labels, self._tick_labels = self._tick_labels, None
        busy_s = max(0.0, float(busy_s))
        if not labels:
            if busy_s <= 0.0 or not self._last_labels:
                return
            labels = list(self._last_labels)
            dispatched = False
        else:
            self._last_labels = list(labels)
            dispatched = True
        with self._lock:
            weights = [max(0.0, self._table.get(l, {}).get("flops",
                                                           0.0))
                       for l in labels]
            total_w = sum(weights)
            if total_w <= 0:
                weights = [1.0] * len(labels)
                total_w = float(len(labels))
            tick_flops = tick_bytes = 0.0
            for lab, w in zip(labels, weights):
                ent = self._table.setdefault(
                    lab, {"flops": 0.0, "bytes": 0.0, "tokens": 0,
                          "invocations": 0, "device_seconds": 0.0})
                share = busy_s * w / total_w
                ent["device_seconds"] += share
                if share:
                    self._m_device_seconds.labels(lab).inc(share)
                if not dispatched:
                    continue     # drain tail: time only, no new work
                ent["invocations"] += 1
                tick_flops += ent["flops"]
                tick_bytes += ent["bytes"]
                self._m_invocations.labels(lab).inc()
                if ent["flops"]:
                    self._m_flops.labels(lab).inc(ent["flops"])
                if ent["bytes"]:
                    self._m_bytes.labels(lab).inc(ent["bytes"])
        self._window.append((_perf(), tick_flops, tick_bytes, busy_s))

    # -- derived rates -------------------------------------------------
    def achieved(self, window_s: Optional[float] = None
                 ) -> Tuple[float, float]:
        """(FLOP/s, bytes/s) achieved over the recent window —
        analytic work dispatched over wall time elapsed."""
        w = self.window_s if window_s is None else float(window_s)
        now = _perf()
        pts = [p for p in self._window if now - p[0] <= w]
        if not pts:
            return 0.0, 0.0
        elapsed = max(now - pts[0][0], 1e-9)
        return (sum(p[1] for p in pts) / elapsed,
                sum(p[2] for p in pts) / elapsed)

    def mfu(self, window_s: Optional[float] = None) -> float:
        """Live MFU: windowed achieved FLOP/s over the chip peak. 0.0
        when the peak is unknown (the gauge must still scrape)."""
        if not self.peak_flops:
            return 0.0
        return self.achieved(window_s)[0] / self.peak_flops

    # -- tenant billing ------------------------------------------------
    def bill_tokens(self, handle, label: Optional[str], tokens: int,
                    kind: str) -> None:
        """Bill ``tokens`` computed under ``label``'s program to the
        handle's tenant, and accumulate the same amounts on the handle
        (sum of per-request bills == the counters, by construction)."""
        if tokens <= 0:
            return
        fl_rate, by_rate = self.token_cost(label)
        flops = fl_rate * tokens
        bytes_ = by_rate * tokens
        tenant = getattr(handle, "tenant", None)
        self.meter.bill(tenant, flops, bytes_, tokens, kind)
        handle.cost_flops = getattr(handle, "cost_flops", 0.0) + flops
        handle.cost_bytes = getattr(handle, "cost_bytes", 0.0) + bytes_

    # -- reports -------------------------------------------------------
    def program_report(self) -> dict:
        """The per-program accounting table: per-invocation analytic
        cost, totals, achieved rates, and the roofline verdict."""
        with self._lock:
            table = {l: dict(v) for l, v in self._table.items()}
        out = {}
        for lab, ent in sorted(table.items()):
            dev = ent["device_seconds"]
            inv = ent["invocations"]
            row = {"flops_per_invocation": ent["flops"],
                   "bytes_per_invocation": ent["bytes"],
                   "tokens_per_invocation": ent["tokens"],
                   "invocations": inv,
                   "device_seconds": dev,
                   "flops_total": ent["flops"] * inv,
                   "bytes_total": ent["bytes"] * inv,
                   "achieved_flops_per_s": (
                       round(ent["flops"] * inv / dev, 1)
                       if dev > 0 else None),
                   "achieved_bytes_per_s": (
                       round(ent["bytes"] * inv / dev, 1)
                       if dev > 0 else None)}
            row.update(roofline(ent["flops"], ent["bytes"],
                                self.peak_flops,
                                self.peak_bytes_per_s))
            out[lab] = row
        return out

    def report(self) -> dict:
        """The `/profilez`-adjacent `profile_report()` body: peaks,
        live MFU, achieved rates, per-program rooflines, per-tenant
        bills."""
        fl, by = self.achieved()
        return {"peak_flops": self.peak_flops,
                "peak_bytes_per_s": self.peak_bytes_per_s,
                "ridge_flops_per_byte": (
                    round(self.peak_flops / self.peak_bytes_per_s, 3)
                    if self.peak_flops and self.peak_bytes_per_s
                    else None),
                "mfu": round(self.mfu(), 6),
                "achieved_flops_per_s": round(fl, 1),
                "achieved_bytes_per_s": round(by, 1),
                "programs": self.program_report(),
                "tenant_costs": self.meter.report()}


class NullProfiler:
    """No-op twin: disable profiling by injection (the benchmark's
    profiling-off arm), never by if-guards at the call sites."""

    enabled = False
    peak_flops = None
    peak_bytes_per_s = None

    def record_program(self, label, cost, tokens) -> None:
        pass

    def has_program(self, label) -> bool:
        return True          # suppress re-capture work at call sites

    def token_cost(self, label):
        return 0.0, 0.0

    def tick_begin(self) -> None:
        pass

    def dispatched(self, label) -> None:
        pass

    def tick_end(self, busy_s) -> None:
        pass

    def achieved(self, window_s=None):
        return 0.0, 0.0

    def mfu(self, window_s=None) -> float:
        return 0.0

    def bill_tokens(self, handle, label, tokens, kind) -> None:
        pass

    def program_report(self) -> dict:
        return {}

    def report(self) -> dict:
        return {"enabled": False}


NULL_PROFILER = NullProfiler()


class ProfileCapture:
    """Single-flight on-demand `jax.profiler` capture — the
    ``/profilez?seconds=N`` endpoint's backend.

    ``capture(seconds)`` starts one bounded trace into the configured
    directory and returns ``(http_status, body_dict)``:

    - 200: capture started; a daemon timer stops it after ``seconds``
      (bounded by ``max_seconds`` so a fat-fingered query cannot
      profile for an hour).
    - 503: unsupported (no directory configured / jax.profiler
      unavailable) or BUSY (single-flight: one capture at a time —
      two overlapping traces corrupt each other's TensorBoard dirs).
    - 400: unparseable/non-positive seconds.
    """

    def __init__(self, directory: Optional[str],
                 max_seconds: float = 60.0):
        self.directory = str(directory) if directory else None
        self.max_seconds = float(max_seconds)
        self._lock = threading.Lock()
        self._active_until: Optional[float] = None
        self.captures = 0

    @staticmethod
    def supported() -> bool:
        try:
            import jax.profiler
            return (hasattr(jax.profiler, "start_trace")
                    and hasattr(jax.profiler, "stop_trace"))
        except Exception:
            return False

    @property
    def active(self) -> bool:
        with self._lock:
            return (self._active_until is not None
                    and _perf() < self._active_until + 5.0)

    def capture(self, seconds: float) -> Tuple[int, dict]:
        try:
            seconds = float(seconds)
        except (TypeError, ValueError):
            return 400, {"error": f"unparseable seconds {seconds!r}"}
        if seconds <= 0:
            return 400, {"error": "seconds must be > 0"}
        seconds = min(seconds, self.max_seconds)
        if self.directory is None:
            return 503, {"error": "profiler capture unsupported: no "
                                  "profile_dir configured"}
        if not self.supported():
            return 503, {"error": "profiler capture unsupported: "
                                  "jax.profiler unavailable"}
        with self._lock:
            if (self._active_until is not None
                    and _perf() < self._active_until):
                return 503, {"error": "capture already in progress",
                             "remaining_s": round(
                                 self._active_until - _perf(), 3)}
            import jax.profiler
            try:
                jax.profiler.start_trace(self.directory)
            except Exception as e:
                return 503, {"error": f"start_trace failed: "
                                      f"{type(e).__name__}: {e}"}
            self._active_until = _perf() + seconds
            self.captures += 1

        def _stop():
            time.sleep(seconds)
            import jax.profiler as jp
            try:
                jp.stop_trace()
            except Exception:
                log.exception("profiler stop_trace failed")
            finally:
                with self._lock:
                    self._active_until = None

        threading.Thread(target=_stop, daemon=True,
                         name="profilez-capture").start()
        return 200, {"started": True, "seconds": seconds,
                     "directory": self.directory,
                     "capture": self.captures}
