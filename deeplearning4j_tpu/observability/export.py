"""Exposition: Prometheus text format, JSON snapshot, HTTP exporter.

`prometheus_text(registry)` renders the text exposition format
(version 0.0.4) an external Prometheus/victoria/grafana-agent scraper
parses: HELP/TYPE headers, label escaping, counters suffixed `_total`,
histograms as cumulative `_bucket{le=...}` series plus `_sum`/`_count`.

`MetricsServer` is the tiny stdlib exporter: `/metrics` (text format),
`/metrics.json` (the JSON snapshot), and `/healthz` + `/readyz` backed
by pluggable callables — wire `InferenceEngine.health` / `.ready`
straight in. The same three endpoints also mount on the training
dashboard (`ui/server.UIServer.attach_metrics`), so one port can serve
charts AND scrapes.

Serving introspection (ISSUE-6): three more pluggable JSON endpoints —
`/debugz` (`debug=engine.debugz`: slot table, queue ages, breaker
state, recent flight-recorder events), `/slo`
(`slo=engine.slo_report`: the windowed TTFT/TPOT/goodput report), and
`/timeline.json` (`timeline=engine.timeline`: Chrome/Perfetto
trace_event export, one lane per slot plus the queue lane). Each 404s
when its callable isn't wired. A scraper that hangs up mid-response
(half-closed socket, curl ctrl-C) is swallowed in `_send` — client
disconnects must never traceback-spam or destabilize the exporter's
daemon thread.
"""
from __future__ import annotations

import json
import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional
from urllib.parse import urlparse

from deeplearning4j_tpu.observability.metrics import (Histogram,
                                                      default_registry)

CONTENT_TYPE_LATEST = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(s: str) -> str:
    return s.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label_value(s: str) -> str:
    return (s.replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _fmt(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v) == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _label_str(labelnames, labelvalues, extra: str = "") -> str:
    parts = [f'{n}="{_escape_label_value(str(v))}"'
             for n, v in zip(labelnames, labelvalues)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text(registry=None) -> str:
    """Render a registry in the Prometheus text exposition format."""
    reg = registry if registry is not None else default_registry()
    lines = []
    for fam in reg.collect():
        name = fam.name
        if fam.kind == "counter" and not name.endswith("_total"):
            name = name + "_total"
        lines.append(f"# HELP {name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {name} {fam.kind}")
        for labelvalues, child in fam.collect():
            if isinstance(fam, Histogram):
                cum, total, count = child.snapshot()
                edges = [*fam.buckets, float("inf")]
                for edge, c in zip(edges, cum):
                    le = f'le="{_fmt(edge)}"'
                    lines.append(
                        f"{name}_bucket"
                        f"{_label_str(fam.labelnames, labelvalues, le)}"
                        f" {c}")
                base = _label_str(fam.labelnames, labelvalues)
                lines.append(f"{name}_sum{base} {_fmt(total)}")
                lines.append(f"{name}_count{base} {count}")
            else:
                lines.append(
                    f"{name}{_label_str(fam.labelnames, labelvalues)}"
                    f" {_fmt(child.value)}")
    return "\n".join(lines) + "\n"


def snapshot_prometheus_text(snap: Dict[str, dict]) -> str:
    """Render a JSON-schema snapshot (the `json_snapshot` shape — also
    what `observability.federation.merge_snapshots` produces) in the
    Prometheus text exposition format. This is how a fleet router's
    FEDERATED view (ISSUE-13) serves `/metrics`: the merged samples
    exist only as a snapshot, never as live instrument objects, so the
    registry-walking `prometheus_text` cannot render them."""
    lines = []
    for name in sorted(snap):
        fam = snap[name]
        kind = fam.get("kind", "untyped")
        out_name = (name + "_total"
                    if kind == "counter" and not name.endswith("_total")
                    else name)
        lines.append(f"# HELP {out_name} "
                     f"{_escape_help(fam.get('help', ''))}")
        lines.append(f"# TYPE {out_name} {kind}")
        for s in fam.get("samples", ()):
            labels = s.get("labels") or {}
            lnames, lvals = list(labels), list(labels.values())
            if kind == "histogram" or "buckets" in s:
                for edge, c in (s.get("buckets") or {}).items():
                    le = f'le="{edge}"'
                    lines.append(
                        f"{out_name}_bucket"
                        f"{_label_str(lnames, lvals, le)} {int(c)}")
                base = _label_str(lnames, lvals)
                lines.append(f"{out_name}_sum{base} "
                             f"{_fmt(float(s.get('sum', 0.0)))}")
                lines.append(f"{out_name}_count{base} "
                             f"{int(s.get('count', 0))}")
            else:
                lines.append(
                    f"{out_name}{_label_str(lnames, lvals)} "
                    f"{_fmt(float(s.get('value', 0.0)))}")
    return "\n".join(lines) + "\n"


def json_snapshot(registry=None) -> Dict[str, dict]:
    """Machine-readable snapshot: {name: {kind, help, samples: [...]}}.
    Histogram samples carry cumulative buckets + sum + count."""
    reg = registry if registry is not None else default_registry()
    out: Dict[str, dict] = {}
    for fam in reg.collect():
        samples = []
        for labelvalues, child in fam.collect():
            labels = dict(zip(fam.labelnames, labelvalues))
            if isinstance(fam, Histogram):
                cum, total, count = child.snapshot()
                samples.append({"labels": labels,
                                "buckets": dict(zip(
                                    [_fmt(b) for b in fam.buckets]
                                    + ["+Inf"], cum)),
                                "sum": total, "count": count})
            else:
                samples.append({"labels": labels,
                                "value": child.value})
        out[fam.name] = {"kind": fam.kind, "help": fam.help,
                         "samples": samples}
    return out


def probe_response(fn: Optional[Callable[[], object]]):
    """(status_code, body_dict) for a health/readiness callable.

    Contract: no callable → 200 (the process answering IS the
    liveness signal); a dict result reports 200/503 from its "ready"
    key (default True) and is echoed in the body; any other result is
    truth-tested; a raising callable is 503 with the error."""
    if fn is None:
        return 200, {"ok": True}
    try:
        res = fn()
    except Exception as e:
        return 503, {"ok": False, "error": f"{type(e).__name__}: {e}"}
    if isinstance(res, dict):
        ok = bool(res.get("ready", True))
        return (200 if ok else 503), {"ok": ok, **res}
    return (200, {"ok": True}) if res else (503, {"ok": False})


class _MetricsHandler(BaseHTTPRequestHandler):
    server_version = "dl4jtpu-metrics/1.0"
    registry = None                  # injected via subclass attrs
    health_fn: Optional[Callable] = None
    ready_fn: Optional[Callable] = None
    debug_fn: Optional[Callable] = None
    slo_fn: Optional[Callable] = None
    timeline_fn: Optional[Callable] = None
    snapshot_fn: Optional[Callable] = None   # federated view override
    profilez_fn: Optional[Callable] = None   # on-demand capture

    def log_message(self, *args) -> None:   # silence request logging
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        # a client that hung up mid-scrape (half-closed socket, curl
        # ctrl-C) raises on the write; that is the CLIENT's problem —
        # swallowing it here keeps the daemon thread from spewing
        # tracebacks via socketserver.handle_error and keeps the
        # exporter serving the next scrape
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            try:
                self.close_connection = True
            except Exception:
                pass

    def _send_callable_json(self, fn: Optional[Callable]) -> None:
        """One pluggable JSON endpoint: 404 when unwired, 500 (with
        the error in the body) when the callable raises — an
        introspection endpoint must never kill the exporter."""
        if fn is None:
            self._send(404, b'{"error": "not wired"}',
                       "application/json")
            return
        try:
            body = json.dumps(fn()).encode()
        except Exception as e:
            self._send(500, json.dumps(
                {"error": f"{type(e).__name__}: {e}"}).encode(),
                "application/json")
            return
        self._send(200, body, "application/json")

    def do_GET(self) -> None:
        # class-attribute access: plain-function callables stored on
        # the subclass must NOT descriptor-bind to the handler instance
        cls = type(self)
        path = urlparse(self.path).path
        if path == "/metrics":
            # snapshot override (ISSUE-13): a router serving its
            # FEDERATED fleet view builds the merged snapshot per
            # scrape; a failing federation must 500, never kill the
            # exporter thread
            if cls.snapshot_fn is not None:
                try:
                    body = snapshot_prometheus_text(
                        cls.snapshot_fn()).encode()
                except Exception as e:
                    self._send(500, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode(),
                        "application/json")
                    return
                self._send(200, body, CONTENT_TYPE_LATEST)
                return
            self._send(200, prometheus_text(cls.registry).encode(),
                       CONTENT_TYPE_LATEST)
        elif path == "/metrics.json":
            if cls.snapshot_fn is not None:
                self._send_callable_json(cls.snapshot_fn)
                return
            self._send(200, json.dumps(
                json_snapshot(cls.registry)).encode(),
                "application/json")
        elif path == "/healthz":
            code, body = probe_response(cls.health_fn)
            self._send(code, json.dumps(body).encode(),
                       "application/json")
        elif path == "/readyz":
            code, body = probe_response(cls.ready_fn or cls.health_fn)
            self._send(code, json.dumps(body).encode(),
                       "application/json")
        elif path == "/debugz":
            self._send_callable_json(cls.debug_fn)
        elif path == "/slo":
            self._send_callable_json(cls.slo_fn)
        elif path == "/timeline.json":
            self._send_callable_json(cls.timeline_fn)
        elif path == "/profilez":
            # on-demand profiler capture (ISSUE-15):
            # GET /profilez?seconds=N starts one bounded jax.profiler
            # trace. The callable owns the status semantics — it
            # returns (code, body): 200 started, 503 unsupported/busy
            # (single-flight), 400 bad seconds — because "cannot
            # capture right now" is an HTTP condition, not a server
            # error
            if cls.profilez_fn is None:
                self._send(404, b'{"error": "not wired"}',
                           "application/json")
                return
            from urllib.parse import parse_qs
            qs = parse_qs(urlparse(self.path).query)
            seconds = (qs.get("seconds") or ["1.0"])[0]
            try:
                code, body = cls.profilez_fn(seconds)
                self._send(int(code), json.dumps(body).encode(),
                           "application/json")
            except Exception as e:
                self._send(500, json.dumps(
                    {"error": f"{type(e).__name__}: {e}"}).encode(),
                    "application/json")
        else:
            self._send(404, b'{"error": "not found"}',
                       "application/json")


class MetricsServer:
    """Stdlib HTTP exporter over one registry.

    >>> srv = MetricsServer(registry, port=0, health=engine.health,
    ...                     ready=engine.ready)
    >>> # curl http://127.0.0.1:<srv.port>/metrics
    >>> srv.stop()

    `port=0` binds an ephemeral port (read it back from `.port`).
    The server thread is a daemon; `stop()` shuts it down cleanly.

    Serving introspection (optional callables; each endpoint 404s
    when unwired):

    >>> srv = MetricsServer(engine.registry, health=engine.health,
    ...                     ready=engine.ready, debug=engine.debugz,
    ...                     slo=engine.slo_report,
    ...                     timeline=engine.timeline)
    >>> # curl .../debugz  .../slo  .../timeline.json

    ``snapshot`` overrides what `/metrics` and `/metrics.json` serve:
    a callable returning a JSON-schema snapshot (the `json_snapshot`
    shape) rendered per scrape — wire `Router.federate` here and the
    router's port serves the whole FLEET's merged series (ISSUE-13).

    ``profilez`` wires `GET /profilez?seconds=N` (ISSUE-15): a
    callable taking the seconds value and returning ``(status, body)``
    — wire `engine.profilez` (single-flight bounded `jax.profiler`
    capture, 503 when unsupported or already capturing) or
    `Router.profilez` for the fleet-fanned version.
    """

    def __init__(self, registry=None, port: int = 0,
                 health: Optional[Callable] = None,
                 ready: Optional[Callable] = None,
                 debug: Optional[Callable] = None,
                 slo: Optional[Callable] = None,
                 timeline: Optional[Callable] = None,
                 snapshot: Optional[Callable] = None,
                 profilez: Optional[Callable] = None):
        self.registry = (registry if registry is not None
                         else default_registry())
        handler = type("BoundMetricsHandler", (_MetricsHandler,),
                       {"registry": self.registry, "health_fn": health,
                        "ready_fn": ready, "debug_fn": debug,
                        "slo_fn": slo, "timeline_fn": timeline,
                        "snapshot_fn": snapshot,
                        "profilez_fn": profilez})
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="metrics-exporter")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
