"""Distributed-trace stitching: ONE timeline per fleet request.

A fleet request's lifecycle is scattered across N process-local flight
recorders: the router records ``submit → queued → dispatched →
handoff/failover → finished`` in ITS ring, while every replica that
served a hop recorded ``submit → admitted → prefill_done →
decode_chunk* → finished`` in its OWN ring, on its OWN
``perf_counter`` clock. This module (ISSUE-13) reassembles them:

- `stitch()` merges the router-side trace with the per-hop replica
  traces the router captured (`serving/fleet.py` ships a subprocess
  replica's completed trace back over the pipe; an in-process
  replica's is read by reference), aligning replica timestamps into
  the router's clock domain via each replica's probe-RTT-midpoint
  ``clock_offset`` and producing a `StitchedTrace`: one chronological
  event list plus derived SPANS — ``queue`` waits, per-hop
  ``hop``/``prefill``/``decode`` spans, and cross-tier ``handoff``
  spans. A kill-mid-decode failover shows both hops (and the
  re-prefill) in the same trace.
- `StitchedTrace` duck-types the `RequestTrace` read surface
  (``events`` / ``first_ts`` / ``last_ts`` / ``complete``), so the
  fleet-level `SLOTracker` consumes it directly — fleet TTFT and e2e
  finally include router queue time and handoff time.
- `router_lane_events()` + `fleet_timeline_json()` render the
  fleet-wide Perfetto export: the router's queue/dispatch lanes as one
  process group, each replica's slot lanes as its own process group
  (named ``<tier>/replica <id>``), every group re-based to one shared
  t=0.

Clock-alignment caveat: a subprocess replica's offset is estimated as
the midpoint of a ping's send/receive ``perf_counter`` pair (the NTP
idea, min-RTT sample wins), so aligned timestamps carry up to ±RTT/2
of error. `stitch()` therefore CLAMPS each hop's events to start no
earlier than its ``dispatched`` event and to end no later than the
router-side terminal event — the stitched trace is monotonically
consistent by construction, at the cost of up to RTT/2 of distortion
at hop edges. In-process replicas share the router's clock
(offset 0) and are exact. Stdlib-only.
"""
from __future__ import annotations

import logging
from typing import Dict, Iterable, List, Optional

from deeplearning4j_tpu.observability.events import (Event,
                                                     TERMINAL_KINDS)
from deeplearning4j_tpu.observability.timeline import trace_events

log = logging.getLogger("deeplearning4j_tpu")

#: span vocabulary a stitched trace can derive (docs/observability.md)
SPAN_NAMES = ("queue", "hop", "prefill", "decode", "handoff")


def _as_event(e) -> Event:
    """Accept Event tuples or their `as_dict` form (pipe-shipped)."""
    if isinstance(e, Event):
        return e
    d = dict(e)
    return Event(float(d.pop("ts", 0.0)), str(d.pop("kind", "shed")),
                 int(d.pop("rid", 0)), d)


class StitchedTrace:
    """One fleet request's merged router+replica timeline plus the
    spans derived from it. Read surface mirrors `RequestTrace` so the
    SLO layer can consume either."""

    __slots__ = ("rid", "_events", "spans", "hops")

    def __init__(self, rid: int, events: List[Event],
                 spans: List[dict], hops: List[dict]):
        self.rid = int(rid)
        self._events = tuple(events)
        self.spans = spans
        self.hops = hops

    @property
    def events(self):
        return self._events

    def kinds(self) -> List[str]:
        return [e.kind for e in self._events]

    def first_ts(self, kind: str) -> Optional[float]:
        for e in self._events:
            if e.kind == kind:
                return e.ts
        return None

    def last_ts(self, kind: str) -> Optional[float]:
        ts = None
        for e in self._events:
            if e.kind == kind:
                ts = e.ts
        return ts

    def complete(self) -> bool:
        return bool(self._events) and \
            self._events[-1].kind in TERMINAL_KINDS

    def span(self, name: str) -> List[dict]:
        return [s for s in self.spans if s["name"] == name]

    def as_dicts(self) -> List[dict]:
        return [e.as_dict() for e in self._events]

    def to_dict(self) -> dict:
        """The `/debugz` / `Router.distributed_trace` JSON body."""
        return {"rid": self.rid,
                "events": self.as_dicts(),
                "spans": list(self.spans),
                "hops": [{k: v for k, v in h.items() if k != "events"}
                         for h in self.hops]}

    def __len__(self) -> int:
        return len(self._events)


def stitch(rid: int, router_events: Iterable[Event],
           hops: Iterable[dict]) -> StitchedTrace:
    """Merge one fleet request's router trace with its captured hops.

    ``hops`` entries are the router's hop records::

        {"hop": int, "replica": int, "tier": str, "kind": str,
         "phase": "prefill"|"decode"|"serving", "hedge": bool,
         "status": str, "clock_offset": float,
         "dispatched_ts": float|None, "events": [Event|dict, ...]}

    Replica event timestamps are aligned (``ts - clock_offset``),
    clamped to the hop's ``dispatched`` moment on the left and the
    router-side terminal event on the right (see module docstring),
    then merged with the router events into one chronological list.
    """
    r_evs = sorted((_as_event(e) for e in router_events),
                   key=lambda e: e.ts)
    term_ts = None
    for e in reversed(r_evs):
        if e.kind in TERMINAL_KINDS:
            term_ts = e.ts
            break
    merged: List[Event] = [
        Event(e.ts, e.kind, e.rid, {**e.data, "src": "router"})
        for e in r_evs]
    spans: List[dict] = []
    out_hops: List[dict] = []
    hop_close: Dict[int, float] = {}       # replica -> last lost-hop t1

    for h in sorted(hops, key=lambda d: int(d.get("hop", 0) or 0)):
        off = float(h.get("clock_offset") or 0.0)
        raw = sorted((_as_event(e) for e in (h.get("events") or ())),
                     key=lambda e: e.ts)
        d_ts = h.get("dispatched_ts")
        # one pass: clock alignment, then clamp-shift right so the
        # hop can't start before its dispatch (midpoint clock error —
        # shifting the whole hop keeps its internal deltas exact),
        # then clamp left of the router-side terminal
        shift = -off
        if raw and d_ts is not None and raw[0].ts + shift < d_ts:
            shift = d_ts - raw[0].ts
        evs = [Event(e.ts + shift if term_ts is None
                     else min(e.ts + shift, term_ts),
                     e.kind, e.rid, e.data)
               for e in raw]
        meta = {k: h.get(k) for k in ("hop", "replica", "tier",
                                      "phase", "kind", "status",
                                      "hedge")}
        t0 = d_ts if d_ts is not None else (evs[0].ts if evs else None)
        t1 = max([e.ts for e in evs] + ([t0] if t0 is not None else []),
                 default=None)
        out_hops.append({**meta, "t0": t0, "t1": t1,
                         "n_events": len(evs)})
        if h.get("status") == "lost" and t1 is not None:
            hop_close[int(h.get("replica", -1))] = t1
        anchor = {k: meta[k] for k in ("hop", "replica", "tier",
                                       "phase")}
        if t0 is not None:
            spans.append({"name": "hop", **anchor, "t0": t0,
                          "t1": max(t0, t1)})
        pf = next((e for e in evs if e.kind == "prefill_done"), None)
        if pf is not None and t0 is not None:
            spans.append({"name": "prefill", **anchor, "t0": t0,
                          "t1": max(t0, pf.ts)})
        toks = [e for e in evs
                if e.kind in ("prefill_done", "decode_chunk")
                and e.data.get("tokens")]
        if toks and meta.get("phase") != "prefill":
            dt0 = pf.ts if pf is not None else (
                t0 if t0 is not None else toks[0].ts)
            spans.append({"name": "decode", **anchor, "t0": dt0,
                          "t1": max(dt0, toks[-1].ts)})
        merged.extend(
            Event(e.ts, e.kind, e.rid,
                  {**e.data, "src": "replica",
                   "replica": meta["replica"], "tier": meta["tier"],
                   "hop": meta["hop"]})
            for e in evs)

    # router-side spans: queue waits (submit→dispatch, handoff→dispatch,
    # replica-loss→re-dispatch) and the handoff export itself
    mark = next((e.ts for e in r_evs if e.kind == "submit"), None)
    for e in r_evs:
        if e.kind == "dispatched":
            if mark is not None:
                spans.append({"name": "queue", "t0": mark,
                              "t1": max(mark, e.ts)})
            mark = None
        elif e.kind == "handoff":
            sec = float(e.data.get("seconds") or 0.0)
            spans.append({"name": "handoff",
                          "from": e.data.get("from"),
                          "outcome": e.data.get("outcome"),
                          "tier": "prefill",
                          "t0": e.ts - sec, "t1": e.ts})
            mark = e.ts
        elif e.kind == "failover":
            # the wait began when the lost replica stopped progressing;
            # its captured hop's last event is the best estimate we have
            lost_t1 = hop_close.get(int(e.data.get("from", -1)))
            mark = min(lost_t1, e.ts) if lost_t1 is not None else e.ts

    # terminal-last tiebreak: clamped replica events sharing the
    # terminal's timestamp must sort BEFORE it, so `complete()` (and
    # the SLO outcome derivation) always sees the terminal event last
    merged.sort(key=lambda e: (
        e.ts, 1 if (e.kind in TERMINAL_KINDS
                    and e.data.get("src") == "router") else 0))
    spans.sort(key=lambda s: (s["t0"], s["t1"]))
    return StitchedTrace(rid, merged, spans, out_hops)


# ---------------------------------------------------------------------------
# fleet-wide Perfetto export
# ---------------------------------------------------------------------------

_ROUTER_QUEUE_TID = 0


def router_lane_events(events: Iterable[Event], pid: int = 0,
                       base: Optional[float] = None,
                       process_name: str = "fleet router"
                       ) -> List[dict]:
    """Render ROUTER-side lifecycle events as trace_event lanes: a
    queue lane of wait spans plus one lane per replica holding each
    request's dispatch-to-resolution span, with
    failover/hedge/handoff/autoscale instants marked. The router
    vocabulary differs from the engine's (no slots), hence the
    dedicated renderer."""
    evs = sorted((_as_event(e) for e in events), key=lambda e: e.ts)
    if base is None:
        base = evs[0].ts if evs else 0.0
    us = lambda t: round((t - base) * 1e6, 3)      # noqa: E731
    lanes: Dict[int, str] = {_ROUTER_QUEUE_TID: "queue"}
    open_span: Dict[int, tuple] = {}
    out: List[dict] = []

    def close(rid: int, end_ts: float, status: str) -> None:
        t0, tid, label = open_span.pop(rid)
        out.append({"name": label, "ph": "X", "pid": pid, "tid": tid,
                    "ts": us(t0),
                    "dur": max(0.0, round((end_ts - t0) * 1e6, 3)),
                    "args": {"rid": rid, "status": status}})

    for e in evs:
        rid = e.rid
        if e.kind == "submit":
            open_span[rid] = (e.ts, _ROUTER_QUEUE_TID, f"r{rid} wait")
        elif e.kind == "dispatched":
            if rid in open_span:
                close(rid, e.ts, "dispatched")
            rep = int(e.data.get("replica", -1))
            tid = rep + 1
            lanes.setdefault(tid, f"replica {rep}")
            hop = e.data.get("hop")
            open_span[rid] = (
                e.ts, tid,
                f"r{rid} hop{'' if hop is None else ' ' + str(hop)}")
        elif e.kind == "handoff":
            if rid in open_span:
                close(rid, e.ts, f"handoff_{e.data.get('outcome')}")
            out.append({"name": f"handoff r{rid}", "ph": "i",
                        "pid": pid, "tid": _ROUTER_QUEUE_TID,
                        "ts": us(e.ts), "s": "t",
                        "args": {"rid": rid, **e.data}})
            open_span[rid] = (e.ts, _ROUTER_QUEUE_TID, f"r{rid} wait")
        elif e.kind in ("failover", "hedge", "autoscale", "queued"):
            tid = (open_span[rid][1] if rid in open_span
                   else _ROUTER_QUEUE_TID)
            out.append({"name": f"{e.kind} r{rid}", "ph": "i",
                        "pid": pid, "tid": tid, "ts": us(e.ts),
                        "s": "t", "args": {"rid": rid, **e.data}})
        elif e.kind in TERMINAL_KINDS:
            if rid in open_span:
                close(rid, e.ts, e.kind)
    if evs:
        for rid in list(open_span):
            close(rid, evs[-1].ts, "running")

    meta: List[dict] = [{"name": "process_name", "ph": "M", "pid": pid,
                         "tid": 0, "args": {"name": process_name}},
                        {"name": "process_sort_index", "ph": "M",
                         "pid": pid, "tid": 0,
                         "args": {"sort_index": pid}}]
    for tid in sorted(lanes):
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": lanes[tid]}})
        meta.append({"name": "thread_sort_index", "ph": "M",
                     "pid": pid, "tid": tid,
                     "args": {"sort_index": tid}})
    return meta + out


def fleet_timeline_json(groups: List[dict]) -> dict:
    """The fleet-wide Perfetto export: one process lane group per
    entry in ``groups``, all re-based to one shared t=0.

    Each group::

        {"pid": int, "name": str, "events": [Event, ...],
         "router": bool,            # router vocabulary vs engine's
         "num_slots": int|None}     # engine groups: slot-lane count
    """
    all_ts = [e.ts for g in groups for e in g.get("events", ())]
    if not all_ts:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = min(all_ts)
    out: List[dict] = []
    for g in groups:
        evs = g.get("events") or ()
        if not evs:
            continue
        if g.get("router"):
            out.extend(router_lane_events(
                evs, pid=int(g.get("pid", 0)), base=base,
                process_name=g.get("name", "fleet router")))
        else:
            out.extend(trace_events(
                list(evs), num_slots=g.get("num_slots"),
                pid=int(g.get("pid", 0)),
                process_name=g.get("name", "replica"), base=base))
    return {"traceEvents": out, "displayTimeUnit": "ms"}
