"""Training UI server: browser dashboard over a StatsStorage.

Parity with the reference's Play-framework UI (reference:
deeplearning4j-ui-parent/deeplearning4j-play/.../PlayUIServer.java,
module/train/TrainModule.java — score chart, layer parameter/update
stats, system tab; remote-stats receiver endpoint). Play + SBE are
replaced by a stdlib ThreadingHTTPServer serving one self-contained
HTML page (inline JS polling JSON endpoints) — no web framework, no
codegen, same dashboard capabilities.

Endpoints:
  GET  /                      dashboard HTML
  GET  /train/sessions        list of session ids
  GET  /train/overview?sid=   score series + iteration timings
  GET  /train/model?sid=      per-parameter norms/histograms (latest)
  GET  /train/system?sid=     static hardware/model info
  POST /remote/receive        remote StatsStorageRouter records
  POST /tsne/upload           t-SNE coords (+labels) (reference: TsneModule)
  GET  /tsne                  scatter viewer HTML
  GET  /tsne/coords           uploaded coords JSON
  GET  /activations           conv activation grids captured by
                              ConvolutionalIterationListener
                              (reference: ActivationsModule)
  GET  /flow                  layer flow graph written by
                              FlowIterationListener (reference: FlowModule)
  GET  /metrics               Prometheus text exposition of the
                              registry mounted via attach_metrics()
  GET  /metrics.json          same registry as a JSON snapshot
  GET  /healthz, /readyz      pluggable health/readiness probes
                              (observability.export.probe_response)
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.ui.storage import (InMemoryStatsStorage,
                                           Persistable, StatsStorage)

_TSNE_PAGE = """<!DOCTYPE html>
<html><head><title>t-SNE viewer</title></head>
<body><h1>t-SNE</h1>
<svg id="plot" width="700" height="700" style="border:1px solid #ccc">
</svg>
<script>
fetch('/tsne/coords').then(r => r.json()).then(d => {
  const svg = document.getElementById('plot'), W = 700, pad = 20;
  const NS = 'http://www.w3.org/2000/svg';
  const xs = d.coords.map(c => c[0]), ys = d.coords.map(c => c[1]);
  if (!xs.length) return;
  const xmin = Math.min(...xs), xmax = Math.max(...xs),
        ymin = Math.min(...ys), ymax = Math.max(...ys);
  const sx = x => pad + (W - 2*pad) * (x - xmin) / ((xmax - xmin) || 1);
  const sy = y => pad + (W - 2*pad) * (y - ymin) / ((ymax - ymin) || 1);
  d.coords.forEach((c, i) => {
    const dot = document.createElementNS(NS, 'circle');
    dot.setAttribute('cx', sx(c[0]));
    dot.setAttribute('cy', sy(c[1]));
    dot.setAttribute('r', 3);
    svg.appendChild(dot);
    if (d.labels[i]) {
      const t = document.createElementNS(NS, 'text');
      t.setAttribute('x', sx(c[0]) + 4);
      t.setAttribute('y', sy(c[1]));
      t.setAttribute('font-size', 9);
      t.textContent = String(d.labels[i]);  // text node: no markup
      svg.appendChild(t);
    }
  });
});
</script></body></html>
"""

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu training UI</title>
<style>
 body { font-family: sans-serif; margin: 2em; }
 .chart { border: 1px solid #ccc; margin-bottom: 1em; }
 h2 { margin: 0.3em 0; }
 pre { background: #f6f6f6; padding: 0.6em; }
</style></head>
<body>
<h1>Training dashboard</h1>
<div>Session: <select id="session"></select></div>
<h2>Score vs iteration</h2>
<svg id="score" class="chart" width="800" height="240"></svg>
<h2>Parameter L2 norms</h2>
<pre id="params"></pre>
<h2>System</h2>
<pre id="system"></pre>
<script>
async function j(u) { const r = await fetch(u); return r.json(); }
function drawScore(svg, xs, ys) {
  svg.innerHTML = '';
  if (!xs.length) return;
  const W = 800, H = 240, P = 30;
  const xmax = Math.max(...xs), ymin = Math.min(...ys),
        ymax = Math.max(...ys) || 1;
  const px = x => P + (W - 2*P) * (xmax ? x / xmax : 0);
  const py = y => H - P - (H - 2*P) * ((y - ymin) / ((ymax - ymin) || 1));
  let d = '';
  xs.forEach((x, i) => { d += (i ? 'L' : 'M') + px(x) + ',' + py(ys[i]); });
  svg.innerHTML = '<path d="' + d +
    '" fill="none" stroke="#36c" stroke-width="1.5"/>' +
    '<text x="4" y="14">' + ymax.toPrecision(4) + '</text>' +
    '<text x="4" y="' + (H-8) + '">' + ymin.toPrecision(4) + '</text>';
}
async function refresh() {
  const sel = document.getElementById('session');
  const sessions = await j('/train/sessions');
  if (sel.options.length !== sessions.length) {
    sel.innerHTML = sessions.map(s =>
      '<option value="' + s + '">' + s + '</option>').join('');
  }
  const sid = sel.value || sessions[0];
  if (!sid) return;
  const ov = await j('/train/overview?sid=' + sid);
  drawScore(document.getElementById('score'), ov.iterations, ov.scores);
  const model = await j('/train/model?sid=' + sid);
  document.getElementById('params').textContent =
    JSON.stringify(model, null, 1);
  const sys = await j('/train/system?sid=' + sid);
  document.getElementById('system').textContent =
    JSON.stringify(sys, null, 1);
}
setInterval(refresh, 2000); refresh();
</script></body></html>
"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4jtpu-ui/1.0"
    storage: StatsStorage = None  # injected
    tsne_data = None              # {"coords": [...], "labels": [...]}
    remote_enabled = True         # --no-remote turns off /remote/receive
    activations_dir = None        # Path written by Conv listener
    flow_path = None              # Path written by Flow listener
    metrics_registry = None       # attach_metrics() mounts /metrics
    health_fn = None              # pluggable /healthz callable
    ready_fn = None               # pluggable /readyz callable

    def log_message(self, *args) -> None:  # silence request logging
        pass

    def _json(self, obj, code: int = 200) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _html(self, page: str) -> None:
        body = page.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _observability(self, path: str) -> None:
        """Metrics/health endpoints mounted by attach_metrics — the
        dashboard port doubles as the scrape target. Class-attribute
        access so plain-function callables never descriptor-bind."""
        from deeplearning4j_tpu.observability.export import (
            CONTENT_TYPE_LATEST, json_snapshot, probe_response,
            prometheus_text)
        cls = type(self)
        if cls.metrics_registry is None and path in ("/metrics",
                                                     "/metrics.json"):
            self._json({"error": "no metrics registry attached"}, 404)
            return
        if path == "/metrics":
            body = prometheus_text(cls.metrics_registry).encode()
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE_LATEST)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/metrics.json":
            self._json(json_snapshot(cls.metrics_registry))
        elif path == "/healthz":
            code, body = probe_response(cls.health_fn)
            self._json(body, code)
        else:                                    # /readyz
            code, body = probe_response(cls.ready_fn or cls.health_fn)
            self._json(body, code)

    @classmethod
    def set_tsne(cls, coords, labels=None) -> None:
        """The one normalizer for t-SNE uploads (HTTP and Python API)."""
        coords = [[float(v) for v in c] for c in coords]
        cls.tsne_data = {"coords": coords,
                         "labels": [str(l) for l in labels]
                         if labels else [""] * len(coords)}

    def _first_worker(self, sid: str) -> Optional[str]:
        workers = self.storage.list_worker_ids_for_session(sid)
        return workers[0] if workers else None

    def do_GET(self) -> None:
        url = urlparse(self.path)
        q = {k: v[0] for k, v in parse_qs(url.query).items()}
        if url.path in ("/", "/train", "/train/overview.html"):
            self._html(_PAGE)
            return
        if url.path in ("/metrics", "/metrics.json", "/healthz",
                        "/readyz"):
            self._observability(url.path)
            return
        if url.path == "/train/sessions":
            self._json(self.storage.list_session_ids())
            return
        if url.path == "/tsne":
            self._html(_TSNE_PAGE)
            return
        if url.path == "/tsne/coords":
            self._json(type(self).tsne_data
                       or {"coords": [], "labels": []})
            return
        if url.path == "/activations":
            d = type(self).activations_dir
            if d is None:
                self._json({"grids": []})
                return
            import numpy as np
            name = q.get("name")
            if name:
                p = d / name
                if not p.resolve().is_relative_to(d.resolve()) \
                        or not p.exists():
                    self._json({"error": "not found"}, 404)
                    return
                self._json({"name": name,
                            "grid": np.load(p).tolist()})
                return
            self._json({"grids": sorted(p.name for p in d.glob("*.npy"))})
            return
        if url.path == "/flow":
            p = type(self).flow_path
            if p is None or not p.exists():
                self._json({"layers": []})
                return
            self._json(json.loads(p.read_text()))
            return
        sid = q.get("sid", "")
        if url.path == "/train/overview":
            out = {"iterations": [], "scores": [], "durations": []}
            for wid in self.storage.list_worker_ids_for_session(sid):
                for u in self.storage.get_all_updates_after(
                        sid, "Update", wid, -1.0):
                    out["iterations"].append(u.get("iteration", 0))
                    out["scores"].append(u.get("score", 0.0))
                    out["durations"].append(
                        u.get("iteration_duration_s", 0.0))
            self._json(out)
            return
        if url.path == "/train/model":
            wid = self._first_worker(sid)
            latest = self.storage.get_latest_update(sid, "Update", wid) \
                if wid else None
            self._json((latest or {}).get("parameters", {}))
            return
        if url.path == "/train/system":
            wid = self._first_worker(sid)
            static = self.storage.get_static_info(sid, "StaticInfo", wid) \
                if wid else None
            self._json(static or {})
            return
        self._json({"error": "not found"}, 404)

    def do_POST(self) -> None:
        path = urlparse(self.path).path
        if path == "/tsne/upload":
            length = int(self.headers.get("Content-Length", 0))
            obj = json.loads(self.rfile.read(length) or b"{}")
            coords = obj.get("coords", [])
            type(self).set_tsne(coords, obj.get("labels"))
            self._json({"ok": True, "n": len(coords)})
            return
        if path != "/remote/receive":
            self._json({"error": "not found"}, 404)
            return
        if not type(self).remote_enabled:
            self._json({"error": "remote receiver disabled"}, 403)
            return
        length = int(self.headers.get("Content-Length", 0))
        obj = json.loads(self.rfile.read(length) or b"{}")
        kind = obj.pop("_kind", "update")
        record = Persistable(obj)
        if kind == "static":
            self.storage.put_static_info(record)
        elif kind == "meta":
            self.storage.put_storage_metadata(record)
        else:
            self.storage.put_update(record)
        self._json({"ok": True})


class UIServer:
    """Reference: UIServer.getInstance() / PlayUIServer — singleton HTTP
    server; attach(statsStorage) to make its sessions visible."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000):
        self.port = port
        self.storage: StatsStorage = InMemoryStatsStorage()
        handler = type("BoundHandler", (_Handler,),
                       {"storage": self.storage})
        self._handler = handler
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    @classmethod
    def get_instance(cls, port: int = 9000) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer(port)
        return cls._instance

    def attach_activations_dir(self, path) -> None:
        """Serve ConvolutionalIterationListener grids at /activations
        (reference: ActivationsModule over ConvolutionalIterationListener
        output)."""
        from pathlib import Path
        self._handler.activations_dir = Path(path)

    def attach_flow(self, path) -> None:
        """Serve FlowIterationListener JSON at /flow (reference:
        FlowModule)."""
        from pathlib import Path
        self._handler.flow_path = Path(path)

    def upload_tsne(self, coords, labels=None) -> None:
        """Publish t-SNE coordinates to the /tsne viewer (reference:
        TsneModule upload)."""
        self._handler.set_tsne(coords, labels)

    def attach_metrics(self, registry=None, health=None,
                       ready=None) -> None:
        """Mount /metrics, /metrics.json, /healthz, /readyz on this
        server over `registry` (default: the process default
        observability registry) — one port serves charts AND scrapes.
        `health`/`ready` follow observability.export.probe_response
        semantics (e.g. pass InferenceEngine.health / .ready)."""
        from deeplearning4j_tpu.observability.metrics import \
            default_registry
        self._handler.metrics_registry = (
            registry if registry is not None else default_registry())
        self._handler.health_fn = health
        self._handler.ready_fn = ready

    def attach(self, storage: StatsStorage) -> None:
        """Mirror records from `storage` into the server's own store
        (reference: UIServer.attach)."""
        def mirror(kind: str, record: Persistable) -> None:
            if kind == "static":
                self.storage.put_static_info(record)
            elif kind == "meta":
                self.storage.put_storage_metadata(record)
            else:
                self.storage.put_update(record)
        storage.register_stats_storage_listener(mirror)

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if UIServer._instance is self:
            UIServer._instance = None


def main(argv=None) -> None:
    """CLI entry (reference: PlayUIServer's JCommander flags —
    uiPort / enableRemote): `python -m deeplearning4j_tpu.ui.server
    --port 9000 [--no-remote]`."""
    import argparse
    import signal

    ap = argparse.ArgumentParser(description="deeplearning4j_tpu UI server")
    ap.add_argument("--port", type=int, default=9000,
                    help="HTTP port (0 = ephemeral)")
    ap.add_argument("--no-remote", action="store_true",
                    help="reject POST /remote/receive (reference: "
                         "PlayUIServer enableRemote off by default)")
    ap.add_argument("--activations-dir", default=None,
                    help="serve ConvolutionalIterationListener grids")
    ap.add_argument("--flow", default=None,
                    help="serve FlowIterationListener JSON")
    ap.add_argument("--metrics", action="store_true",
                    help="mount /metrics (+healthz/readyz) over the "
                         "process default observability registry")
    args = ap.parse_args(argv)
    server = UIServer(port=args.port)
    if args.no_remote:
        server._handler.remote_enabled = False
    if args.metrics:
        server.attach_metrics()
    if args.activations_dir:
        server.attach_activations_dir(args.activations_dir)
    if args.flow:
        server.attach_flow(args.flow)
    remote = ("disabled" if args.no_remote
              else "POST /remote/receive accepts remote stats")
    print(f"UI server listening on {server.url} ({remote})")
    # block the signals BEFORE sigwait (POSIX: sigwait on unblocked
    # signals is undefined; unblocked SIGTERM would just kill us and
    # skip the clean stop())
    signal.pthread_sigmask(signal.SIG_BLOCK,
                           {signal.SIGINT, signal.SIGTERM})
    signal.sigwait({signal.SIGINT, signal.SIGTERM})
    server.stop()


if __name__ == "__main__":
    main()
