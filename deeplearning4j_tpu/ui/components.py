"""Server-renderable UI component model.

Parity with the reference's ui-components module (reference:
deeplearning4j-ui-parent/deeplearning4j-ui-components — api/Component,
api/Style, components/chart/{Chart,ChartLine,ChartScatter,
ChartHistogram,ChartHorizontalBar,ChartStackedArea,ChartTimeline},
components/component/ComponentDiv, components/decorator/
DecoratorAccordion, components/table/ComponentTable,
components/text/ComponentText, standalone/StaticPageUtil). Components
serialize to JSON tagged with ``componentType`` for a front end;
``StaticPageUtil.render_to_html`` emits a self-contained page. The
reference ships a jQuery/flot front end; here charts render to inline
SVG so the exported page has zero external dependencies.
"""
from __future__ import annotations

import json
import html as _html
from typing import Any, Dict, List, Optional, Sequence, Tuple


# ------------------------------------------------------------------- styles
class Style:
    """Base style (reference: api/Style.java — width/height/margins with
    LengthUnit; here plain CSS-ish units)."""

    def __init__(self, *, width: Optional[float] = None,
                 height: Optional[float] = None,
                 width_unit: str = "px", height_unit: str = "px",
                 margin_top: float = 0, margin_bottom: float = 0,
                 margin_left: float = 0, margin_right: float = 0,
                 background_color: Optional[str] = None):
        self.width = width
        self.height = height
        self.width_unit = width_unit
        self.height_unit = height_unit
        self.margin_top = margin_top
        self.margin_bottom = margin_bottom
        self.margin_left = margin_left
        self.margin_right = margin_right
        self.background_color = background_color

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items() if v is not None}


class StyleChart(Style):
    """reference: chart/style/StyleChart.java"""

    def __init__(self, *, stroke_width: float = 1.0,
                 point_size: float = 3.0,
                 series_colors: Optional[List[str]] = None,
                 axis_stroke_width: float = 1.0,
                 title_font_size: float = 14.0, **kw):
        super().__init__(**kw)
        self.stroke_width = stroke_width
        self.point_size = point_size
        self.series_colors = series_colors or [
            "#2969b0", "#d0542c", "#3b8746", "#8d5bb8", "#b5a03c"]
        self.axis_stroke_width = axis_stroke_width
        self.title_font_size = title_font_size


class StyleTable(Style):
    """reference: table/style/StyleTable.java"""

    def __init__(self, *, border_width: float = 1.0,
                 header_color: str = "#dddddd",
                 column_widths: Optional[List[float]] = None,
                 whitespace_mode: str = "normal", **kw):
        super().__init__(**kw)
        self.border_width = border_width
        self.header_color = header_color
        self.column_widths = column_widths
        self.whitespace_mode = whitespace_mode


class StyleText(Style):
    """reference: text/style/StyleText.java"""

    def __init__(self, *, font: str = "sans-serif",
                 font_size: float = 12.0, underline: bool = False,
                 color: str = "#000000", **kw):
        super().__init__(**kw)
        self.font = font
        self.font_size = font_size
        self.underline = underline
        self.color = color


class StyleDiv(Style):
    """reference: component/style/StyleDiv.java (floatValue)."""

    def __init__(self, *, float_value: Optional[str] = None, **kw):
        super().__init__(**kw)
        self.float_value = float_value


# --------------------------------------------------------------- components
_COMPONENT_REGISTRY: Dict[str, type] = {}


def _register(cls):
    _COMPONENT_REGISTRY[cls.__name__] = cls
    return cls


class Component:
    """reference: api/Component.java — every component carries a type tag
    for polymorphic JSON deserialization."""

    def __init__(self, style: Optional[Style] = None):
        self.style = style

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"componentType": type(self).__name__}
        if self.style is not None:
            d["style"] = self.style.to_dict()
        d.update(self._fields())
        return d

    def _fields(self) -> Dict[str, Any]:
        return {}

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @staticmethod
    def from_json(s: str) -> "Component":
        return _component_from_dict(json.loads(s))

    # minimal inline-SVG/HTML rendering (standalone static pages)
    def render_html(self) -> str:
        return f"<pre>{_html.escape(self.to_json())}</pre>"


class _RawStyle(Style):
    """Deserialized style: keeps the exact dict so a round trip is
    lossless even though the concrete Style subclass isn't tagged."""

    def __init__(self, d: Dict[str, Any]):
        self._d = dict(d)

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._d)


def _component_from_dict(d: Dict[str, Any]) -> Component:
    kind = d.get("componentType")
    cls = _COMPONENT_REGISTRY.get(kind)
    if cls is None:
        raise ValueError(f"Unknown componentType '{kind}'")
    comp = cls._from_fields(d)
    if "style" in d and comp.style is None:
        comp.style = _RawStyle(d["style"])
    return comp


@_register
class ComponentText(Component):
    """reference: text/ComponentText.java"""

    def __init__(self, text: str, style: Optional[StyleText] = None):
        super().__init__(style)
        self.text = text

    def _fields(self):
        return {"text": self.text}

    @classmethod
    def _from_fields(cls, d):
        return cls(d["text"])

    def render_html(self):
        st = self.style
        css = ""
        if isinstance(st, StyleText):
            css = (f"font-family:{st.font};font-size:{st.font_size}px;"
                   f"color:{st.color};"
                   + ("text-decoration:underline;" if st.underline else ""))
        return f'<p style="{css}">{_html.escape(self.text)}</p>'


@_register
class ComponentTable(Component):
    """reference: table/ComponentTable.java (header + content rows)."""

    def __init__(self, header: Optional[Sequence[str]] = None,
                 content: Optional[Sequence[Sequence[Any]]] = None,
                 style: Optional[StyleTable] = None):
        super().__init__(style)
        self.header = list(header) if header else None
        self.content = [list(r) for r in content] if content else []

    def _fields(self):
        return {"header": self.header, "content": self.content}

    @classmethod
    def _from_fields(cls, d):
        return cls(d.get("header"), d.get("content"))

    def render_html(self):
        rows = []
        if self.header:
            cells = "".join(f"<th>{_html.escape(str(h))}</th>"
                            for h in self.header)
            rows.append(f"<tr>{cells}</tr>")
        for r in self.content:
            cells = "".join(f"<td>{_html.escape(str(c))}</td>" for c in r)
            rows.append(f"<tr>{cells}</tr>")
        return ('<table border="1" style="border-collapse:collapse">'
                + "".join(rows) + "</table>")


@_register
class ComponentDiv(Component):
    """reference: component/ComponentDiv.java — container of children."""

    def __init__(self, style: Optional[StyleDiv] = None,
                 *children: Component):
        super().__init__(style)
        self.children = list(children)

    def _fields(self):
        return {"components": [c.to_dict() for c in self.children]}

    @classmethod
    def _from_fields(cls, d):
        kids = [_component_from_dict(c) for c in d.get("components", [])]
        return cls(None, *kids)

    def render_html(self):
        return ("<div>" + "".join(c.render_html() for c in self.children)
                + "</div>")


@_register
class DecoratorAccordion(Component):
    """reference: decorator/DecoratorAccordion.java — collapsible section
    wrapping inner components."""

    def __init__(self, title: str = "", default_collapsed: bool = False,
                 *children: Component, style: Optional[Style] = None):
        super().__init__(style)
        self.title = title
        self.default_collapsed = default_collapsed
        self.children = list(children)

    def _fields(self):
        return {"title": self.title,
                "defaultCollapsed": self.default_collapsed,
                "components": [c.to_dict() for c in self.children]}

    @classmethod
    def _from_fields(cls, d):
        kids = [_component_from_dict(c) for c in d.get("components", [])]
        return cls(d.get("title", ""), d.get("defaultCollapsed", False),
                   *kids)

    def render_html(self):
        inner = "".join(c.render_html() for c in self.children)
        open_attr = "" if self.default_collapsed else " open"
        return (f"<details{open_attr}><summary>"
                f"{_html.escape(self.title)}</summary>{inner}</details>")


class Chart(Component):
    """reference: chart/Chart.java — title + axis bounds."""

    def __init__(self, title: str = "", style: Optional[StyleChart] = None,
                 set_x_min: Optional[float] = None,
                 set_x_max: Optional[float] = None,
                 set_y_min: Optional[float] = None,
                 set_y_max: Optional[float] = None):
        super().__init__(style)
        self.title = title
        self.set_x_min = set_x_min
        self.set_x_max = set_x_max
        self.set_y_min = set_y_min
        self.set_y_max = set_y_max

    def _axis_fields(self):
        return {"title": self.title, "xMin": self.set_x_min,
                "xMax": self.set_x_max, "yMin": self.set_y_min,
                "yMax": self.set_y_max}

    # shared SVG scaffolding for xy-series charts
    def _svg(self, series: List[Tuple[str, List[float], List[float]]],
             *, mode: str = "line", w: int = 480, h: int = 280) -> str:
        colors = (self.style.series_colors if isinstance(self.style,
                                                         StyleChart)
                  else StyleChart().series_colors)
        all_x = [v for _, xs, _ in series for v in xs] or [0.0, 1.0]
        all_y = [v for _, _, ys in series for v in ys] or [0.0, 1.0]
        x0 = self.set_x_min if self.set_x_min is not None else min(all_x)
        x1 = self.set_x_max if self.set_x_max is not None else max(all_x)
        y0 = self.set_y_min if self.set_y_min is not None else min(all_y)
        y1 = self.set_y_max if self.set_y_max is not None else max(all_y)
        xr = (x1 - x0) or 1.0
        yr = (y1 - y0) or 1.0
        pad = 30

        def sx(v):
            return pad + (v - x0) / xr * (w - 2 * pad)

        def sy(v):
            return h - pad - (v - y0) / yr * (h - 2 * pad)

        parts = [f'<svg width="{w}" height="{h}" '
                 'xmlns="http://www.w3.org/2000/svg">',
                 f'<text x="{w//2}" y="16" text-anchor="middle">'
                 f'{_html.escape(self.title)}</text>',
                 f'<line x1="{pad}" y1="{h-pad}" x2="{w-pad}" '
                 f'y2="{h-pad}" stroke="black"/>',
                 f'<line x1="{pad}" y1="{pad}" x2="{pad}" y2="{h-pad}" '
                 'stroke="black"/>']
        for i, (name, xs, ys) in enumerate(series):
            color = colors[i % len(colors)]
            if mode == "line" and xs:
                pts = " ".join(f"{sx(x):.1f},{sy(y):.1f}"
                               for x, y in zip(xs, ys))
                parts.append(f'<polyline fill="none" stroke="{color}" '
                             f'points="{pts}"/>')
            elif mode == "scatter":
                for x, y in zip(xs, ys):
                    parts.append(f'<circle cx="{sx(x):.1f}" '
                                 f'cy="{sy(y):.1f}" r="3" '
                                 f'fill="{color}"/>')
        parts.append("</svg>")
        return "".join(parts)


@_register
class ChartLine(Chart):
    """reference: chart/ChartLine.java — named x/y series."""

    def __init__(self, title: str = "", style: Optional[StyleChart] = None,
                 **kw):
        super().__init__(title, style, **kw)
        self.series: List[Tuple[str, List[float], List[float]]] = []

    def add_series(self, name: str, x: Sequence[float],
                   y: Sequence[float]) -> "ChartLine":
        if len(x) != len(y):
            raise ValueError(f"series '{name}': len(x)={len(x)} != "
                             f"len(y)={len(y)}")
        self.series.append((name, [float(v) for v in x],
                            [float(v) for v in y]))
        return self

    def _fields(self):
        d = self._axis_fields()
        d.update({"seriesNames": [s[0] for s in self.series],
                  "x": [s[1] for s in self.series],
                  "y": [s[2] for s in self.series]})
        return d

    @classmethod
    def _from_fields(cls, d):
        c = cls(d.get("title", ""))
        for name, xs, ys in zip(d.get("seriesNames", []), d.get("x", []),
                                d.get("y", [])):
            c.add_series(name, xs, ys)
        return c

    def render_html(self):
        return self._svg(self.series, mode="line")


@_register
class ChartScatter(ChartLine):
    """reference: chart/ChartScatter.java"""

    def render_html(self):
        return self._svg(self.series, mode="scatter")


@_register
class ChartHistogram(Chart):
    """reference: chart/ChartHistogram.java — (binLower, binUpper, count)
    triples."""

    def __init__(self, title: str = "", style: Optional[StyleChart] = None,
                 **kw):
        super().__init__(title, style, **kw)
        self.bins: List[Tuple[float, float, float]] = []

    def add_bin(self, lower: float, upper: float,
                y: float) -> "ChartHistogram":
        self.bins.append((float(lower), float(upper), float(y)))
        return self

    def _fields(self):
        d = self._axis_fields()
        d.update({"lowerBounds": [b[0] for b in self.bins],
                  "upperBounds": [b[1] for b in self.bins],
                  "yValues": [b[2] for b in self.bins]})
        return d

    @classmethod
    def _from_fields(cls, d):
        c = cls(d.get("title", ""))
        for lo, hi, y in zip(d.get("lowerBounds", []),
                             d.get("upperBounds", []),
                             d.get("yValues", [])):
            c.add_bin(lo, hi, y)
        return c

    def render_html(self):
        if not self.bins:
            return self._svg([])
        w, h, pad = 480, 280, 30
        x0 = min(b[0] for b in self.bins)
        x1 = max(b[1] for b in self.bins)
        ymax = max(b[2] for b in self.bins) or 1.0
        xr = (x1 - x0) or 1.0
        color = (self.style.series_colors[0]
                 if isinstance(self.style, StyleChart)
                 else StyleChart().series_colors[0])
        parts = [f'<svg width="{w}" height="{h}" '
                 'xmlns="http://www.w3.org/2000/svg">',
                 f'<text x="{w//2}" y="16" text-anchor="middle">'
                 f'{_html.escape(self.title)}</text>']
        for lo, hi, y in self.bins:
            bx = pad + (lo - x0) / xr * (w - 2 * pad)
            bw = max((hi - lo) / xr * (w - 2 * pad), 1.0)
            bh = y / ymax * (h - 2 * pad)
            parts.append(f'<rect x="{bx:.1f}" y="{h-pad-bh:.1f}" '
                         f'width="{bw:.1f}" height="{bh:.1f}" '
                         f'fill="{color}" stroke="white"/>')
        parts.append("</svg>")
        return "".join(parts)


@_register
class ChartHorizontalBar(Chart):
    """reference: chart/ChartHorizontalBar.java — named values."""

    def __init__(self, title: str = "", style: Optional[StyleChart] = None,
                 **kw):
        super().__init__(title, style, **kw)
        self.names: List[str] = []
        self.values: List[float] = []

    def add_value(self, name: str, value: float) -> "ChartHorizontalBar":
        self.names.append(name)
        self.values.append(float(value))
        return self

    def _fields(self):
        d = self._axis_fields()
        d.update({"names": self.names, "values": self.values})
        return d

    @classmethod
    def _from_fields(cls, d):
        c = cls(d.get("title", ""))
        for n, v in zip(d.get("names", []), d.get("values", [])):
            c.add_value(n, v)
        return c

    def render_html(self):
        w, row_h, pad = 480, 22, 100
        vmax = max(self.values, default=1.0) or 1.0
        color = StyleChart().series_colors[0]
        h = 30 + row_h * len(self.names)
        parts = [f'<svg width="{w}" height="{h}" '
                 'xmlns="http://www.w3.org/2000/svg">',
                 f'<text x="{w//2}" y="16" text-anchor="middle">'
                 f'{_html.escape(self.title)}</text>']
        for i, (n, v) in enumerate(zip(self.names, self.values)):
            y = 24 + i * row_h
            bw = max(v / vmax * (w - pad - 10), 0.0)
            parts.append(f'<text x="{pad-6}" y="{y+14}" '
                         f'text-anchor="end">{_html.escape(n)}</text>')
            parts.append(f'<rect x="{pad}" y="{y}" width="{bw:.1f}" '
                         f'height="{row_h-4}" fill="{color}"/>')
        parts.append("</svg>")
        return "".join(parts)


@_register
class ChartStackedArea(Chart):
    """reference: chart/ChartStackedArea.java — shared x, stacked y
    series."""

    def __init__(self, title: str = "", style: Optional[StyleChart] = None,
                 **kw):
        super().__init__(title, style, **kw)
        self.x: List[float] = []
        self.labels: List[str] = []
        self.ys: List[List[float]] = []

    def set_x_values(self, x: Sequence[float]) -> "ChartStackedArea":
        self.x = [float(v) for v in x]
        return self

    def add_series(self, name: str,
                   y: Sequence[float]) -> "ChartStackedArea":
        if self.x and len(y) != len(self.x):
            raise ValueError("series length != x length")
        self.labels.append(name)
        self.ys.append([float(v) for v in y])
        return self

    def _fields(self):
        d = self._axis_fields()
        d.update({"x": self.x, "labels": self.labels, "y": self.ys})
        return d

    @classmethod
    def _from_fields(cls, d):
        c = cls(d.get("title", ""))
        c.set_x_values(d.get("x", []))
        for n, ys in zip(d.get("labels", []), d.get("y", [])):
            c.add_series(n, ys)
        return c

    def render_html(self):
        # cumulative stacking, rendered as successive line series
        acc = [0.0] * len(self.x)
        series = []
        for name, ys in zip(self.labels, self.ys):
            acc = [a + y for a, y in zip(acc, ys)]
            series.append((name, self.x, list(acc)))
        return self._svg(series, mode="line")


@_register
class ChartTimeline(Chart):
    """reference: chart/ChartTimeline.java — lanes of (start, end,
    label, color) entries."""

    def __init__(self, title: str = "", style: Optional[StyleChart] = None,
                 **kw):
        super().__init__(title, style, **kw)
        self.lanes: List[Tuple[str, List[Dict[str, Any]]]] = []

    def add_lane(self, name: str,
                 entries: Sequence[Dict[str, Any]]) -> "ChartTimeline":
        """entries: dicts with startTimeMs, endTimeMs, optional
        entryLabel, color."""
        self.lanes.append((name, list(entries)))
        return self

    def _fields(self):
        d = self._axis_fields()
        d.update({"laneNames": [l[0] for l in self.lanes],
                  "laneData": [l[1] for l in self.lanes]})
        return d

    @classmethod
    def _from_fields(cls, d):
        c = cls(d.get("title", ""))
        for n, entries in zip(d.get("laneNames", []),
                              d.get("laneData", [])):
            c.add_lane(n, entries)
        return c

    def render_html(self):
        w, row_h, pad = 600, 26, 100
        times = [t for _, es in self.lanes
                 for e in es for t in (e["startTimeMs"], e["endTimeMs"])]
        t0, t1 = (min(times), max(times)) if times else (0.0, 1.0)
        tr = (t1 - t0) or 1.0
        h = 30 + row_h * len(self.lanes)
        parts = [f'<svg width="{w}" height="{h}" '
                 'xmlns="http://www.w3.org/2000/svg">',
                 f'<text x="{w//2}" y="16" text-anchor="middle">'
                 f'{_html.escape(self.title)}</text>']
        for i, (name, entries) in enumerate(self.lanes):
            y = 24 + i * row_h
            parts.append(f'<text x="{pad-6}" y="{y+16}" '
                         f'text-anchor="end">{_html.escape(name)}</text>')
            for e in entries:
                bx = pad + (e["startTimeMs"] - t0) / tr * (w - pad - 10)
                bw = max((e["endTimeMs"] - e["startTimeMs"]) / tr
                         * (w - pad - 10), 1.0)
                color = e.get("color", "#2969b0")
                parts.append(f'<rect x="{bx:.1f}" y="{y}" '
                             f'width="{bw:.1f}" height="{row_h-6}" '
                             f'fill="{color}"/>')
                label = e.get("entryLabel")
                if label:
                    parts.append(f'<text x="{bx+2:.1f}" y="{y+14}" '
                                 f'font-size="10">'
                                 f'{_html.escape(label)}</text>')
        parts.append("</svg>")
        return "".join(parts)


# ------------------------------------------------------------- static pages
class StaticPageUtil:
    """reference: standalone/StaticPageUtil.java — render components to a
    single self-contained HTML page."""

    @staticmethod
    def render_html(components: Sequence[Component],
                    title: str = "deeplearning4j_tpu report") -> str:
        body = "\n".join(c.render_html() for c in components)
        return ("<!DOCTYPE html><html><head><meta charset=\"utf-8\">"
                f"<title>{_html.escape(title)}</title></head>"
                f"<body>{body}</body></html>")

    @staticmethod
    def save_html(components: Sequence[Component], path: str,
                  title: str = "deeplearning4j_tpu report") -> None:
        with open(path, "w") as f:
            f.write(StaticPageUtil.render_html(components, title))
