"""Legacy visualization listeners.

Parity with the reference's deeplearning4j-ui module (reference:
deeplearning4j-ui-parent/deeplearning4j-ui/.../ConvolutionalIterationListener
(activation image grids) and FlowIterationListener (layer-flow view)).
The Play-rendering half lives in ui/server.py; these listeners capture
the underlying artifacts — per-layer activation snapshots and the layer
flow graph — to disk as .npy / .json for any front end to render.
"""
from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Optional

import numpy as np

from deeplearning4j_tpu.train.listeners import IterationListener


class ConvolutionalIterationListener(IterationListener):
    """Every `frequency` iterations, run the model's feed-forward on the
    last batch's first example and save each 4-D (conv) activation as an
    .npy grid (reference: ConvolutionalIterationListener activation
    image grids)."""

    def __init__(self, out_dir: str, frequency: int = 10):
        self.out_dir = Path(out_dir)
        self.out_dir.mkdir(parents=True, exist_ok=True)
        self.frequency = max(1, frequency)
        self.last_input: Optional[np.ndarray] = None

    def iteration_done(self, model, iteration: int, score: float) -> None:
        if iteration % self.frequency != 0 or self.last_input is None:
            return
        acts = model.feed_forward(self.last_input[:1])
        if isinstance(acts, dict):
            items = acts.items()
        else:
            items = ((f"layer_{i}", a) for i, a in enumerate(acts))
        for name, a in items:
            a = np.asarray(a)
            if a.ndim == 4:  # [1, H, W, C] → [C, H, W] grid source
                np.save(self.out_dir / f"iter{iteration}_{name}.npy",
                        np.transpose(a[0], (2, 0, 1)))

    def record_input(self, x) -> None:
        self.last_input = np.asarray(x)


class FlowIterationListener(IterationListener):
    """Write the layer-flow graph + per-layer score info as JSON
    (reference: FlowIterationListener layer-flow viz)."""

    def __init__(self, out_path: str, frequency: int = 10):
        self.out_path = out_path
        self.frequency = max(1, frequency)

    def iteration_done(self, model, iteration: int, score: float) -> None:
        if iteration % self.frequency != 0:
            return
        layers = []
        conf = getattr(model, "conf", None)
        if hasattr(model, "layer_names"):  # MultiLayerNetwork
            for i, name in enumerate(model.layer_names):
                layers.append({"name": name,
                               "type": type(model.layers[i]).__name__,
                               "inputs": [model.layer_names[i - 1]]
                               if i else []})
        elif conf is not None and hasattr(conf, "vertices"):
            for name, spec in conf.vertices.items():
                layers.append({"name": name,
                               "type": type(spec.vertex).__name__,
                               "inputs": list(spec.inputs)})
        with open(self.out_path, "w") as f:
            json.dump({"iteration": iteration, "score": float(score),
                       "layers": layers}, f, indent=1)
