"""Training UI / stats pipeline (reference: deeplearning4j-ui-parent)."""
from deeplearning4j_tpu.ui.storage import (
    Persistable, StatsStorage, StatsStorageRouter, InMemoryStatsStorage,
    FileStatsStorage, SqliteStatsStorage, RemoteUIStatsStorageRouter)
from deeplearning4j_tpu.ui.stats import StatsListener
from deeplearning4j_tpu.ui.server import UIServer
from deeplearning4j_tpu.ui.components import (
    Component, ComponentText, ComponentTable, ComponentDiv,
    DecoratorAccordion, ChartLine, ChartScatter, ChartHistogram,
    ChartHorizontalBar, ChartStackedArea, ChartTimeline, Style,
    StyleChart, StyleTable, StyleText, StyleDiv, StaticPageUtil)

__all__ = [
    "Persistable", "StatsStorage", "StatsStorageRouter",
    "InMemoryStatsStorage", "FileStatsStorage", "SqliteStatsStorage",
    "RemoteUIStatsStorageRouter", "StatsListener", "UIServer",
    "Component", "ComponentText", "ComponentTable", "ComponentDiv",
    "DecoratorAccordion", "ChartLine", "ChartScatter", "ChartHistogram",
    "ChartHorizontalBar", "ChartStackedArea", "ChartTimeline", "Style",
    "StyleChart", "StyleTable", "StyleText", "StyleDiv",
    "StaticPageUtil",
]
