"""Training UI / stats pipeline (reference: deeplearning4j-ui-parent)."""
from deeplearning4j_tpu.ui.storage import (
    Persistable, StatsStorage, StatsStorageRouter, InMemoryStatsStorage,
    FileStatsStorage, SqliteStatsStorage, RemoteUIStatsStorageRouter)
from deeplearning4j_tpu.ui.stats import StatsListener
from deeplearning4j_tpu.ui.server import UIServer

__all__ = [
    "Persistable", "StatsStorage", "StatsStorageRouter",
    "InMemoryStatsStorage", "FileStatsStorage", "SqliteStatsStorage",
    "RemoteUIStatsStorageRouter", "StatsListener", "UIServer",
]
