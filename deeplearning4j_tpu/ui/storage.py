"""Stats storage: persistence + routing for training statistics.

Parity with the reference's storage API (reference:
deeplearning4j-core/.../api/storage/StatsStorage.java:30,
StatsStorageRouter.java, Persistable.java; backends in
deeplearning4j-ui-parent/deeplearning4j-ui-model/.../ui/storage/:
InMemoryStatsStorage, FileStatsStorage (MapDB), sqlite
J7FileStatsStorage; remote routing
api/storage/impl/RemoteUIStatsStorageRouter.java). Records are JSON
dicts instead of SBE-encoded byte blobs — the reference needed SBE for
compact wire framing to the Play server; a JSON-lines file and sqlite
cover the same durability/remote cases without generated codecs.

Key model (same as reference): records are addressed by
(session_id, type_id, worker_id, timestamp); static info once per
session/worker, updates many.
"""
from __future__ import annotations

import json
import os
import sqlite3
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple


class Persistable(dict):
    """One record: a JSON-serializable dict with addressing metadata
    (reference: api/storage/Persistable.java)."""

    @property
    def session_id(self) -> str:
        return self["session_id"]

    @property
    def type_id(self) -> str:
        return self["type_id"]

    @property
    def worker_id(self) -> str:
        return self["worker_id"]

    @property
    def timestamp(self) -> float:
        return self.get("timestamp", 0.0)


class StatsStorageRouter:
    """Write-side interface (reference: StatsStorageRouter.java)."""

    def put_static_info(self, record: Persistable) -> None:
        raise NotImplementedError

    def put_update(self, record: Persistable) -> None:
        raise NotImplementedError

    def put_storage_metadata(self, record: Persistable) -> None:
        raise NotImplementedError


class StatsStorage(StatsStorageRouter):
    """Read+write storage (reference: StatsStorage.java:30). Listeners
    get callbacks on new sessions/records (reference:
    StatsStorageListener)."""

    def __init__(self):
        self._listeners: List[Callable[[str, Persistable], None]] = []
        self._lock = threading.Lock()

    # -- write -------------------------------------------------------------
    def put_static_info(self, record: Persistable) -> None:
        self._store("static", record)
        self._notify("static", record)

    def put_update(self, record: Persistable) -> None:
        self._store("update", record)
        self._notify("update", record)

    def put_storage_metadata(self, record: Persistable) -> None:
        self._store("meta", record)
        self._notify("meta", record)

    def _store(self, kind: str, record: Persistable) -> None:
        raise NotImplementedError

    # -- read --------------------------------------------------------------
    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def list_type_ids_for_session(self, session_id: str) -> List[str]:
        raise NotImplementedError

    def list_worker_ids_for_session(self, session_id: str) -> List[str]:
        raise NotImplementedError

    def get_all_updates_after(self, session_id: str, type_id: str,
                              worker_id: str, timestamp: float
                              ) -> List[Persistable]:
        raise NotImplementedError

    def get_static_info(self, session_id: str, type_id: str,
                        worker_id: str) -> Optional[Persistable]:
        raise NotImplementedError

    def get_latest_update(self, session_id: str, type_id: str,
                          worker_id: str) -> Optional[Persistable]:
        ups = self.get_all_updates_after(session_id, type_id, worker_id,
                                         -1.0)
        return ups[-1] if ups else None

    # -- listeners ---------------------------------------------------------
    def register_stats_storage_listener(
            self, fn: Callable[[str, Persistable], None]) -> None:
        self._listeners.append(fn)

    def _notify(self, kind: str, record: Persistable) -> None:
        for fn in list(self._listeners):
            fn(kind, record)

    def close(self) -> None:
        pass


class InMemoryStatsStorage(StatsStorage):
    """Reference: ui/storage/InMemoryStatsStorage.java."""

    def __init__(self):
        super().__init__()
        self._static: Dict[Tuple[str, str, str], Persistable] = {}
        self._updates: Dict[Tuple[str, str, str], List[Persistable]] = {}
        self._meta: List[Persistable] = []

    def _store(self, kind: str, record: Persistable) -> None:
        key = (record.session_id, record.type_id, record.worker_id)
        with self._lock:
            if kind == "static":
                self._static[key] = record
            elif kind == "update":
                self._updates.setdefault(key, []).append(record)
            else:
                self._meta.append(record)

    def list_session_ids(self) -> List[str]:
        with self._lock:
            keys = set(self._static) | set(self._updates)
            return sorted({k[0] for k in keys})

    def list_type_ids_for_session(self, session_id: str) -> List[str]:
        with self._lock:
            keys = set(self._static) | set(self._updates)
            return sorted({k[1] for k in keys if k[0] == session_id})

    def list_worker_ids_for_session(self, session_id: str) -> List[str]:
        with self._lock:
            keys = set(self._static) | set(self._updates)
            return sorted({k[2] for k in keys if k[0] == session_id})

    def get_all_updates_after(self, session_id, type_id, worker_id,
                              timestamp) -> List[Persistable]:
        with self._lock:
            ups = self._updates.get((session_id, type_id, worker_id), [])
            return [u for u in ups if u.timestamp > timestamp]

    def get_static_info(self, session_id, type_id, worker_id):
        with self._lock:
            return self._static.get((session_id, type_id, worker_id))


class FileStatsStorage(StatsStorage):
    """JSON-lines file storage, durable across processes (reference:
    ui/storage/FileStatsStorage.java — MapDB there). Appends records;
    reloads on open."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._mem = InMemoryStatsStorage()
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    obj = json.loads(line)
                    self._mem._store(obj.pop("_kind"),
                                     Persistable(obj))
        self._fh = open(path, "a")

    def _store(self, kind: str, record: Persistable) -> None:
        self._mem._store(kind, record)
        with self._lock:
            self._fh.write(json.dumps({"_kind": kind, **record}) + "\n")
            self._fh.flush()

    def close(self) -> None:
        self._fh.close()

    # reads delegate
    def list_session_ids(self):
        return self._mem.list_session_ids()

    def list_type_ids_for_session(self, s):
        return self._mem.list_type_ids_for_session(s)

    def list_worker_ids_for_session(self, s):
        return self._mem.list_worker_ids_for_session(s)

    def get_all_updates_after(self, s, t, w, ts):
        return self._mem.get_all_updates_after(s, t, w, ts)

    def get_static_info(self, s, t, w):
        return self._mem.get_static_info(s, t, w)


class SqliteStatsStorage(StatsStorage):
    """SQLite-backed storage (reference: ui/storage/sqlite/
    J7FileStatsStorage.java)."""

    def __init__(self, path: str):
        super().__init__()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS records ("
            "kind TEXT, session_id TEXT, type_id TEXT, worker_id TEXT,"
            "timestamp REAL, payload TEXT)")
        self._conn.commit()

    def _store(self, kind: str, record: Persistable) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO records VALUES (?,?,?,?,?,?)",
                (kind, record.session_id, record.type_id, record.worker_id,
                 record.timestamp, json.dumps(record)))
            self._conn.commit()

    def list_session_ids(self):
        cur = self._conn.execute("SELECT DISTINCT session_id FROM records")
        return sorted(r[0] for r in cur.fetchall())

    def list_type_ids_for_session(self, s):
        cur = self._conn.execute(
            "SELECT DISTINCT type_id FROM records WHERE session_id=?", (s,))
        return sorted(r[0] for r in cur.fetchall())

    def list_worker_ids_for_session(self, s):
        cur = self._conn.execute(
            "SELECT DISTINCT worker_id FROM records WHERE session_id=?",
            (s,))
        return sorted(r[0] for r in cur.fetchall())

    def get_all_updates_after(self, s, t, w, ts):
        cur = self._conn.execute(
            "SELECT payload FROM records WHERE kind='update' AND "
            "session_id=? AND type_id=? AND worker_id=? AND timestamp>? "
            "ORDER BY timestamp", (s, t, w, ts))
        return [Persistable(json.loads(r[0])) for r in cur.fetchall()]

    def get_static_info(self, s, t, w):
        cur = self._conn.execute(
            "SELECT payload FROM records WHERE kind='static' AND "
            "session_id=? AND type_id=? AND worker_id=? "
            "ORDER BY timestamp DESC LIMIT 1", (s, t, w))
        row = cur.fetchone()
        return Persistable(json.loads(row[0])) if row else None

    def close(self) -> None:
        self._conn.close()


class RemoteUIStatsStorageRouter(StatsStorageRouter):
    """POST records to a remote UI server (reference:
    api/storage/impl/RemoteUIStatsStorageRouter.java — lets distributed
    workers report to one dashboard). Buffers and drops on connection
    failure after `max_retries`, like the reference's async queue."""

    def __init__(self, url: str, max_retries: int = 3):
        self.url = url.rstrip("/")
        self.max_retries = max_retries
        self.failures = 0

    def _post(self, kind: str, record: Persistable) -> None:
        import urllib.request
        body = json.dumps({"_kind": kind, **record}).encode()
        req = urllib.request.Request(
            self.url + "/remote/receive", data=body,
            headers={"Content-Type": "application/json"})
        for attempt in range(self.max_retries):
            try:
                urllib.request.urlopen(req, timeout=5)
                return
            except Exception:
                continue
        self.failures += 1

    def put_static_info(self, record: Persistable) -> None:
        self._post("static", record)

    def put_update(self, record: Persistable) -> None:
        self._post("update", record)

    def put_storage_metadata(self, record: Persistable) -> None:
        self._post("meta", record)
