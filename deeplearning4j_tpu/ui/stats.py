"""StatsListener: collect per-iteration training statistics.

Parity with the reference (reference:
deeplearning4j-ui-parent/deeplearning4j-ui-model/.../stats/
BaseStatsListener.java:287 iterationDone — score, param/gradient/update
histograms and norms, memory, GC, hardware info, every N iterations;
encoded with SBE codecs stats/sbe/UpdateEncoder.java). Here records are
plain dicts routed to any StatsStorageRouter; norms/histograms are
computed on device in one jitted call per collection step (the reference
pulls each param array to host and loops).
"""
from __future__ import annotations

import os
import time
import resource
from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.train.listeners import IterationListener
from deeplearning4j_tpu.ui.storage import (Persistable, StatsStorageRouter)


@partial(jax.jit, static_argnames=("nbins",))
def _tensor_stats(flat: jax.Array, nbins: int = 20):
    """mean / std / min / max / L2 norm / histogram for one flat vector."""
    norm = jnp.linalg.norm(flat)
    mn, mx = jnp.min(flat), jnp.max(flat)
    hist = jnp.histogram(flat, bins=nbins)[0]
    return (jnp.mean(flat), jnp.std(flat), mn, mx, norm, hist)


def _summarize(tree, nbins: int = 20) -> Dict[str, Dict[str, Any]]:
    out: Dict[str, Dict[str, Any]] = {}
    flat_items = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in flat_items:
        name = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in path)
        arr = jnp.ravel(jnp.asarray(leaf)).astype(jnp.float32)
        if arr.size == 0:
            continue
        mean, std, mn, mx, norm, hist = _tensor_stats(arr, nbins)
        out[name] = {
            "mean": float(mean), "std": float(std), "min": float(mn),
            "max": float(mx), "norm": float(norm),
            "histogram": np.asarray(hist).tolist(),
        }
    return out


class StatsListener(IterationListener):
    """Collects stats every `frequency` iterations and routes them
    (reference: BaseStatsListener(statsStorageRouter, frequency))."""

    def __init__(self, router: StatsStorageRouter, frequency: int = 1,
                 session_id: Optional[str] = None,
                 worker_id: str = "worker_0", collect_histograms: bool = True,
                 histogram_bins: int = 20):
        self.router = router
        self.frequency = max(1, frequency)
        self.session_id = session_id or f"session_{int(time.time())}"
        self.worker_id = worker_id
        self.collect_histograms = collect_histograms
        self.histogram_bins = histogram_bins
        self._static_sent = False
        self._start_time: Optional[float] = None
        self._last_iter_time: Optional[float] = None

    # -- static info (reference: BaseStatsListener initial report) ---------
    def _send_static(self, model) -> None:
        import platform
        record = Persistable({
            "session_id": self.session_id, "type_id": "StaticInfo",
            "worker_id": self.worker_id, "timestamp": time.time(),
            "hardware": {
                "jax_backend": jax.default_backend(),
                "device_count": jax.device_count(),
                "devices": [str(d) for d in jax.devices()],
                "host": platform.node(),
                "python": platform.python_version(),
            },
            "model": {
                "class": type(model).__name__,
                "num_params": int(getattr(model, "num_params",
                                          lambda: 0)()),
            },
        })
        self.router.put_static_info(record)
        self._static_sent = True

    def iteration_done(self, model, iteration: int, score: float) -> None:
        if not self._static_sent:
            self._send_static(model)
            self._start_time = time.time()
        if iteration % self.frequency != 0:
            return
        # display timestamps stay wall-clock; the iteration INTERVAL is
        # measured on the monotonic clock so the duration series (and
        # any rate derived from it) survives wall-clock steps
        now_mono = time.perf_counter()
        duration = (now_mono - self._last_iter_time) \
            if self._last_iter_time else 0.0
        self._last_iter_time = now_mono
        record = Persistable({
            "session_id": self.session_id, "type_id": "Update",
            "worker_id": self.worker_id, "timestamp": time.time(),
            "iteration": iteration,
            "score": float(score),
            "iteration_duration_s": duration,
            "memory": {
                "rss_mb": resource.getrusage(
                    resource.RUSAGE_SELF).ru_maxrss / 1024.0,
            },
        })
        params = getattr(model, "params", None)
        if params and self.collect_histograms:
            record["parameters"] = _summarize(params, self.histogram_bins)
        state = getattr(model, "updater_state", None)
        if state and self.collect_histograms:
            try:
                record["updater_state"] = _summarize(state,
                                                     self.histogram_bins)
            except Exception:
                pass  # opt states can hold non-array leaves
        self.router.put_update(record)
