"""ctypes bridge to the native C++ IO library.

Role parity: the reference's data/tensor path is native (libnd4j via
JavaCPP JNI; DataVec native loaders; SURVEY.md §2.9). Here the tensor
runtime is XLA/PJRT (jax's own C++ stack); this bridge covers the
*IO-side* native components: IDX/CSV/CIFAR binary parsing into dense
buffers wrapped zero-copy as numpy arrays, and a background-thread file
prefetcher (the disk half of AsyncDataSetIterator). The library builds
on first use with g++ (or cmake+ninja); every call site keeps a pure-
Python fallback, mirroring how the reference falls back from cuDNN
helpers to the built-in path when the native helper is missing
(ConvolutionLayer.java:69-76 reflection load).
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path
from typing import List, Optional, Tuple

import numpy as np

log = logging.getLogger("deeplearning4j_tpu")

_REPO_ROOT = Path(__file__).resolve().parent.parent
_SRC = _REPO_ROOT / "native" / "dataloader.cpp"
_BUILD_DIR = _REPO_ROOT / "native" / "build"
_LIB_PATH = _BUILD_DIR / "libdl4jtpu_io.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", str(_SRC),
           "-o", str(_LIB_PATH), "-pthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError) as e:
        log.warning("native IO library build failed (%s); using pure-"
                    "Python IO paths", e)
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None on failure."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if not _LIB_PATH.exists() or (_SRC.exists() and
                                      _SRC.stat().st_mtime
                                      > _LIB_PATH.stat().st_mtime):
            if not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(str(_LIB_PATH))
        except OSError as e:
            log.warning("native IO library load failed: %s", e)
            _load_failed = True
            return None
        # ABI gate FIRST: a stale library must fall back gracefully, not
        # crash on a missing newer symbol below
        if lib.dl4jtpu_io_abi_version() != 3:
            log.warning("native IO library ABI mismatch; rebuild needed")
            _load_failed = True
            return None
        lib.idx_read.restype = ctypes.c_int
        lib.idx_read.argtypes = [ctypes.c_char_p,
                                 ctypes.POINTER(ctypes.c_uint8),
                                 ctypes.c_int64,
                                 ctypes.POINTER(ctypes.c_int64),
                                 ctypes.POINTER(ctypes.c_int32)]
        lib.csv_read_floats.restype = ctypes.c_int
        lib.csv_read_floats.argtypes = [ctypes.c_char_p,
                                        ctypes.POINTER(ctypes.c_float),
                                        ctypes.c_int64,
                                        ctypes.POINTER(ctypes.c_int64),
                                        ctypes.POINTER(ctypes.c_int64),
                                        ctypes.c_char, ctypes.c_int32]
        lib.cifar_read.restype = ctypes.c_int
        lib.cifar_read.argtypes = [ctypes.c_char_p,
                                   ctypes.POINTER(ctypes.c_float),
                                   ctypes.POINTER(ctypes.c_uint8),
                                   ctypes.c_int64,
                                   ctypes.POINTER(ctypes.c_int64)]
        lib.prefetch_create.restype = ctypes.c_void_p
        lib.prefetch_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int64,
            ctypes.c_int64]
        lib.prefetch_peek_size.restype = ctypes.c_int64
        lib.prefetch_peek_size.argtypes = [ctypes.c_void_p]
        lib.prefetch_next.restype = ctypes.c_int64
        lib.prefetch_next.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_int64]
        lib.prefetch_destroy.restype = None
        lib.prefetch_destroy.argtypes = [ctypes.c_void_p]
        lib.vocab_count_buffer.restype = ctypes.c_int64
        lib.vocab_count_buffer.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_char_p,
            ctypes.c_int64]
        lib.window_pairs.restype = ctypes.c_int64
        lib.window_pairs.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32)]
        lib.pair_shuffle.restype = ctypes.c_int32
        lib.pair_shuffle.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_uint64]
        lib.neg_pool_fill.restype = ctypes.c_int32
        lib.neg_pool_fill.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_uint64]
        _lib = lib
        return _lib


def native_available() -> bool:
    return get_lib() is not None


# ---------------------------------------------------------------------------
# High-level wrappers (None → caller uses the Python fallback)
# ---------------------------------------------------------------------------

def idx_read(path: str) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    dims = (ctypes.c_int64 * 4)()
    ndim = ctypes.c_int32()
    rc = lib.idx_read(path.encode(), None, 0, dims, ctypes.byref(ndim))
    if rc != 0:
        return None
    shape = tuple(dims[i] for i in range(ndim.value))
    out = np.empty(shape, np.uint8)
    rc = lib.idx_read(path.encode(),
                      out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                      out.size, dims, ctypes.byref(ndim))
    return out if rc == 0 else None


def csv_read_floats(path: str, delimiter: str = ",",
                    skip_lines: int = 0) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.csv_read_floats(path.encode(), None, 0, ctypes.byref(rows),
                             ctypes.byref(cols), delimiter.encode(),
                             skip_lines)
    if rc != 0 or rows.value == 0:
        return None
    out = np.empty((rows.value, cols.value), np.float32)
    rc = lib.csv_read_floats(
        path.encode(), out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.size, ctypes.byref(rows), ctypes.byref(cols),
        delimiter.encode(), skip_lines)
    return out if rc == 0 else None


def cifar_read(path: str) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    lib = get_lib()
    if lib is None:
        return None
    n = ctypes.c_int64()
    rc = lib.cifar_read(path.encode(), None, None, 0, ctypes.byref(n))
    if rc != 0 or n.value == 0:
        return None
    images = np.empty((n.value, 32, 32, 3), np.float32)
    labels = np.empty(n.value, np.uint8)
    rc = lib.cifar_read(
        path.encode(),
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n.value, ctypes.byref(n))
    return (images, labels) if rc == 0 else None


def vocab_count(text: str, *, lowercase: bool = True, min_count: int = 1,
                nthreads: int = 0) -> Optional[dict]:
    """Parallel token-frequency count over a whitespace-tokenized corpus
    (the reference's VocabConstructor parallel scan,
    VocabConstructor.java:168, in C++). Returns {word: count} or None
    when the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    data = text.encode("utf-8")
    needed = lib.vocab_count_buffer(data, len(data), int(lowercase),
                                    min_count, nthreads, None, 0)
    if needed < 0:
        return None
    buf = ctypes.create_string_buffer(needed)
    n = lib.vocab_count_buffer(data, len(data), int(lowercase), min_count,
                               nthreads, buf, needed)
    if n < 0:
        return None
    out = {}
    for line in buf.raw[:n].decode("utf-8").splitlines():
        word, _, count = line.rpartition("\t")
        if word:
            out[word] = int(count)
    return out


class FilePrefetcher:
    """Background-thread file reader (the reference's
    AsyncDataSetIterator disk half, in C++). Iterate to get each file's
    bytes in order."""

    def __init__(self, paths: List[str], queue_cap: int = 4):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native IO library unavailable")
        self._lib = lib
        arr = (ctypes.c_char_p * len(paths))(
            *[p.encode() for p in paths])
        self._handle = lib.prefetch_create(arr, len(paths), queue_cap)
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        size = self._lib.prefetch_peek_size(self._handle)
        if size < 0:
            raise StopIteration
        buf = ctypes.create_string_buffer(size)
        n = self._lib.prefetch_next(self._handle, buf, size)
        if n < 0:
            raise StopIteration
        return buf.raw[:n]

    def close(self) -> None:
        if not self._closed:
            self._lib.prefetch_destroy(self._handle)
            self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def window_pairs(flat: np.ndarray, sid: np.ndarray, w: np.ndarray,
                 window: int, bufs=None
                 ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Skip-gram (center, context) pair expansion in C++ — the r5 fast
    path for SequenceVectors._corpus_window_pairs (the profiled staging
    bottleneck at reference-scale vocabularies). The reduced-window RNG
    draw stays in numpy upstream, so this and the numpy fallback are
    bit-identical on the same inputs. ``bufs``: an optional caller-held
    [capacity]-int32 buffer pair reused across epochs (fresh ~80MB
    output allocations were a profiled per-epoch cost; the returned
    arrays are VIEWS of the buffers — consume before the next call).
    None -> caller uses the fallback."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(flat)
    flat32 = np.ascontiguousarray(flat, np.int32)
    sid32 = np.ascontiguousarray(sid, np.int32)
    w32 = np.ascontiguousarray(w, np.int32)
    cap = max(1, 2 * int(window) * n)
    if bufs is not None and len(bufs[0]) >= cap:
        centers, contexts = bufs
    else:
        centers = np.empty(cap, np.int32)
        contexts = np.empty(cap, np.int32)
        if bufs is not None:
            bufs[0], bufs[1] = centers, contexts
    i32p = ctypes.POINTER(ctypes.c_int32)
    cnt = lib.window_pairs(
        flat32.ctypes.data_as(i32p), sid32.ctypes.data_as(i32p),
        w32.ctypes.data_as(i32p), n, int(window),
        centers.ctypes.data_as(i32p), contexts.ctypes.data_as(i32p))
    if cnt < 0:
        return None
    return centers[:cnt], contexts[:cnt]


def pair_shuffle(centers: np.ndarray, contexts: np.ndarray,
                 seed: int) -> bool:
    """IN-PLACE paired Fisher-Yates shuffle of two int32 arrays (the
    skip-gram epoch shuffle) with the native xoshiro RNG; ``seed`` is
    one draw from the model's numpy Generator, keeping runs
    reproducible. False -> caller uses the numpy fallback."""
    lib = get_lib()
    if lib is None or len(centers) != len(contexts):
        return False
    if not (centers.flags.c_contiguous and contexts.flags.c_contiguous
            and centers.dtype == np.int32
            and contexts.dtype == np.int32):
        return False
    i32p = ctypes.POINTER(ctypes.c_int32)
    return lib.pair_shuffle(
        centers.ctypes.data_as(i32p), contexts.ctypes.data_as(i32p),
        len(centers), ctypes.c_uint64(seed)) == 0


def neg_pool_fill(table: np.ndarray, shape: Tuple[int, ...],
                  seed: int) -> Optional[np.ndarray]:
    """A negative-sample pool of ``shape`` drawn from the unigram
    ``table`` natively (one bounded xoshiro draw + gather per entry);
    ``seed`` is one draw from the model's numpy Generator. None ->
    caller uses the numpy fallback."""
    lib = get_lib()
    if lib is None:
        return None
    t32 = np.ascontiguousarray(table, np.int32)
    out = np.empty(shape, np.int32)
    n = out.size
    i32p = ctypes.POINTER(ctypes.c_int32)
    rc = lib.neg_pool_fill(t32.ctypes.data_as(i32p), len(t32),
                           out.ctypes.data_as(i32p), n,
                           ctypes.c_uint64(seed))
    return out if rc == 0 else None
