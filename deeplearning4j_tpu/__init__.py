"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A from-scratch framework with the capability surface of Deeplearning4j
(reference: /root/reference, surveyed in SURVEY.md): builder-style network
configuration with JSON round-trip, sequential (MultiLayerNetwork) and DAG
(ComputationGraph) models, a full layer library, training driver with
updaters/schedules/listeners, evaluation and gradient-check harnesses, Keras
import, embedding models, and distributed training.

Unlike the reference — eager per-op JNI dispatch into libnd4j with
reflection-loaded cuDNN helpers (see SURVEY.md §3.1) — every model here traces
to a single XLA program: forward + backward + updater fuse into one compiled
step executed on TPU, and gradient synchronization is an in-program collective
over the ICI mesh (`jax.sharding` + `shard_map`) rather than host-staged
parameter averaging.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.nn.conf import (  # noqa: F401
    NeuralNetConfiguration,
    MultiLayerConfiguration,
    ComputationGraphConfiguration,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: F401
from deeplearning4j_tpu.nn.graph import ComputationGraph  # noqa: F401
