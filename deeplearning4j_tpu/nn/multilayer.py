"""MultiLayerNetwork — the sequential model.

Parity with the reference's MultiLayerNetwork (reference:
deeplearning4j-nn/.../nn/multilayer/MultiLayerNetwork.java, 2,590 LoC:
init:405 flat buffer:445, fit(DataSetIterator):947, backprop():1019,
doTruncatedBPTT:1119, rnnTimeStep:2234, pretrain, score, output).

TPU-native inversion of the reference's design (SURVEY.md §3.1): instead of
eager per-op JNI dispatch through a Solver/Updater object graph, the entire
minibatch step — forward, loss (+L1/L2), backward (autodiff), gradient
normalization, updater transform, parameter update — traces into ONE jitted
XLA program. The reference's flat-parameter-view protocol
(setParamsViewArray) becomes a params pytree; `params()` returns the
ravel_pytree flat view for API parity.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from deeplearning4j_tpu.common import promote_score
from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers.base import Layer, apply_dropout
from deeplearning4j_tpu.nn.layers.misc import FrozenLayer
from deeplearning4j_tpu.nn.layers.recurrent import (
    wavefront_eligible_run as _wavefront_run,
    wavefront_scan_stack as _wavefront_scan)
from deeplearning4j_tpu.train.updaters import (apply_updater,
                                               init_updater_state)

Array = jax.Array


def _dtype_of(name: str):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
            "float16": jnp.float16, "float64": jnp.float64}[name]


class MultiLayerNetwork:
    """Sequential network over a MultiLayerConfiguration."""

    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        conf.resolve_shapes()
        self.layers: List[Layer] = conf.layers
        self.layer_names = [conf.layer_name(i)
                            for i in range(len(conf.layers))]
        self.dtype = _dtype_of(conf.training.dtype)
        self.params: Dict[str, Dict[str, Array]] = {}
        self.state: Dict[str, Dict[str, Array]] = {}
        self.updater_state: Dict[str, Any] = {}
        self.iteration_count = 0
        self.epoch_count = 0
        # cross-layer LSTM wavefront fusion (nn/layers/recurrent.py);
        # instance-level switch so cost analysis can lower the
        # UNFUSED schedule without touching process-global env state
        self.lstm_wavefront = True
        self.listeners: List[Any] = []
        self.training_guard: Optional[Any] = None
        self.last_grad_norm: float = float("nan")
        self.score_value: float = float("nan")
        self._jit_cache: Dict[Any, Any] = {}
        self._pretrain_counts: Dict[int, int] = {}
        self._rnn_carries: Optional[Dict[str, Any]] = None
        self._solver = None
        self._initialized = False

    # ------------------------------------------------------------------ init
    def init(self, seed: Optional[int] = None) -> "MultiLayerNetwork":
        """Initialize parameters (reference: MultiLayerNetwork.init():405)."""
        seed = self.conf.training.seed if seed is None else seed
        root = jax.random.PRNGKey(seed)
        for i, layer in enumerate(self.layers):
            name = self.layer_names[i]
            # eager activation validation: a typo'd name should fail
            # HERE with the valid list, not at the first forward inside
            # a traced program (r5 verify probe)
            act = getattr(layer, "activation", None)
            if isinstance(act, str):
                from deeplearning4j_tpu.nn.activations import \
                    get_activation
                get_activation(act)
            key = jax.random.fold_in(root, i)
            self.params[name] = layer.init_params(key, self.dtype)
            self.state[name] = layer.init_state(self.dtype)
        self.updater_state = init_updater_state(self.conf.training,
                                                self.params)
        self._initialized = True
        return self

    def set_listeners(self, *listeners) -> None:
        self.listeners = list(listeners)

    def set_training_guard(self, guard) -> None:
        """Install (or clear, with None) a `train.guard.TrainingGuard`:
        `fit`'s SGD path switches to the guarded step — post-step score
        AND global grad-norm checked, non-finite updates discarded on
        device, skip/rollback policy applied host-side."""
        self.training_guard = guard

    # --------------------------------------------------------------- forward
    def _forward(self, params, state, x, *, train: bool,
                 key: Optional[jax.Array], mask: Optional[Array],
                 carries: Optional[Dict[str, Any]] = None,
                 collect: bool = False):
        """Pure forward over all layers. Returns (activations list if collect
        else final activation, preout of output layer, new_state,
        new_carries)."""
        acts = []
        new_state = {}
        new_carries = {}
        h = x.astype(self.dtype) if jnp.issubdtype(x.dtype, jnp.floating) \
            else x
        preout = None
        i = 0
        while i < len(self.layers):
            layer = self.layers[i]
            name = self.layer_names[i]
            pp = self.conf.input_preprocessors.get(str(i))
            if pp is not None:
                h = pp.pre_process(h)
            lkey = (jax.random.fold_in(key, i)
                    if key is not None else None)
            if train and (layer.dropout or 0.0) > 0 and lkey is not None:
                h = apply_dropout(h, layer.dropout, lkey)
            # adjacent unidirectional LSTM layers run as ONE wavefront
            # scan (nn/layers/recurrent.wavefront_scan_stack — exact
            # reordering, measured 1.14-1.28x on the 2-layer char-RNN);
            # collect=True needs every layer's activations, so it keeps
            # the per-layer path
            run = [] if collect else _wavefront_run(
                self.layers, self.layer_names, i, train=train,
                mask=mask, carries=carries,
                preprocessors=self.conf.input_preprocessors,
                enabled=self.lstm_wavefront)
            if len(run) > 1:
                h = self._apply_wavefront(run, params, h, carries,
                                          state, new_state,
                                          new_carries, stop_grad=False)
                i = run[-1] + 1
                continue
            if carries is not None and hasattr(layer, "scan_sequence") \
                    and name in carries:
                h, carry = layer.scan_sequence(params[name], h,
                                               carry=carries[name],
                                               mask=mask)
                new_carries[name] = carry
                new_state[name] = state.get(name, {})
            else:
                h, st = layer.apply(params[name], state.get(name, {}), h,
                                    train=train, key=lkey, mask=mask)
                new_state[name] = st
            if collect:
                acts.append(h)
            i += 1
        return (acts if collect else h), preout, new_state, new_carries

    def _apply_wavefront(self, run, params, h, carries, state,
                         new_state, new_carries, *, stop_grad):
        """Run one fused LSTM stack (shared by _forward and the TBPTT
        chunk step — ONE definition so the two integration sites can't
        drift). Emits per-layer final carries when the carries dict
        covers the run (eligibility enforces all-or-none coverage);
        ``stop_grad`` reproduces the TBPTT chunk boundary."""
        rnames = [self.layer_names[j] for j in run]
        cl = ([carries[nm] for nm in rnames]
              if carries is not None and rnames[0] in carries else None)
        h, finals = _wavefront_scan(
            [self.layers[j] for j in run],
            [params[nm] for nm in rnames], h, carries=cl)
        for nm, fc in zip(rnames, finals):
            if cl is not None:
                new_carries[nm] = (jax.tree_util.tree_map(
                    jax.lax.stop_gradient, fc) if stop_grad else fc)
            new_state[nm] = state.get(nm, {})
        return h

    def _regularization_score(self, params) -> Array:
        """0.5·l2·||W||² + l1·||W||₁ summed over layers (reference:
        BaseLayer.calcL2/calcL1 feeding computeScore)."""
        total = jnp.asarray(0.0)
        for i, layer in enumerate(self.layers):
            name = self.layer_names[i]
            l1 = layer.l1 or 0.0
            l2 = layer.l2 or 0.0
            if (l1 == 0.0 and l2 == 0.0) or not params.get(name):
                continue
            for k in layer.weight_param_keys():
                if k not in params[name]:
                    continue
                w = promote_score(params[name][k])
                if l2 > 0:
                    total = total + 0.5 * l2 * jnp.sum(w * w)
                if l1 > 0:
                    total = total + l1 * jnp.sum(jnp.abs(w))
        return total

    def _loss_fn(self, params, state, x, y, key, mask, train=True):
        out_layer = self.layers[-1]
        out_name = self.layer_names[-1]
        if not hasattr(out_layer, "loss"):
            raise ValueError("Last layer must be an output/loss layer to "
                             "compute a score")
        h = x.astype(self.dtype) if jnp.issubdtype(x.dtype, jnp.floating) \
            else x
        new_state = {}
        n = len(self.layers)
        for i, layer in enumerate(self.layers[:-1]):
            name = self.layer_names[i]
            pp = self.conf.input_preprocessors.get(str(i))
            if pp is not None:
                h = pp.pre_process(h)
            lkey = jax.random.fold_in(key, i) if key is not None else None
            if train and (layer.dropout or 0.0) > 0 and lkey is not None:
                h = apply_dropout(h, layer.dropout, lkey)
            h, st = layer.apply(params[name], state.get(name, {}), h,
                                train=train, key=lkey, mask=mask)
            new_state[name] = st
        pp = self.conf.input_preprocessors.get(str(n - 1))
        if pp is not None:
            h = pp.pre_process(h)
        okey = jax.random.fold_in(key, n - 1) if key is not None else None
        if (out_layer.dropout or 0.0) > 0 and okey is not None:
            h = apply_dropout(h, out_layer.dropout, okey)
        if hasattr(out_layer, "update_centers"):  # center loss
            loss = out_layer.loss(params[out_name], h, y, mask,
                                  state.get(out_name))
            new_state[out_name] = out_layer.update_centers(
                state.get(out_name, {}), h, y)
        else:
            loss = out_layer.loss(params[out_name], h, y, mask)
            new_state[out_name] = state.get(out_name, {})
        score = promote_score(loss) + self._regularization_score(params)
        return score, new_state

    # ----------------------------------------------------------- train step
    def _lr_multipliers(self) -> Dict[str, float]:
        base = self.conf.training.learning_rate
        out = {}
        for i, layer in enumerate(self.layers):
            lr = layer.learning_rate
            # explicit 0.0 is a valid per-layer LR (DL4J-style freezing), so
            # test for None rather than falsiness
            out[self.layer_names[i]] = (lr / base) \
                if (lr is not None and base) else 1.0
        return out

    def _trainable(self) -> Dict[str, bool]:
        return {self.layer_names[i]: not isinstance(l, FrozenLayer)
                for i, l in enumerate(self.layers)}

    def _step_math(self):
        """The pure minibatch-update function shared by the per-batch jit
        and the scanned epoch path."""
        tc = self.conf.training
        lr_mult = self._lr_multipliers()
        trainable = self._trainable()

        def step(params, state, opt_state, iteration, x, y, key, mask):
            def loss_fn(p):
                return self._loss_fn(p, state, x, y, key, mask)
            (score, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            new_params, new_opt = apply_updater(
                tc, params, grads, opt_state, iteration,
                lr_multipliers=lr_mult, trainable=trainable)
            return new_params, new_state, new_opt, score

        return step

    def _make_train_step(self, **jit_kwargs):
        """Build the jitted minibatch step. ``jit_kwargs`` lets callers (e.g.
        ParallelWrapper) compile the same step with mesh shardings."""
        return jax.jit(self._step_math(), donate_argnums=(0, 1, 2),
                       **jit_kwargs)

    def _make_guarded_train_step(self):
        """TrainingGuard variant of the minibatch step: additionally
        returns the global gradient norm, discards a non-finite update
        ON DEVICE (params/state/opt pass through unchanged when score
        or grad-norm is NaN/Inf — a poisoned batch cannot contaminate
        the tree even before the host sees the score), and does NOT
        donate its inputs, so the host keeps the pre-step tree and a
        guard SKIP is a no-op commit."""
        tc = self.conf.training
        lr_mult = self._lr_multipliers()
        trainable = self._trainable()

        def step(params, state, opt_state, iteration, x, y, key, mask):
            def loss_fn(p):
                return self._loss_fn(p, state, x, y, key, mask)
            (score, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            gnorm = jnp.sqrt(sum(
                jnp.sum(promote_score(g) ** 2)
                for g in jax.tree_util.tree_leaves(grads)))
            new_params, new_opt = apply_updater(
                tc, params, grads, opt_state, iteration,
                lr_multipliers=lr_mult, trainable=trainable)
            ok = jnp.isfinite(score) & jnp.isfinite(gnorm)

            def keep(new, old):
                return jax.tree_util.tree_map(
                    lambda n, o: jnp.where(ok, n, o), new, old)

            return (keep(new_params, params), keep(new_state, state),
                    keep(new_opt, opt_state), score, gnorm)

        return jax.jit(step)

    def _make_epoch_program(self, mb_body_factory, epochs: int,
                            **jit_kwargs):
        """Shared scaffolding for the scanned training programs: an
        inner `lax.scan` walks the minibatch pool with the body built by
        ``mb_body_factory(xs, ys, base_key)``, and ``epochs`` > 1 nests
        that in an outer pass-counting scan — the staged pool is
        traversed `epochs` times inside the SAME program, so HBM holds
        one pool but the program spans the whole run (the iteration
        counter — and with it the dropout key and LR schedule position —
        keeps advancing across passes)."""
        def epoch(params, state, opt_state, start_iteration, xs, ys,
                  base_key):
            body = mb_body_factory(xs, ys, base_key)

            def one_pass(carry, _):
                return jax.lax.scan(body, carry, (xs, ys))

            carry = (params, state, opt_state, start_iteration)
            if epochs == 1:
                carry, scores = one_pass(carry, None)
            else:
                carry, scores = jax.lax.scan(one_pass, carry, None,
                                             length=epochs)
            params, state, opt_state, _ = carry
            return params, state, opt_state, scores.reshape(-1)

        return jax.jit(epoch, donate_argnums=(0, 1, 2), **jit_kwargs)

    def _make_scan_fit(self, epochs: int = 1, **jit_kwargs):
        """Whole-epoch program: `lax.scan` of the minibatch step over a
        leading batches axis — the per-step loop stays ON DEVICE, so no
        host dispatch between steps (the SURVEY §3.1 design consequence:
        the reference's eager per-op/per-step JNI round-trips collapse
        into one XLA program; this is the multi-STEP version of that)."""
        step = self._step_math()

        def factory(xs, ys, base_key):
            def body(carry, xy):
                params, state, opt, it = carry
                x, y = xy
                key = jax.random.fold_in(base_key, it)
                params, state, opt, score = step(
                    params, state, opt, it, x, y, key, None)
                return (params, state, opt, it + 1), score

            return body

        return self._make_epoch_program(factory, epochs, **jit_kwargs)

    def fit_batched(self, xs, ys, epochs: int = 1) -> "jnp.ndarray":
        """Train on a pre-staged stack of minibatches in ONE compiled
        program: ``xs`` [N, B, ...], ``ys`` [N, B, ...] → per-step
        scores [N * epochs]. The high-throughput path for data already
        on (or streamable to) the device; `fit(iterator)` remains the
        host-streaming path. ``epochs`` repeats the staged pool inside
        the same program. Listeners fire after the program returns
        (scores come back as one array).

        With backprop_type='tbptt', ``xs``/``ys`` are [N, B, T, F] with
        T divisible by tbptt_fwd_length; each minibatch scans its time
        chunks with carried RNN state and one update per chunk, so
        scores (and iteration counts) are per CHUNK: [N * T/L * epochs]."""
        xs = jnp.asarray(xs)
        ys = jnp.asarray(ys)
        fn, chunks = self._scan_fit_fn(xs, ys, epochs)
        return self._run_scan_fit(fn, xs, ys, chunks_per_batch=chunks)

    def fit_batched_cost(self, xs, ys, epochs: int = 1,
                         lstm_wavefront: Optional[bool] = None) -> dict:
        """XLA cost analysis ({'flops', 'bytes accessed', ...}) for the
        exact program `fit_batched(xs, ys, epochs)` runs at these shapes.
        Lower+compile only — no execution, parameters untouched. Feeds
        MFU reporting (util/flops.py); the reference's PerformanceListener
        reports examples/sec only.

        ``lstm_wavefront=False`` costs the UNFUSED schedule: the
        wavefront moves layer-2+'s hoisted input projections into the
        scan body, which XLA's cost model counts once instead of T
        times — model FLOPs are schedule-independent, so the unfused
        lowering is the honest denominator for MFU."""
        xs = jnp.asarray(xs)
        ys = jnp.asarray(ys)
        prev = self.lstm_wavefront
        if lstm_wavefront is not None:
            self.lstm_wavefront = lstm_wavefront
        try:
            fn, _ = self._scan_fit_fn(xs, ys, epochs)
        finally:
            self.lstm_wavefront = prev
        from deeplearning4j_tpu.util.flops import cost_analysis
        base_key = jax.random.PRNGKey(self.conf.training.seed)
        start = jnp.asarray(self.iteration_count, jnp.int32)
        return cost_analysis(fn, self.params, self.state,
                             self.updater_state, start, xs, ys, base_key)

    def _scan_fit_fn(self, xs, ys, epochs: int):
        """Dispatch + cache for the scanned-fit program; returns
        (jitted_fn, chunks_per_batch)."""
        self._validate_fit_batched(epochs, allow_tbptt=True)
        # tbptt needs temporal labels; non-temporal targets fall through
        # to standard BPTT, matching fit()'s dispatch
        use_tbptt = (self.conf.backprop_type == "tbptt" and ys.ndim == 4)
        if use_tbptt:
            L = self.conf.tbptt_fwd_length
            if xs.ndim != 4:
                raise ValueError("tbptt fit_batched needs [N, B, T, F] "
                                 f"inputs, got ndim={xs.ndim}")
            if xs.shape[2] != ys.shape[2]:
                raise ValueError(
                    f"tbptt fit_batched needs one sequence length; "
                    f"inputs T={xs.shape[2]} vs labels T={ys.shape[2]}")
            if xs.shape[2] % L:
                raise ValueError(
                    f"tbptt fit_batched needs T ({xs.shape[2]}) divisible "
                    f"by tbptt_fwd_length ({L}); use fit() for ragged "
                    "tails")
            cache_key = ("scanfit-tbptt", epochs, self.lstm_wavefront)
            maker = self._make_scan_fit_tbptt
        else:
            cache_key = ("scanfit", epochs, self.lstm_wavefront)
            maker = self._make_scan_fit
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            fn = maker(epochs)
            self._jit_cache[cache_key] = fn
        chunks = (xs.shape[2] // self.conf.tbptt_fwd_length
                  if use_tbptt else 1)
        return fn, chunks

    def _validate_fit_batched(self, epochs: int,
                              allow_tbptt: bool = False) -> None:
        if not self._initialized:
            self.init()
        tc = self.conf.training
        if tc.optimization_algo not in ("stochastic_gradient_descent",
                                        "sgd"):
            raise ValueError(
                "fit_batched supports first-order optimization only; "
                f"optimization_algo={tc.optimization_algo!r} dispatches "
                "to the Solver path — use fit() instead")
        if self.conf.backprop_type == "tbptt" and not allow_tbptt:
            raise ValueError("this scanned path does not implement "
                             "truncated BPTT; use fit() or "
                             "MultiLayerNetwork.fit_batched")
        if max(1, tc.num_iterations) != 1:
            raise ValueError(
                "fit_batched applies one update per minibatch; "
                f"num_iterations={tc.num_iterations} requires fit()")
        if epochs < 1:
            raise ValueError(f"epochs must be >= 1, got {epochs}")

    def _run_scan_fit(self, fn, xs, ys,
                      chunks_per_batch: int = 1) -> "jnp.ndarray":
        base_key = jax.random.PRNGKey(self.conf.training.seed)
        start = jnp.asarray(self.iteration_count, jnp.int32)
        self.params, self.state, self.updater_state, scores = fn(
            self.params, self.state, self.updater_state, start, xs, ys,
            base_key)
        n = int(scores.shape[0])
        if n == 0:
            return scores
        if not self.listeners:
            # no per-step host work in the hot path (bench case)
            self.iteration_count += n
            self.score_value = float(scores[-1])
            return scores
        host_scores = np.asarray(scores)
        pool = int(xs.shape[0])
        for i in range(n):
            # TBPTT yields chunks_per_batch scores per minibatch; batch/
            # input telemetry fires once per minibatch (its first chunk)
            self._notify_iteration(float(host_scores[i]),
                                   xs[(i // chunks_per_batch) % pool],
                                   record=(i % chunks_per_batch == 0))
        return scores

    def _notify_iteration(self, score, x, record: bool = True) -> None:
        """Fire per-iteration listener hooks and advance iteration_count
        (reference: BaseOptimizer notifies listeners each iteration).
        ``record`` gates the batch/input telemetry hooks — TBPTT fires
        iteration_done per chunk but counts each minibatch's examples
        once."""
        self.score_value = score
        for l in self.listeners:
            if record and hasattr(l, "record_batch"):
                l.record_batch(int(x.shape[0]))
            if record and hasattr(l, "record_input"):
                l.record_input(x)
            l.iteration_done(self, self.iteration_count, score)
        self.iteration_count += 1

    def _get_train_step(self, shape_key):
        fn = self._jit_cache.get(("train", shape_key))
        if fn is None:
            fn = self._make_train_step()
            self._jit_cache[("train", shape_key)] = fn
        return fn

    # ------------------------------------------------------------------- fit
    def fit(self, data, labels=None, mask=None) -> None:
        """Train. ``data`` is a DataSetIterator-like (yielding
        (features, labels) or DataSet objects) or a raw array with
        ``labels`` (reference: fit(DataSetIterator):947 /
        fit(INDArray,INDArray):1399)."""
        if not self._initialized:
            self.init()
        if labels is not None:
            self._fit_batch(data, labels, mask)
            return
        for l in self.listeners:
            l.on_epoch_start(self)
        for batch in data:
            feats, labs, fmask, lmask = _unpack_batch(batch)
            self._fit_batch(feats, labs, lmask if lmask is not None
                            else fmask)
        for l in self.listeners:
            l.on_epoch_end(self)
        self.epoch_count += 1
        if hasattr(data, "reset"):
            data.reset()

    def _fit_batch(self, x, y, mask=None) -> None:
        x = jnp.asarray(x)
        y = jnp.asarray(y)
        if self.conf.backprop_type == "tbptt" and x.ndim == 3:
            if self.conf.training.optimization_algo not in (
                    "stochastic_gradient_descent", "sgd"):
                raise ValueError(
                    "TBPTT supports first-order optimization only — "
                    f"optimization_algo="
                    f"{self.conf.training.optimization_algo!r}")
            self._fit_tbptt(x, y, mask)
            return
        if self.conf.training.optimization_algo not in (
                "stochastic_gradient_descent", "sgd"):
            # Second-order path (reference: Solver.java:48 dispatches on
            # OptimizationAlgorithm to LBFGS/CG/LineGD)
            from deeplearning4j_tpu.train.solvers import Solver
            if self._solver is None:
                self._solver = Solver(self)

            self._solver.optimize(
                x, y, mask,
                iteration_callback=lambda s: self._notify_iteration(s, x))
            return
        if self.training_guard is not None:
            self._fit_batch_guarded(x, y, mask)
            return
        step = self._get_train_step((x.shape, y.shape,
                                     mask is not None))
        for _ in range(max(1, self.conf.training.num_iterations)):
            key = jax.random.fold_in(jax.random.PRNGKey(
                self.conf.training.seed), self.iteration_count)
            self.params, self.state, self.updater_state, score = step(
                self.params, self.state, self.updater_state,
                self.iteration_count, x, y, key,
                None if mask is None else jnp.asarray(mask))
            self._notify_iteration(score, x)

    def _fit_batch_guarded(self, x, y, mask=None) -> None:
        """SGD minibatch step under a TrainingGuard: run the guarded
        step (no donation; non-finite update already discarded on
        device), then let the guard judge (score, grad_norm). ACCEPT
        commits the new tree; SKIP keeps the pre-step tree (the
        iteration counter still advances, so the dropout/RNG stream and
        LR schedule move past the bad batch); ROLLBACK raises
        DivergenceError for the caller's checkpoint-restore policy
        (FaultTolerantTrainer catches it; a bare fit propagates)."""
        from deeplearning4j_tpu.train.guard import (DivergenceError,
                                                    TrainingGuard)
        cache_key = ("train-guarded", x.shape, y.shape, mask is not None)
        step = self._jit_cache.get(cache_key)
        if step is None:
            step = self._make_guarded_train_step()
            self._jit_cache[cache_key] = step
        for _ in range(max(1, self.conf.training.num_iterations)):
            key = jax.random.fold_in(jax.random.PRNGKey(
                self.conf.training.seed), self.iteration_count)
            new_p, new_s, new_o, score, gnorm = step(
                self.params, self.state, self.updater_state,
                self.iteration_count, x, y, key,
                None if mask is None else jnp.asarray(mask))
            score_f = float(score)
            self.last_grad_norm = float(gnorm)
            action = self.training_guard.update(score_f,
                                                self.last_grad_norm)
            if action == TrainingGuard.ACCEPT:
                self.params, self.state, self.updater_state = (
                    new_p, new_s, new_o)
            elif action == TrainingGuard.ROLLBACK:
                raise DivergenceError(
                    f"training diverged at iteration "
                    f"{self.iteration_count}: "
                    f"{self.training_guard.max_consecutive} consecutive "
                    f"bad steps (last: {self.training_guard.last_reason},"
                    f" score={score_f}, grad_norm="
                    f"{self.last_grad_norm})")
            # SKIP: pre-step tree kept; fall through to notify
            self._notify_iteration(score_f, x)

    def _fit_tbptt(self, x, y, mask=None) -> None:
        """Truncated BPTT (reference: doTruncatedBPTT,
        MultiLayerNetwork.java:1119): split the time axis into chunks of
        tbptt_fwd_length, carry RNN state (stop-gradient) across chunks."""
        T = x.shape[1]
        L = self.conf.tbptt_fwd_length
        n_chunks = math.ceil(T / L)
        carries = self._init_carries(x.shape[0])
        tc = self.conf.training
        chunk_step = self._jit_cache.get(
            ("tbptt", x.shape[0], x.shape[2], self.lstm_wavefront))
        if chunk_step is None:
            chunk_step = self._make_tbptt_step()
            self._jit_cache[("tbptt", x.shape[0], x.shape[2],
                             self.lstm_wavefront)] = chunk_step

        for c in range(n_chunks):
            sl = slice(c * L, min((c + 1) * L, T))
            xs, ys = x[:, sl], y[:, sl]
            m = None if mask is None else jnp.asarray(mask)[:, sl]
            key = jax.random.fold_in(jax.random.PRNGKey(tc.seed),
                                     self.iteration_count)
            (self.params, self.state, self.updater_state, carries,
             score) = chunk_step(self.params, self.state,
                                 self.updater_state, self.iteration_count,
                                 xs, ys, carries, key, m)
            # batch/input telemetry once per minibatch (first chunk),
            # iteration_done per chunk — same contract as the scanned
            # TBPTT path (_run_scan_fit). score stays a device array:
            # forcing it would serialize the chunk pipeline.
            self._notify_iteration(score, x, record=(c == 0))

    def _tbptt_chunk_math(self):
        """The pure TBPTT chunk update: one forward over a time chunk
        with carried (stop-gradient) RNN state, one optimizer step.
        Shared by the per-chunk jitted path (_make_tbptt_step) and the
        scanned fit_batched path (_make_scan_fit_tbptt)."""
        tc = self.conf.training
        lr_mult = self._lr_multipliers()
        trainable = self._trainable()

        def chunk_step(params, state, opt_state, iteration, xs, ys, carries,
                       key, m):
            def loss_fn(p):
                h = xs.astype(self.dtype)
                new_state = {}
                new_carries = {}
                i = 0
                while i < len(self.layers) - 1:
                    layer = self.layers[i]
                    name = self.layer_names[i]
                    # adjacent LSTM layers: one wavefront scan (same
                    # fusion as _forward; carried state stop-gradiented
                    # per layer exactly like the sequential path)
                    run = _wavefront_run(
                        self.layers[:-1], self.layer_names, i,
                        train=True, mask=m, carries=carries,
                        preprocessors=self.conf.input_preprocessors,
                        enabled=self.lstm_wavefront)
                    if len(run) > 1:
                        h = self._apply_wavefront(
                            run, p, h, carries, state, new_state,
                            new_carries, stop_grad=True)
                        i = run[-1] + 1
                        continue
                    if hasattr(layer, "scan_sequence") and name in carries:
                        h, carry = layer.scan_sequence(
                            p[name], h, carry=carries.get(name), mask=m)
                        new_carries[name] = jax.tree_util.tree_map(
                            jax.lax.stop_gradient, carry)
                        new_state[name] = state.get(name, {})
                    else:
                        h, st = layer.apply(p[name], state.get(name, {}), h,
                                            train=True, key=key, mask=m)
                        new_state[name] = st
                    i += 1
                out_layer = self.layers[-1]
                out_name = self.layer_names[-1]
                loss = out_layer.loss(p[out_name], h, ys, m)
                new_state[out_name] = state.get(out_name, {})
                score = promote_score(loss) \
                    + self._regularization_score(p)
                return score, (new_state, new_carries)

            (score, (new_state, new_carries)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            new_params, new_opt = apply_updater(
                tc, params, grads, opt_state, iteration,
                lr_multipliers=lr_mult, trainable=trainable)
            return new_params, new_state, new_opt, new_carries, score

        return chunk_step

    def _make_tbptt_step(self):
        """Jitted TBPTT chunk step, cached per (batch, features) shape —
        the compiled program is reused across minibatches and chunks."""
        return jax.jit(self._tbptt_chunk_math())

    def _make_scan_fit_tbptt(self, epochs: int = 1, **jit_kwargs):
        """Whole-run TBPTT program: for each staged minibatch, an inner
        `lax.scan` walks the time chunks (carried RNN state reset per
        minibatch, parameters updated per chunk — iteration semantics of
        _fit_tbptt), an outer scan walks the minibatch pool, and the
        `epochs` scan repeats the pool — all inside ONE compiled
        program, the TBPTT counterpart of _make_scan_fit."""
        chunk_step = self._tbptt_chunk_math()
        L = self.conf.tbptt_fwd_length

        def factory(xs, ys, base_key):
            b, t = xs.shape[1], xs.shape[2]
            s = t // L
            carries0 = self._init_carries(b)

            def to_chunks(a):
                # [B, T, ...] -> [S, B, L, ...]
                a = a.reshape((b, s, L) + a.shape[2:])
                return jnp.moveaxis(a, 1, 0)

            def mb_body(carry, xy):
                params, state, opt, it = carry
                x, y = xy

                def chunk_body(c2, xyc):
                    params, state, opt, it, carries = c2
                    xc, yc = xyc
                    key = jax.random.fold_in(base_key, it)
                    params, state, opt, carries, score = chunk_step(
                        params, state, opt, it, xc, yc, carries, key,
                        None)
                    return (params, state, opt, it + 1, carries), score

                (params, state, opt, it, _), scores = jax.lax.scan(
                    chunk_body, (params, state, opt, it, carries0),
                    (to_chunks(x), to_chunks(y)))
                return (params, state, opt, it), scores

            return mb_body

        return self._make_epoch_program(factory, epochs, **jit_kwargs)

    def _init_carries(self, batch: int) -> Dict[str, Any]:
        carries = {}
        for i, layer in enumerate(self.layers):
            if hasattr(layer, "initial_carry") \
                    and getattr(layer, "supports_streaming", True):
                carries[self.layer_names[i]] = layer.initial_carry(
                    batch, self.dtype)
        return carries

    # -------------------------------------------------------------- pretrain
    def pretrain(self, data) -> None:
        """Greedy layerwise unsupervised pretraining for AE/VAE layers
        (reference: MultiLayerNetwork.pretrain / pretrainLayer)."""
        if not self._initialized:
            self.init()
        for i, layer in enumerate(self.layers):
            if not layer.is_pretrain_layer():
                continue
            self.pretrain_layer(i, data)
            if hasattr(data, "reset"):
                data.reset()

    def _make_pretrain_step(self, layer_idx: int):
        layer = self.layers[layer_idx]
        name = self.layer_names[layer_idx]
        tc = self.conf.training

        def pstep(below_params, below_state, params, opt_state, iteration,
                  x, key):
            def loss_fn(p):
                h = x.astype(self.dtype)
                for j in range(layer_idx):
                    jn = self.layer_names[j]
                    pp = self.conf.input_preprocessors.get(str(j))
                    if pp is not None:
                        h = pp.pre_process(h)
                    h, _ = self.layers[j].apply(
                        jax.lax.stop_gradient(below_params[jn]),
                        below_state.get(jn, {}), h, train=False)
                pp = self.conf.input_preprocessors.get(str(layer_idx))
                if pp is not None:
                    h = pp.pre_process(h)
                return layer.pretrain_loss(p, h, key)

            score, grads = jax.value_and_grad(loss_fn)(params)
            wrapped_p = {name: params}
            wrapped_g = {name: grads}
            wrapped_s = {name: opt_state}
            new_p, new_s = apply_updater(tc, wrapped_p, wrapped_g, wrapped_s,
                                         iteration)
            return new_p[name], new_s[name], score

        return jax.jit(pstep)

    def pretrain_layer(self, layer_idx: int, data) -> None:
        layer = self.layers[layer_idx]
        name = self.layer_names[layer_idx]
        if not layer.is_pretrain_layer():
            return
        tc = self.conf.training
        pstep = self._jit_cache.get(("pretrain", layer_idx))
        if pstep is None:
            pstep = self._make_pretrain_step(layer_idx)
            self._jit_cache[("pretrain", layer_idx)] = pstep
        below = {self.layer_names[j]: self.params[self.layer_names[j]]
                 for j in range(layer_idx)}
        below_state = {self.layer_names[j]:
                       self.state.get(self.layer_names[j], {})
                       for j in range(layer_idx)}
        # persistent per-layer counter: repeated calls keep advancing the
        # updater's t (Adam bias correction) and the RNG stream
        it = self._pretrain_counts.get(layer_idx, 0)
        batches = data if not hasattr(data, "__array__") else [(data, None)]
        for batch in batches:
            feats, _, _, _ = _unpack_batch(batch)
            key = jax.random.fold_in(jax.random.PRNGKey(tc.seed), it)
            (self.params[name], self.updater_state[name],
             score) = pstep(below, below_state, self.params[name],
                            self.updater_state[name], it,
                            jnp.asarray(feats), key)
            self.score_value = score
            it += 1
        self._pretrain_counts[layer_idx] = it

    # ------------------------------------------------------------- inference
    def output(self, x, train: bool = False) -> Array:
        """Final-layer activations (reference: MultiLayerNetwork.output)."""
        fn = self._jit_cache.get(("output", train))
        if fn is None:
            def _out(params, state, x):
                h, _, _, _ = self._forward(params, state, x, train=train,
                                           key=None, mask=None)
                return h
            fn = jax.jit(_out)
            self._jit_cache[("output", train)] = fn
        return fn(self.params, self.state, jnp.asarray(x))

    def feed_forward(self, x, train: bool = False) -> List[Array]:
        """All layer activations (reference: feedForward)."""
        acts, _, _, _ = self._forward(self.params, self.state,
                                      jnp.asarray(x), train=train, key=None,
                                      mask=None, collect=True)
        return acts

    def feed_forward_to_layer(self, layer_idx: int, x,
                              train: bool = False) -> List[Array]:
        """Activations of layers [0..layer_idx] ONLY — layers beyond the
        index are not executed (reference: feedForwardToLayer,
        MultiLayerNetwork.java:698)."""
        x = jnp.asarray(x)
        h = x.astype(self.dtype) \
            if jnp.issubdtype(x.dtype, jnp.floating) else x
        acts = []
        for i, layer in enumerate(self.layers[:layer_idx + 1]):
            name = self.layer_names[i]
            pp = self.conf.input_preprocessors.get(str(i))
            if pp is not None:
                h = pp.pre_process(h)
            h, _ = layer.apply(self.params[name],
                               self.state.get(name, {}), h, train=train)
            acts.append(h)
        return acts

    def predict(self, x) -> np.ndarray:
        """Predicted class index per example (reference:
        MultiLayerNetwork.predict)."""
        out = np.asarray(self.output(x))
        return out.argmax(axis=-1)

    def label_probabilities(self, x) -> Array:
        """Output-layer probabilities (reference: labelProbabilities)."""
        return self.output(x)

    def num_labels(self) -> int:
        """Output dimension (reference: numLabels)."""
        n = getattr(self.layers[-1], "n_out", None)
        if n is not None:
            return int(n)
        # LossLayer has no params/n_out: infer from the layer below
        for layer in reversed(self.layers[:-1]):
            n = getattr(layer, "n_out", None)
            if n is not None:
                return int(n)
        raise ValueError("cannot infer label count: no layer declares "
                         "n_out")

    def f1_score(self, x, y) -> float:
        """Macro F1 on one batch (reference: Classifier.f1Score)."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        ev = Evaluation()
        ev.eval(y, self.output(x))
        return ev.f1()

    def score_examples(self, x, y, add_regularization_terms: bool = True
                       ) -> np.ndarray:
        """Per-example loss values (reference:
        MultiLayerNetwork.scoreExamples — regularization added uniformly
        when requested). One vmapped program over _loss_fn, so the full
        forward semantics (preprocessors, dtype guards, layer state)
        match score() exactly."""
        x = jnp.asarray(x)
        y = jnp.asarray(y)

        def one(xi, yi):
            s, _ = self._loss_fn(self.params, self.state, xi[None],
                                 yi[None], None, None, train=False)
            return s

        per = jax.vmap(one)(x, y)
        if not add_regularization_terms:
            per = per - self._regularization_score(self.params)
        return np.asarray(per)

    def rnn_get_previous_state(self, layer_idx: int):
        """Stored streaming state of one RNN layer (reference:
        rnnGetPreviousState)."""
        if self._rnn_carries is None:
            return None
        return self._rnn_carries.get(self.layer_names[layer_idx])

    def rnn_set_previous_state(self, layer_idx: int, state) -> None:
        """reference: rnnSetPreviousState. On a fresh/cleared network
        the OTHER streaming layers are seeded with zero carries (a
        partial carries dict would silently disable their streaming)."""
        if self._rnn_carries is None:
            batch = int(jax.tree_util.tree_leaves(state)[0].shape[0])
            self._rnn_carries = self._init_carries(batch)
        self._rnn_carries[self.layer_names[layer_idx]] = state

    def summary(self) -> str:
        """Printable per-layer table (reference:
        MultiLayerNetwork.summary)."""
        from deeplearning4j_tpu.common import (count_params,
                                               render_summary_table)
        rows = [("idx", "name", "type", "n_params")]
        total = 0
        for i, layer in enumerate(self.layers):
            name = self.layer_names[i]
            n = count_params(self.params.get(name, {}))
            total += n
            rows.append((str(i), name, type(layer).__name__, f"{n:,}"))
        return render_summary_table(rows, total)

    def score(self, x, y=None, mask=None) -> float:
        """Mean score on a dataset/batch (reference:
        MultiLayerNetwork.score(DataSet))."""
        if y is None:
            feats, labs, fm, lm = _unpack_batch(x)
            return self.score(feats, labs, lm)
        fn = self._jit_cache.get("score")
        if fn is None:
            # Inference-mode scoring (reference: MultiLayerNetwork.score
            # delegates to score(data, training=false) — batchnorm must use
            # running stats, not the scored batch's statistics).
            def _score(params, state, x, y, mask):
                s, _ = self._loss_fn(params, state, x, y, None, mask,
                                     train=False)
                return s
            fn = jax.jit(_score)
            self._jit_cache["score"] = fn
        return float(fn(self.params, self.state, jnp.asarray(x),
                        jnp.asarray(y),
                        None if mask is None else jnp.asarray(mask)))

    def _run_evaluation(self, iterator, ev):
        """Feed every batch's predictions into an IEvaluation instance."""
        for batch in iterator:
            feats, labs, _, lmask = _unpack_batch(batch)
            out = self.output(feats)
            ev.eval(labs, out, mask=lmask)
        if hasattr(iterator, "reset"):
            iterator.reset()
        return ev

    def evaluate_regression(self, iterator):
        """Regression metrics over an iterator (reference:
        MultiLayerNetwork.evaluateRegression:2422)."""
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation
        return self._run_evaluation(iterator, RegressionEvaluation())

    def evaluate_roc(self, iterator, threshold_steps: int = 30):
        """Binary ROC over an iterator (reference:
        MultiLayerNetwork.evaluateROC:2436)."""
        from deeplearning4j_tpu.eval.roc import ROC
        return self._run_evaluation(iterator, ROC(threshold_steps))

    def evaluate_roc_multi_class(self, iterator,
                                 threshold_steps: int = 30):
        """One-vs-all ROC over an iterator (reference:
        MultiLayerNetwork.evaluateROCMultiClass:2449)."""
        from deeplearning4j_tpu.eval.roc import ROCMultiClass
        return self._run_evaluation(iterator, ROCMultiClass(threshold_steps))

    def evaluate(self, iterator):
        """Classification evaluation over an iterator (reference:
        MultiLayerNetwork.evaluate)."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        ev = self._run_evaluation(iterator, Evaluation())
        return ev

    def output_batched(self, xs) -> Array:
        """Scanned inference over a pre-staged pool: ``xs``
        [N, B, ...] -> activations [N, B, ...]. One compiled program for
        the whole pool (the inference face of fit_batched: per-batch
        dispatch stays on device), bounded memory — only the outputs are
        kept, not the pool's activations."""
        if not self._initialized:
            self.init()
        xs = jnp.asarray(xs)
        fn = self._jit_cache.get(("output-scan",))
        if fn is None:
            fn = self._make_scan_out()
            self._jit_cache[("output-scan",)] = fn
        return fn(self.params, self.state, xs)

    def _make_scan_out(self, **jit_kwargs):
        """The scanned-inference program (shared by output_batched and
        ParallelWrapper.output_batched, which adds shardings)."""
        def _scan_out(params, state, xs):
            def body(_, x):
                h, _, _, _ = self._forward(params, state, x, train=False,
                                           key=None, mask=None)
                return None, h

            return jax.lax.scan(body, None, xs)[1]

        return jax.jit(_scan_out, **jit_kwargs)

    def evaluate_batched(self, xs, ys):
        """Evaluation over a pre-staged pool [N, B, ...] — scanned
        forward, then one host-side metrics pass."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        out = np.asarray(self.output_batched(xs))
        ys = np.asarray(ys)
        ev = Evaluation()
        ev.eval(ys.reshape(-1, ys.shape[-1]),
                out.reshape(-1, out.shape[-1]))
        return ev

    # --------------------------------------------------------- rnn inference
    def rnn_clear_previous_state(self) -> None:
        self._rnn_carries = None

    def rnn_time_step(self, x) -> Array:
        """Stateful single/multi-step inference (reference: rnnTimeStep,
        MultiLayerNetwork.java:2234)."""
        for i, layer in enumerate(self.layers):
            if not getattr(layer, "supports_streaming", True):
                raise ValueError(
                    f"rnn_time_step unsupported: layer {i} "
                    f"({type(layer).__name__}) needs the full sequence "
                    "(reference: GravesBidirectionalLSTM cannot rnnTimeStep)")
        x = jnp.asarray(x)
        squeeze = x.ndim == 2  # [B, F] -> single step
        if squeeze:
            x = x[:, None, :]
        if self._rnn_carries is None:
            self._rnn_carries = self._init_carries(x.shape[0])
        h, _, _, new_carries = self._forward(
            self.params, self.state, x, train=False, key=None, mask=None,
            carries=self._rnn_carries)
        self._rnn_carries.update(new_carries)
        return h[:, 0] if squeeze else h

    # ------------------------------------------------------------ flat views
    def params_flat(self) -> Array:
        """Flat parameter vector (reference: Model.params() — the flat view
        buffer, MultiLayerNetwork.java:445)."""
        flat, _ = ravel_pytree(self.params)
        return flat

    def set_params_flat(self, flat: Array) -> None:
        _, unravel = ravel_pytree(self.params)
        self.params = unravel(jnp.asarray(flat))

    def num_params(self) -> int:
        return int(self.params_flat().shape[0])

    def clone(self) -> "MultiLayerNetwork":
        import copy
        net = MultiLayerNetwork(copy.deepcopy(self.conf))
        net.params = jax.tree_util.tree_map(lambda a: a, self.params)
        net.state = jax.tree_util.tree_map(lambda a: a, self.state)
        net.updater_state = jax.tree_util.tree_map(lambda a: a,
                                                   self.updater_state)
        net._initialized = self._initialized
        return net


def _unpack_batch(batch):
    """Accept (x, y), (x, y, fmask, lmask), or (Multi)DataSet-like
    objects (MultiDataSet carries plural features_masks/labels_masks)."""
    if hasattr(batch, "features"):
        fmask = getattr(batch, "features_mask", None)
        lmask = getattr(batch, "labels_mask", None)
        if fmask is None:
            fmask = getattr(batch, "features_masks", None)
        if lmask is None:
            lmask = getattr(batch, "labels_masks", None)
        return (batch.features, getattr(batch, "labels", None),
                fmask, lmask)
    if isinstance(batch, (tuple, list)):
        if len(batch) == 2:
            return batch[0], batch[1], None, None
        if len(batch) == 4:
            return tuple(batch)
    raise ValueError(f"Cannot unpack batch of type {type(batch)}")
