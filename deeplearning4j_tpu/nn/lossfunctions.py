"""Loss functions.

Parity with ND4J's `LossFunctions.LossFunction` enum consumed by the
reference's output layers (reference: deeplearning4j-nn/.../nn/conf/layers/
BaseOutputLayer.java `lossFunction` field). Each loss takes
``(labels, preout, activation_fn, mask)`` and returns the mean score over the
minibatch, matching the reference's per-example-then-average semantics.

All losses are written on *pre-output* + activation so that fused, numerically
stable forms (softmax-cross-entropy, sigmoid-cross-entropy) are used where the
activation/loss pair allows — the TPU-native equivalent of ND4J's fused loss
kernels.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation

Array = jax.Array

_EPS = 1e-7


def _apply_mask_and_mean(per_example: Array, mask: Optional[Array]) -> Array:
    """Average per-example scores, honoring an optional {0,1} mask.

    ``per_example`` has shape [batch] (already reduced over feature dims) or
    [batch, time] for sequence outputs; mask broadcasts against it.
    """
    if mask is None:
        return jnp.mean(per_example)
    mask = mask.astype(per_example.dtype)
    total = jnp.sum(per_example * mask)
    count = jnp.maximum(jnp.sum(mask), 1.0)
    return total / count


def _activate(preout: Array, activation) -> Array:
    return get_activation(activation)(preout)


def mcxent(labels: Array, preout: Array, activation="softmax",
           mask: Optional[Array] = None) -> Array:
    """Multi-class cross entropy. With softmax activation uses the fused
    log-softmax form (stable); otherwise -sum(y*log(p))."""
    act = activation if isinstance(activation, str) else "custom"
    if act == "softmax":
        logp = jax.nn.log_softmax(preout, axis=-1)
        per = -jnp.sum(labels * logp, axis=-1)
    else:
        p = jnp.clip(_activate(preout, activation), _EPS, 1.0 - _EPS)
        per = -jnp.sum(labels * jnp.log(p), axis=-1)
    return _apply_mask_and_mean(per, mask)


def xent(labels: Array, preout: Array, activation="sigmoid",
         mask: Optional[Array] = None) -> Array:
    """Binary cross entropy (elementwise over possibly-multilabel outputs)."""
    act = activation if isinstance(activation, str) else "custom"
    if act == "sigmoid":
        # stable: max(x,0) - x*y + log(1+exp(-|x|))
        x = preout
        per = jnp.sum(
            jnp.maximum(x, 0.0) - x * labels + jnp.log1p(jnp.exp(-jnp.abs(x))),
            axis=-1,
        )
    else:
        p = jnp.clip(_activate(preout, activation), _EPS, 1.0 - _EPS)
        per = -jnp.sum(labels * jnp.log(p) + (1 - labels) * jnp.log(1 - p),
                       axis=-1)
    return _apply_mask_and_mean(per, mask)


def mse(labels: Array, preout: Array, activation="identity",
        mask: Optional[Array] = None) -> Array:
    out = _activate(preout, activation)
    per = jnp.mean((labels - out) ** 2, axis=-1)
    return _apply_mask_and_mean(per, mask)


def l1(labels: Array, preout: Array, activation="identity",
       mask: Optional[Array] = None) -> Array:
    out = _activate(preout, activation)
    per = jnp.sum(jnp.abs(labels - out), axis=-1)
    return _apply_mask_and_mean(per, mask)


def l2(labels: Array, preout: Array, activation="identity",
       mask: Optional[Array] = None) -> Array:
    out = _activate(preout, activation)
    per = jnp.sum((labels - out) ** 2, axis=-1)
    return _apply_mask_and_mean(per, mask)


def mae(labels: Array, preout: Array, activation="identity",
        mask: Optional[Array] = None) -> Array:
    out = _activate(preout, activation)
    per = jnp.mean(jnp.abs(labels - out), axis=-1)
    return _apply_mask_and_mean(per, mask)


def mape(labels: Array, preout: Array, activation="identity",
         mask: Optional[Array] = None) -> Array:
    out = _activate(preout, activation)
    per = jnp.mean(
        100.0 * jnp.abs((labels - out) / jnp.where(labels == 0, _EPS, labels)),
        axis=-1)
    return _apply_mask_and_mean(per, mask)


def msle(labels: Array, preout: Array, activation="identity",
         mask: Optional[Array] = None) -> Array:
    out = _activate(preout, activation)
    per = jnp.mean(
        (jnp.log1p(jnp.maximum(labels, 0)) - jnp.log1p(jnp.maximum(out, 0)))
        ** 2,
        axis=-1)
    return _apply_mask_and_mean(per, mask)


def kl_divergence(labels: Array, preout: Array, activation="softmax",
                  mask: Optional[Array] = None) -> Array:
    p = jnp.clip(_activate(preout, activation), _EPS, 1.0)
    y = jnp.clip(labels, _EPS, 1.0)
    per = jnp.sum(y * (jnp.log(y) - jnp.log(p)), axis=-1)
    return _apply_mask_and_mean(per, mask)


def negativeloglikelihood(labels: Array, preout: Array, activation="softmax",
                          mask: Optional[Array] = None) -> Array:
    # In the reference NLL is MCXENT with softmax output (same math).
    return mcxent(labels, preout, activation, mask)


def poisson(labels: Array, preout: Array, activation="identity",
            mask: Optional[Array] = None) -> Array:
    out = _activate(preout, activation)
    per = jnp.sum(out - labels * jnp.log(jnp.maximum(out, _EPS)), axis=-1)
    return _apply_mask_and_mean(per, mask)


def cosine_proximity(labels: Array, preout: Array, activation="identity",
                     mask: Optional[Array] = None) -> Array:
    out = _activate(preout, activation)
    num = jnp.sum(labels * out, axis=-1)
    denom = jnp.linalg.norm(labels, axis=-1) * jnp.linalg.norm(out, axis=-1)
    per = -num / jnp.maximum(denom, _EPS)
    return _apply_mask_and_mean(per, mask)


def hinge(labels: Array, preout: Array, activation="identity",
          mask: Optional[Array] = None) -> Array:
    out = _activate(preout, activation)
    y = 2.0 * labels - 1.0  # {0,1} -> {-1,1}
    per = jnp.sum(jnp.maximum(0.0, 1.0 - y * out), axis=-1)
    return _apply_mask_and_mean(per, mask)


def squared_hinge(labels: Array, preout: Array, activation="identity",
                  mask: Optional[Array] = None) -> Array:
    out = _activate(preout, activation)
    y = 2.0 * labels - 1.0
    per = jnp.sum(jnp.maximum(0.0, 1.0 - y * out) ** 2, axis=-1)
    return _apply_mask_and_mean(per, mask)


LOSS_FUNCTIONS: dict = {
    "mcxent": mcxent,
    "negativeloglikelihood": negativeloglikelihood,
    "xent": xent,
    "mse": mse,
    "squared_loss": l2,
    "l2": l2,
    "l1": l1,
    "mean_absolute_error": mae,
    "mae": mae,
    "mean_absolute_percentage_error": mape,
    "mape": mape,
    "mean_squared_logarithmic_error": msle,
    "msle": msle,
    "kl_divergence": kl_divergence,
    "reconstruction_crossentropy": xent,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
}


def get_loss(name) -> Callable:
    if callable(name):
        return name
    key = str(name).lower()
    if key not in LOSS_FUNCTIONS:
        raise ValueError(f"Unknown loss '{name}'. Available: "
                         f"{sorted(LOSS_FUNCTIONS)}")
    return LOSS_FUNCTIONS[key]
