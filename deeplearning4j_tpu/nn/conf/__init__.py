from deeplearning4j_tpu.nn.conf.configuration import (  # noqa: F401
    NeuralNetConfiguration,
    MultiLayerConfiguration,
    ComputationGraphConfiguration,
    GraphBuilder,
    TrainingConfig,
)
from deeplearning4j_tpu.nn.conf import inputs, preprocessors  # noqa: F401
from deeplearning4j_tpu.nn.conf.inputs import InputType  # noqa: F401
