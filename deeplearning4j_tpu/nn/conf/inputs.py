"""Input types — shape metadata propagated through a network configuration.

Parity with the reference's `InputType` (reference:
deeplearning4j-nn/.../nn/conf/inputs/InputType.java): feed-forward, recurrent,
convolutional, convolutional-flat. Used for nIn inference and automatic
preprocessor insertion.

TPU-first divergence: convolutional activations are **NHWC** ([batch, height,
width, channels]), the layout XLA:TPU tiles best, instead of the reference's
NCHW. Keras import handles layout conversion at the border.
"""
from __future__ import annotations

from dataclasses import dataclass

from deeplearning4j_tpu.nn.conf.serde import register


class InputType:
    """Factory namespace, mirroring the reference's static methods."""

    @staticmethod
    def feed_forward(size: int) -> "InputTypeFeedForward":
        return InputTypeFeedForward(size=int(size))

    @staticmethod
    def recurrent(size: int, time_series_length: int = -1
                  ) -> "InputTypeRecurrent":
        return InputTypeRecurrent(size=int(size),
                                  time_series_length=int(time_series_length))

    @staticmethod
    def convolutional(height: int, width: int, channels: int
                      ) -> "InputTypeConvolutional":
        return InputTypeConvolutional(height=int(height), width=int(width),
                                      channels=int(channels))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int
                           ) -> "InputTypeConvolutionalFlat":
        return InputTypeConvolutionalFlat(height=int(height),
                                          width=int(width),
                                          channels=int(channels))


@register
@dataclass(frozen=True)
class InputTypeFeedForward:
    size: int

    def array_shape(self, batch: int):
        return (batch, self.size)


@register
@dataclass(frozen=True)
class InputTypeRecurrent:
    """Sequence input. Activations are [batch, time, size] (time-major inside
    scan loops; batch-major at the API surface)."""
    size: int
    time_series_length: int = -1

    def array_shape(self, batch: int):
        t = self.time_series_length if self.time_series_length > 0 else 1
        return (batch, t, self.size)


@register
@dataclass(frozen=True)
class InputTypeConvolutional:
    """Image input, NHWC activations."""
    height: int
    width: int
    channels: int

    def array_shape(self, batch: int):
        return (batch, self.height, self.width, self.channels)

    @property
    def flat_size(self) -> int:
        return self.height * self.width * self.channels


@register
@dataclass(frozen=True)
class InputTypeConvolutionalFlat:
    """Flattened image input [batch, h*w*c] (e.g. raw MNIST rows)."""
    height: int
    width: int
    channels: int

    @property
    def flat_size(self) -> int:
        return self.height * self.width * self.channels

    def array_shape(self, batch: int):
        return (batch, self.flat_size)
