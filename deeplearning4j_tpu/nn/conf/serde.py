"""JSON serialization registry for configuration objects.

Plays the role of the reference's Jackson polymorphic-subtype machinery
(reference: deeplearning4j-nn/.../nn/conf/MultiLayerConfiguration.java:108-126
`toJson`/`fromJson`, NeuralNetConfiguration.mapper:123, ReflectionsHelper
subtype scanning). Every serializable config class registers under its class
name; ``to_dict``/``from_dict`` recurse over dataclass fields, tagging each
object with ``"@class"`` so round-trips reconstruct exact subtypes. Custom
user layers register the same way (the reference's custom-layer story).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Type

_REGISTRY: Dict[str, Type] = {}


def register(cls):
    """Class decorator: make a dataclass JSON round-trippable by name."""
    _REGISTRY[cls.__name__] = cls
    return cls


def get_registered(name: str):
    if name not in _REGISTRY:
        raise ValueError(
            f"Unknown config class '{name}'. Registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def to_dict(obj: Any) -> Any:
    """Recursively convert a (possibly nested) config object to plain JSON
    types, tagging registered dataclasses with @class."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {"@class": type(obj).__name__}
        for f in dataclasses.fields(obj):
            out[f.name] = to_dict(getattr(obj, f.name))
        return out
    if isinstance(obj, dict):
        return {str(k): to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    if hasattr(obj, "item"):  # numpy / jax scalar
        return obj.item()
    raise TypeError(f"Cannot serialize {type(obj)} to config JSON")


def from_dict(data: Any) -> Any:
    """Inverse of :func:`to_dict`."""
    if isinstance(data, dict):
        if "@class" in data:
            cls = get_registered(data["@class"])
            kwargs = {}
            names = {f.name for f in dataclasses.fields(cls)}
            for k, v in data.items():
                if k == "@class":
                    continue
                if k in names:
                    kwargs[k] = from_dict(v)
            obj = cls(**kwargs)
            return obj
        return {k: from_dict(v) for k, v in data.items()}
    if isinstance(data, list):
        return [from_dict(v) for v in data]
    return data


def to_yaml(obj: Any) -> str:
    """YAML face of the registry — the reference's config DSL is
    dual-format (reference: MultiLayerConfiguration.java:79 `toYaml` /
    :108-126 both formats share one object mapper pipeline); here both
    formats share to_dict/from_dict, so the @class-tagged document is
    identical modulo syntax."""
    import yaml

    return yaml.safe_dump(to_dict(obj), sort_keys=False,
                          default_flow_style=False)


def from_yaml(s: str) -> Any:
    """Inverse of :func:`to_yaml`."""
    import yaml

    return from_dict(yaml.safe_load(s))
