"""Network configuration DSL.

Parity with the reference's configuration layer (reference:
deeplearning4j-nn/.../nn/conf/NeuralNetConfiguration.java:73 Builder:495
ListBuilder:206; MultiLayerConfiguration.java toJson:108 fromJson:122;
ComputationGraphConfiguration + GraphBuilder): global hyperparameters with
per-layer overrides, sequential and DAG topologies, InputType-driven shape
inference with automatic preprocessor insertion, and JSON round-trip.

Pythonic builder instead of Java's nested Builder classes::

    conf = (NeuralNetConfiguration(seed=12345, updater="adam",
                                   learning_rate=1e-3, weight_init="xavier")
            .list(DenseLayer(n_out=500, activation="relu"),
                  OutputLayer(n_out=10, activation="softmax",
                              loss_function="mcxent"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1)))
    net = MultiLayerNetwork(conf)

The configuration is pure metadata — models trace it into a single jitted XLA
program (contrast the reference, where configs instantiate stateful Java layer
objects executing eagerly, MultiLayerNetwork.java:462).
"""
from __future__ import annotations

import copy
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from deeplearning4j_tpu.nn.conf import inputs as it
from deeplearning4j_tpu.nn.conf.preprocessors import (InputPreProcessor,
                                                      infer_preprocessor)
from deeplearning4j_tpu.nn.conf.serde import (from_dict, register, to_dict)
from deeplearning4j_tpu.nn.layers.base import Layer


@register
@dataclass
class TrainingConfig:
    """Global training hyperparameters (the reference's NeuralNetConfiguration
    scalar fields + Updater enum + LearningRatePolicy,
    NeuralNetConfiguration.java:73-170)."""
    seed: int = 12345
    optimization_algo: str = "stochastic_gradient_descent"
    updater: str = "sgd"
    learning_rate: float = 1e-1  # reference default, NeuralNetConfiguration.java:500
    bias_learning_rate: Optional[float] = None
    momentum: float = 0.5
    # adam / rmsprop / adadelta hyperparams (ND4J learning-pkg defaults)
    adam_mean_decay: float = 0.9
    adam_var_decay: float = 0.999
    epsilon: float = 1e-8
    rho: float = 0.95
    rms_decay: float = 0.95
    # lr schedule (reference: LearningRatePolicy enum + schedule map :106)
    lr_policy: str = "none"  # none|exponential|inverse|poly|sigmoid|step|schedule
    lr_policy_decay_rate: float = 0.0
    lr_policy_steps: float = 1.0
    lr_policy_power: float = 1.0
    lr_schedule: Optional[Dict[str, float]] = None  # iteration -> lr
    # regularization + gradient treatment
    l1: float = 0.0
    l2: float = 0.0
    gradient_normalization: str = "none"
    gradient_normalization_threshold: float = 1.0
    minimize: bool = True
    max_num_line_search_iterations: int = 5
    num_iterations: int = 1  # reference: fits each minibatch N times
    dtype: str = "float32"


class NeuralNetConfiguration:
    """Entry-point builder. Keyword args cover the reference Builder's
    methods; extra layer-default fields (activation, weight_init, dropout,
    dist) are applied to layers that leave them unset."""

    def __init__(self, *, seed: int = 12345, activation: str = "sigmoid",
                 weight_init: str = "xavier", dist: Optional[dict] = None,
                 dropout: float = 0.0, **training_kwargs):
        self.training = TrainingConfig(seed=seed, **training_kwargs)
        self.default_activation = activation
        self.default_weight_init = weight_init
        self.default_dist = dist
        self.default_dropout = dropout

    # -- defaults ----------------------------------------------------------
    def _apply_defaults(self, layer: Layer) -> Layer:
        layer = copy.deepcopy(layer)
        if getattr(layer, "activation", "__missing__") is None:
            layer.activation = self.default_activation
        if getattr(layer, "weight_init", "__missing__") is None:
            layer.weight_init = self.default_weight_init
        if getattr(layer, "dist", "__missing__") is None:
            layer.dist = self.default_dist
        if layer.dropout is None:
            layer.dropout = self.default_dropout
        if layer.l1 is None:
            layer.l1 = self.training.l1
        if layer.l2 is None:
            layer.l2 = self.training.l2
        if layer.learning_rate is None:
            layer.learning_rate = self.training.learning_rate
        if layer.bias_learning_rate is None:
            layer.bias_learning_rate = (self.training.bias_learning_rate
                                        or layer.learning_rate)
        inner = getattr(layer, "inner", None)
        if inner is not None:
            layer.inner = self._apply_defaults(inner)
        return layer

    # -- sequential --------------------------------------------------------
    def list(self, *layers: Layer) -> "MultiLayerConfiguration":
        """Build a sequential configuration (reference: Builder.list() ->
        ListBuilder, NeuralNetConfiguration.java:206)."""
        resolved = [self._apply_defaults(l) for l in layers]
        return MultiLayerConfiguration(layers=resolved,
                                       training=copy.deepcopy(self.training))

    # -- DAG ---------------------------------------------------------------
    def graph_builder(self) -> "GraphBuilder":
        return GraphBuilder(self)


@register
@dataclass
class MultiLayerConfiguration:
    """Sequential network configuration (reference:
    nn/conf/MultiLayerConfiguration.java)."""
    layers: List[Layer] = field(default_factory=list)
    training: TrainingConfig = field(default_factory=TrainingConfig)
    input_type: Optional[Any] = None
    input_preprocessors: Dict[str, Any] = field(default_factory=dict)
    backprop_type: str = "standard"  # 'standard' | 'tbptt'
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    pretrain: bool = False
    _shapes_resolved: bool = False

    # -- fluent setters ----------------------------------------------------
    def set_input_type(self, input_type) -> "MultiLayerConfiguration":
        self.input_type = input_type
        return self

    def backprop_type_tbptt(self, fwd_length: int = 20,
                            back_length: int = 20
                            ) -> "MultiLayerConfiguration":
        self.backprop_type = "tbptt"
        self.tbptt_fwd_length = fwd_length
        self.tbptt_back_length = back_length
        return self

    def set_pretrain(self, pretrain: bool) -> "MultiLayerConfiguration":
        self.pretrain = pretrain
        return self

    def set_input_preprocessor(self, layer_index: int,
                               preproc) -> "MultiLayerConfiguration":
        self.input_preprocessors[str(layer_index)] = preproc
        return self

    # -- shape inference ---------------------------------------------------
    def resolve_shapes(self) -> None:
        """Walk the layers once: auto-insert preprocessors where the
        activation family changes, set each layer's n_in (reference:
        InputType propagation in MultiLayerConfiguration.Builder /
        InputTypeUtil)."""
        if self._shapes_resolved:
            return
        if self.input_type is None:
            # Reference behavior: setInputType is optional when the user sets
            # nIn on every layer (ListBuilder only auto-wires when an
            # InputType is given). Recover the initial InputType from the
            # first layer's declared n_in so downstream layers still chain.
            first = self.layers[0] if self.layers else None
            if first is not None and getattr(first, "inner", None) is not None:
                first = first.inner  # FrozenLayer-style wrappers
            n_in = getattr(first, "n_in", None)
            if first is None or n_in is None:
                raise ValueError(
                    "input_type must be set (set_input_type) or the first "
                    "layer must specify n_in explicitly")
            if first.input_family == "rnn":
                self.input_type = it.InputType.recurrent(n_in)
            elif first.input_family == "ff":
                self.input_type = it.InputType.feed_forward(n_in)
            else:
                raise ValueError(
                    "convolutional networks need set_input_type(...) — "
                    "kernel shape inference requires height/width/channels")
        current = self.input_type
        for i, layer in enumerate(self.layers):
            key = str(i)
            if key not in self.input_preprocessors:
                pre = infer_preprocessor(current, layer.input_family)
                if pre is not None:
                    self.input_preprocessors[key] = pre
            if key in self.input_preprocessors:
                current = self.input_preprocessors[key].output_type(current)
            current = layer.update_input_type(current)
        self._shapes_resolved = True

    def layer_name(self, i: int) -> str:
        return self.layers[i].name or f"layer_{i}"

    # -- serde -------------------------------------------------------------
    def to_json(self) -> str:
        return json.dumps(to_dict(self), indent=2)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        obj = from_dict(json.loads(s))
        if not isinstance(obj, MultiLayerConfiguration):
            raise ValueError("JSON does not encode a MultiLayerConfiguration")
        return obj

    def to_yaml(self) -> str:
        """Reference: MultiLayerConfiguration.java:79 (toYaml)."""
        from deeplearning4j_tpu.nn.conf.serde import to_yaml
        return to_yaml(self)

    @staticmethod
    def from_yaml(s: str) -> "MultiLayerConfiguration":
        from deeplearning4j_tpu.nn.conf.serde import from_yaml
        obj = from_yaml(s)
        if not isinstance(obj, MultiLayerConfiguration):
            raise ValueError("YAML does not encode a MultiLayerConfiguration")
        return obj


@register
@dataclass
class GraphVertexSpec:
    """One node in the DAG: a Layer or a GraphVertex plus its input names."""
    vertex: Any = None
    inputs: List[str] = field(default_factory=list)


@register
@dataclass
class ComputationGraphConfiguration:
    """DAG configuration (reference:
    nn/conf/ComputationGraphConfiguration.java + GraphBuilder)."""
    network_inputs: List[str] = field(default_factory=list)
    network_outputs: List[str] = field(default_factory=list)
    vertices: Dict[str, GraphVertexSpec] = field(default_factory=dict)
    training: TrainingConfig = field(default_factory=TrainingConfig)
    input_types: Dict[str, Any] = field(default_factory=dict)
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    pretrain: bool = False

    def to_json(self) -> str:
        return json.dumps(to_dict(self), indent=2)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        obj = from_dict(json.loads(s))
        if not isinstance(obj, ComputationGraphConfiguration):
            raise ValueError(
                "JSON does not encode a ComputationGraphConfiguration")
        return obj

    def to_yaml(self) -> str:
        """Reference: ComputationGraphConfiguration toYaml (same dual
        format contract as MultiLayerConfiguration.java:79)."""
        from deeplearning4j_tpu.nn.conf.serde import to_yaml
        return to_yaml(self)

    @staticmethod
    def from_yaml(s: str) -> "ComputationGraphConfiguration":
        from deeplearning4j_tpu.nn.conf.serde import from_yaml
        obj = from_yaml(s)
        if not isinstance(obj, ComputationGraphConfiguration):
            raise ValueError(
                "YAML does not encode a ComputationGraphConfiguration")
        return obj

    def topological_order(self) -> List[str]:
        """Kahn's algorithm over vertex dependencies (reference:
        ComputationGraph.topologicalSortOrder(), ComputationGraph.java:888)."""
        indeg: Dict[str, int] = {}
        dependents: Dict[str, List[str]] = {}
        for name, spec in self.vertices.items():
            indeg[name] = 0
            for inp in spec.inputs:
                if inp in self.network_inputs:
                    continue
                indeg[name] += 1
                dependents.setdefault(inp, []).append(name)
        ready = sorted(n for n, d in indeg.items() if d == 0)
        order: List[str] = []
        while ready:
            n = ready.pop(0)
            order.append(n)
            for m in dependents.get(n, []):
                indeg[m] -= 1
                if indeg[m] == 0:
                    ready.append(m)
        if len(order) != len(self.vertices):
            cyc = set(self.vertices) - set(order)
            raise ValueError(f"Graph has a cycle involving {sorted(cyc)}")
        return order


class GraphBuilder:
    """Fluent DAG builder (reference:
    ComputationGraphConfiguration.GraphBuilder)."""

    def __init__(self, nn_conf: NeuralNetConfiguration):
        self._nn = nn_conf
        self._conf = ComputationGraphConfiguration(
            training=copy.deepcopy(nn_conf.training))

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._conf.network_inputs.extend(names)
        return self

    def set_input_types(self, **types) -> "GraphBuilder":
        self._conf.input_types.update(types)
        return self

    def add_layer(self, name: str, layer: Layer,
                  *inputs: str) -> "GraphBuilder":
        layer = self._nn._apply_defaults(layer)
        layer.name = name
        self._conf.vertices[name] = GraphVertexSpec(vertex=layer,
                                                    inputs=list(inputs))
        return self

    def add_vertex(self, name: str, vertex,
                   *inputs: str) -> "GraphBuilder":
        self._conf.vertices[name] = GraphVertexSpec(vertex=vertex,
                                                    inputs=list(inputs))
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._conf.network_outputs.extend(names)
        return self

    def backprop_type_tbptt(self, fwd_length: int = 20,
                            back_length: int = 20) -> "GraphBuilder":
        self._conf.backprop_type = "tbptt"
        self._conf.tbptt_fwd_length = fwd_length
        self._conf.tbptt_back_length = back_length
        return self

    def build(self) -> ComputationGraphConfiguration:
        if not self._conf.network_inputs:
            raise ValueError("GraphBuilder: no inputs declared")
        if not self._conf.network_outputs:
            raise ValueError("GraphBuilder: no outputs declared")
        self._conf.topological_order()  # validates acyclicity + names
        return self._conf
