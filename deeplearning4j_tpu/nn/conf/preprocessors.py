"""Input preprocessors — shape adapters auto-inserted between layer families.

Parity with the reference's `nn/conf/preprocessor/` (reference:
CnnToFeedForwardPreProcessor.java, FeedForwardToRnnPreProcessor.java,
RnnToCnnPreProcessor.java, etc. — 12 classes). In the reference each carries a
hand-written `preProcess` and `backprop`; here only the forward reshape is
needed (autodiff provides the backward), and XLA folds reshapes into the
surrounding program for free.

Activations layouts: FF [B, F] — RNN [B, T, F] — CNN [B, H, W, C] (NHWC).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import inputs as it
from deeplearning4j_tpu.nn.conf.serde import register

Array = jax.Array


class InputPreProcessor:
    """Base: pre_process(x) and output_type(input_type)."""

    def pre_process(self, x: Array) -> Array:  # pragma: no cover - interface
        raise NotImplementedError

    def output_type(self, input_type):  # pragma: no cover - interface
        raise NotImplementedError


@register
@dataclass(frozen=True)
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def pre_process(self, x: Array) -> Array:
        return x.reshape(x.shape[0], -1)

    def output_type(self, input_type):
        assert isinstance(input_type, it.InputTypeConvolutional), input_type
        return it.InputType.feed_forward(input_type.flat_size)


@register
@dataclass(frozen=True)
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def pre_process(self, x: Array) -> Array:
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, input_type):
        return it.InputType.convolutional(self.height, self.width,
                                          self.channels)


@register
@dataclass(frozen=True)
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    """[B*T, F] or [B, F] -> [B, T, F]: in this framework dense layers operate
    on the trailing axis, so FF activations inside an RNN pipeline stay
    [B, T, F] and this preprocessor is an identity marker kept for config
    parity with the reference."""

    def pre_process(self, x: Array) -> Array:
        return x

    def output_type(self, input_type):
        if isinstance(input_type, it.InputTypeFeedForward):
            return it.InputType.recurrent(input_type.size)
        return input_type


@register
@dataclass(frozen=True)
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """Marker inverse of FeedForwardToRnnPreProcessor (identity here)."""

    def pre_process(self, x: Array) -> Array:
        return x

    def output_type(self, input_type):
        if isinstance(input_type, it.InputTypeRecurrent):
            return it.InputType.feed_forward(input_type.size)
        return input_type


@register
@dataclass(frozen=True)
class CnnToRnnPreProcessor(InputPreProcessor):
    """[B, H, W, C] -> [B, T=H*W? no: T from caller]. The reference treats the
    conv output depth*h*w as the per-timestep feature when bridging CNN->RNN
    over video-like inputs; here we flatten spatial dims to features and add a
    length-1 time axis."""
    height: int = 0
    width: int = 0
    channels: int = 0

    def pre_process(self, x: Array) -> Array:
        return x.reshape(x.shape[0], 1, -1)

    def output_type(self, input_type):
        assert isinstance(input_type, it.InputTypeConvolutional)
        return it.InputType.recurrent(input_type.flat_size, 1)


@register
@dataclass(frozen=True)
class RnnToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def pre_process(self, x: Array) -> Array:
        b, t, f = x.shape
        return x.reshape(b * t, self.height, self.width, self.channels)

    def output_type(self, input_type):
        return it.InputType.convolutional(self.height, self.width,
                                          self.channels)


@register
@dataclass(frozen=True)
class CnnFlatToCnnPreProcessor(InputPreProcessor):
    """[B, h*w*c] (e.g. raw MNIST rows) -> [B, H, W, C]."""
    height: int = 0
    width: int = 0
    channels: int = 0

    def pre_process(self, x: Array) -> Array:
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, input_type):
        return it.InputType.convolutional(self.height, self.width,
                                          self.channels)


def infer_preprocessor(from_type, to_family: str):
    """Auto-insert logic, mirroring the reference's
    `Layer.getPreProcessorForInputType` dispatch: given the producing layer's
    output InputType and the consuming layer family ('ff', 'cnn', 'rnn'),
    return a preprocessor or None."""
    if to_family == "ff":
        if isinstance(from_type, it.InputTypeConvolutional):
            return CnnToFeedForwardPreProcessor(from_type.height,
                                                from_type.width,
                                                from_type.channels)
        if isinstance(from_type, it.InputTypeConvolutionalFlat):
            return None  # already flat
        return None
    if to_family == "cnn":
        if isinstance(from_type, it.InputTypeConvolutionalFlat):
            return CnnFlatToCnnPreProcessor(from_type.height, from_type.width,
                                            from_type.channels)
        if isinstance(from_type, it.InputTypeFeedForward):
            return None  # requires explicit FeedForwardToCnnPreProcessor
        return None
    if to_family == "rnn":
        if isinstance(from_type, it.InputTypeFeedForward):
            return FeedForwardToRnnPreProcessor()
        if isinstance(from_type, it.InputTypeConvolutional):
            return CnnToRnnPreProcessor(from_type.height, from_type.width,
                                        from_type.channels)
        return None
    return None
