"""Activation functions.

Capability parity with the reference's ND4J activation set consumed by DL4J
layer configs (reference: deeplearning4j-nn/.../nn/conf/layers/*.java
`activation` field; the functions themselves live in external ND4J). Here they
are plain jax functions — XLA fuses them into the surrounding matmul, which is
the TPU-native replacement for ND4J's per-op transform kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def identity(x: Array) -> Array:
    return x


def sigmoid(x: Array) -> Array:
    return jax.nn.sigmoid(x)


def tanh(x: Array) -> Array:
    return jnp.tanh(x)


def relu(x: Array) -> Array:
    return jax.nn.relu(x)


def leakyrelu(x: Array, alpha: float = 0.01) -> Array:
    return jax.nn.leaky_relu(x, negative_slope=alpha)


def elu(x: Array, alpha: float = 1.0) -> Array:
    return jax.nn.elu(x, alpha=alpha)


def selu(x: Array) -> Array:
    return jax.nn.selu(x)


def softplus(x: Array) -> Array:
    return jax.nn.softplus(x)


def softsign(x: Array) -> Array:
    return jax.nn.soft_sign(x)


def softmax(x: Array) -> Array:
    return jax.nn.softmax(x, axis=-1)


def hardtanh(x: Array) -> Array:
    return jnp.clip(x, -1.0, 1.0)


def hardsigmoid(x: Array) -> Array:
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def cube(x: Array) -> Array:
    return x * x * x


def rationaltanh(x: Array) -> Array:
    # 1.7159 * tanh(2x/3) approximation via rational function, as in ND4J.
    a = jnp.abs(2.0 * x / 3.0)
    rational = 1.0 - 1.0 / (1.0 + a + a * a + 1.41645 * a ** 4)
    return 1.7159 * jnp.sign(x) * rational


def rectifiedtanh(x: Array) -> Array:
    return jnp.maximum(0.0, jnp.tanh(x))


def gelu(x: Array) -> Array:
    """Net-new vs the reference (needed by transformer layers)."""
    return jax.nn.gelu(x)


def swish(x: Array) -> Array:
    return jax.nn.silu(x)


def leakyrelu_derivative_free(x: Array) -> Array:  # pragma: no cover - alias
    return leakyrelu(x)


ACTIVATIONS = {
    "identity": identity,
    "linear": identity,
    "sigmoid": sigmoid,
    "tanh": tanh,
    "relu": relu,
    "leakyrelu": leakyrelu,
    "elu": elu,
    "selu": selu,
    "softplus": softplus,
    "softsign": softsign,
    "softmax": softmax,
    "hardtanh": hardtanh,
    "hardsigmoid": hardsigmoid,
    "cube": cube,
    "rationaltanh": rationaltanh,
    "rectifiedtanh": rectifiedtanh,
    "gelu": gelu,
    "swish": swish,
}


def get_activation(name):
    """Resolve an activation by name (or pass a callable through)."""
    if callable(name):
        return name
    key = str(name).lower()
    if key not in ACTIVATIONS:
        raise ValueError(
            f"Unknown activation '{name}'. Available: {sorted(ACTIVATIONS)}"
        )
    return ACTIVATIONS[key]
