"""Weight initialization schemes.

Parity with the reference's `WeightInit` enum + `WeightInitUtil`
(reference: deeplearning4j-nn/.../nn/weights/WeightInit.java,
WeightInitUtil.java): XAVIER, XAVIER_UNIFORM, XAVIER_FAN_IN, RELU,
RELU_UNIFORM, UNIFORM, SIGMOID_UNIFORM, ZERO, ONES, IDENTITY, DISTRIBUTION,
VAR_SCALING variants. Uses jax PRNG keys instead of ND4J's global RNG.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

Array = jax.Array


def init_weights(key: jax.Array, shape: Sequence[int], fan_in: float,
                 fan_out: float, scheme: str = "xavier",
                 distribution: Optional[dict] = None,
                 dtype=jnp.float32) -> Array:
    """Initialize a weight tensor.

    ``fan_in``/``fan_out`` are passed explicitly because for conv kernels they
    include the receptive-field size (kh*kw*c), mirroring the reference's
    `WeightInitUtil.initWeights(fanIn, fanOut, shape, ...)` signature.
    """
    scheme = str(scheme).lower()
    shape = tuple(int(s) for s in shape)
    if scheme == "zero":
        return jnp.zeros(shape, dtype)
    if scheme == "ones":
        return jnp.ones(shape, dtype)
    if scheme == "identity":
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("IDENTITY init requires a square 2-D shape")
        return jnp.eye(shape[0], dtype=dtype)
    if scheme == "xavier":
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, shape, dtype)
    if scheme == "xavier_uniform":
        a = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "xavier_fan_in":
        std = math.sqrt(1.0 / fan_in)
        return std * jax.random.normal(key, shape, dtype)
    if scheme == "xavier_legacy":
        std = math.sqrt(1.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, shape, dtype)
    if scheme == "relu":
        std = math.sqrt(2.0 / fan_in)
        return std * jax.random.normal(key, shape, dtype)
    if scheme == "relu_uniform":
        a = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "sigmoid_uniform":
        a = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "uniform":
        # reference: U(-a, a) with a = 1/sqrt(fanIn)
        a = 1.0 / math.sqrt(fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "normal_in":
        std = math.sqrt(1.0 / fan_in)
        return std * jax.random.normal(key, shape, dtype)
    if scheme == "normal_out":
        std = math.sqrt(1.0 / fan_out)
        return std * jax.random.normal(key, shape, dtype)
    if scheme == "normal_avg":
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, shape, dtype)
    if scheme == "distribution":
        return _sample_distribution(key, shape, distribution or {}, dtype)
    raise ValueError(f"Unknown weight init scheme '{scheme}'")


def _sample_distribution(key: jax.Array, shape, dist: dict,
                         dtype) -> Array:
    """Sample from a serialized distribution spec.

    Mirrors the reference's `nn/conf/distribution/` classes:
    NormalDistribution(mean, std), UniformDistribution(lower, upper),
    GaussianDistribution == Normal, BinomialDistribution(n, p).
    """
    kind = str(dist.get("type", "normal")).lower()
    if kind in ("normal", "gaussian"):
        mean = float(dist.get("mean", 0.0))
        std = float(dist.get("std", 1.0))
        return mean + std * jax.random.normal(key, shape, dtype)
    if kind == "uniform":
        lo = float(dist.get("lower", -1.0))
        hi = float(dist.get("upper", 1.0))
        return jax.random.uniform(key, shape, dtype, lo, hi)
    if kind == "binomial":
        n = int(dist.get("n", 1))
        p = float(dist.get("p", 0.5))
        return jax.random.binomial(key, n, p, shape).astype(dtype)
    raise ValueError(f"Unknown distribution type '{kind}'")
