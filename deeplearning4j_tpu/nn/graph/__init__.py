from deeplearning4j_tpu.nn.graph.computation_graph import ComputationGraph  # noqa: F401
from deeplearning4j_tpu.nn.graph.vertices import (  # noqa: F401
    GraphVertex,
    MergeVertex,
    ElementWiseVertex,
    SubsetVertex,
    StackVertex,
    UnstackVertex,
    ScaleVertex,
    L2Vertex,
    L2NormalizeVertex,
    PreprocessorVertex,
    LastTimeStepVertex,
    DuplicateToTimeSeriesVertex,
    ReshapeVertex,
)
