"""ComputationGraph — the DAG model.

Parity with the reference's ComputationGraph (reference:
deeplearning4j-nn/.../nn/graph/ComputationGraph.java, 2,447 LoC:
topologicalSortOrder():888, fit(DataSetIterator):701,
fit(MultiDataSetIterator):783, multi-input/multi-output execution). Executes
vertices in topological order inside ONE traced function; forward + all
output losses + backward + update jit into a single XLA program.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from deeplearning4j_tpu.common import promote_score
from deeplearning4j_tpu.nn.conf.configuration import (
    ComputationGraphConfiguration)
from deeplearning4j_tpu.nn.conf.preprocessors import infer_preprocessor
from deeplearning4j_tpu.nn.graph.vertices import GraphVertex
from deeplearning4j_tpu.nn.layers.base import Layer, apply_dropout
from deeplearning4j_tpu.nn.layers.misc import FrozenLayer
from deeplearning4j_tpu.nn.multilayer import _dtype_of, _unpack_batch
from deeplearning4j_tpu.train.updaters import (apply_updater,
                                               init_updater_state)

Array = jax.Array


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.topo = conf.topological_order()
        self.dtype = _dtype_of(conf.training.dtype)
        self.params: Dict[str, Dict[str, Array]] = {}
        self.state: Dict[str, Dict[str, Array]] = {}
        self.updater_state: Dict[str, Any] = {}
        self.iteration_count = 0
        self.epoch_count = 0
        self.listeners: List[Any] = []
        self.score_value = float("nan")
        self._jit_cache: Dict[Any, Any] = {}
        self._solver = None
        self._pretrain_counts: Dict[Any, int] = {}
        self._preprocessors: Dict[str, Any] = {}
        self._initialized = False
        self._resolve_shapes()

    # ---------------------------------------------------------------- shapes
    def _resolve_shapes(self) -> None:
        """Propagate InputTypes through the topo order, set layer n_in, and
        auto-insert preprocessors on family changes (reference:
        ComputationGraphConfiguration.addPreProcessors)."""
        types: Dict[str, Any] = dict(self.conf.input_types)
        if not types:
            # no declared input types: layers must carry explicit n_in
            for name in self.topo:
                spec = self.conf.vertices[name]
                v = spec.vertex
                if isinstance(v, Layer) and getattr(v, "n_in", None) is None \
                        and type(v).__name__ not in ("ActivationLayer",):
                    pass
            return
        for name in self.topo:
            spec = self.conf.vertices[name]
            v = spec.vertex
            in_types = [types[i] for i in spec.inputs if i in types]
            if not in_types:
                continue
            if isinstance(v, Layer):
                t = in_types[0]
                pre = infer_preprocessor(t, v.input_family)
                if pre is not None:
                    self._preprocessors[name] = pre
                    t = pre.output_type(t)
                types[name] = v.update_input_type(t)
            else:
                types[name] = v.output_type(in_types)
        self.resolved_types = types

    # ------------------------------------------------------------------ init
    def init(self, seed: Optional[int] = None) -> "ComputationGraph":
        seed = self.conf.training.seed if seed is None else seed
        root = jax.random.PRNGKey(seed)
        for i, name in enumerate(self.topo):
            v = self.conf.vertices[name].vertex
            if isinstance(v, Layer):
                key = jax.random.fold_in(root, i)
                self.params[name] = v.init_params(key, self.dtype)
                self.state[name] = v.init_state(self.dtype)
            else:
                self.params[name] = {}
                self.state[name] = {}
        self.updater_state = init_updater_state(self.conf.training,
                                                self.params)
        self._initialized = True
        return self

    def set_listeners(self, *listeners) -> None:
        self.listeners = list(listeners)

    # --------------------------------------------------------------- forward
    def _apply_layer_vertex(self, v, name, params, state, h, *, train,
                            lkey, mask, carries, new_state, new_carries):
        """Run one Layer vertex: scan_sequence from the given carry when
        streaming/TBPTT, plain apply otherwise. Shared by _forward and
        _forward_preout so the dispatch can't drift."""
        if carries is not None and name in carries \
                and hasattr(v, "scan_sequence"):
            h, carry = v.scan_sequence(params[name], h,
                                       carry=carries[name], mask=mask)
            new_carries[name] = carry
            new_state[name] = state.get(name, {})
        else:
            h, st = v.apply(params[name], state.get(name, {}), h,
                            train=train, key=lkey, mask=mask)
            new_state[name] = st
        return h

    def _forward(self, params, state, inputs: Dict[str, Array], *,
                 train: bool, key, masks: Optional[Dict[str, Array]] = None,
                 carries: Optional[Dict[str, Any]] = None):
        values: Dict[str, Array] = {}
        for k, v in inputs.items():
            values[k] = v.astype(self.dtype) \
                if jnp.issubdtype(v.dtype, jnp.floating) else v
        new_state: Dict[str, Any] = {}
        new_carries: Dict[str, Any] = {}
        masks = masks or {}
        for i, name in enumerate(self.topo):
            spec = self.conf.vertices[name]
            v = spec.vertex
            ins = [values[n] for n in spec.inputs]
            in_masks = [masks.get(n) for n in spec.inputs]
            if isinstance(v, Layer):
                h = ins[0]
                pre = self._preprocessors.get(name)
                if pre is not None:
                    h = pre.pre_process(h)
                lkey = jax.random.fold_in(key, i) if key is not None else None
                if train and (v.dropout or 0.0) > 0 and lkey is not None:
                    h = apply_dropout(h, v.dropout, lkey)
                h = self._apply_layer_vertex(
                    v, name, params, state, h, train=train, lkey=lkey,
                    mask=in_masks[0], carries=carries,
                    new_state=new_state, new_carries=new_carries)
                values[name] = h
                if in_masks[0] is not None and v.family == "rnn":
                    masks[name] = in_masks[0]
            else:
                values[name] = v.apply(ins, masks=in_masks)
                new_state[name] = state.get(name, {})
        if carries is not None:
            return values, new_state, new_carries
        return values, new_state

    def _loss_fn(self, params, state, inputs, labels: Dict[str, Array], key,
                 masks=None, train=True):
        values, new_state = self._forward_preout(params, state, inputs,
                                                 key=key, masks=masks,
                                                 train=train)
        total = jnp.asarray(0.0)
        for out_name in self.conf.network_outputs:
            layer = self.conf.vertices[out_name].vertex
            h_in, mask = values[out_name]
            total = total + promote_score(layer.loss(params[out_name], h_in,
                                                labels[out_name], mask))
        total = total + self._regularization_score(params)
        return total, new_state

    def _forward_preout(self, params, state, inputs, *, key, masks=None,
                        train=True, carries=None):
        """Forward in train mode, but for output layers record their INPUT
        (pre-layer activation) so the loss can use fused pre-output forms.
        With ``carries`` (name -> RNN carry), recurrent layers run
        `scan_sequence` from the given state and the new carries are
        returned — the TBPTT/streaming path (reference:
        ComputationGraph.doTruncatedBPTT:2042 / rnnTimeStep)."""
        values: Dict[str, Array] = {}
        for k, v in inputs.items():
            values[k] = v.astype(self.dtype) \
                if jnp.issubdtype(v.dtype, jnp.floating) else v
        new_state: Dict[str, Any] = {}
        new_carries: Dict[str, Any] = {}
        masks = dict(masks or {})
        out_records: Dict[str, Tuple[Array, Optional[Array]]] = {}
        outputs = set(self.conf.network_outputs)
        for i, name in enumerate(self.topo):
            spec = self.conf.vertices[name]
            v = spec.vertex
            ins = [values[n] for n in spec.inputs]
            in_masks = [masks.get(n) for n in spec.inputs]
            if isinstance(v, Layer):
                h = ins[0]
                pre = self._preprocessors.get(name)
                if pre is not None:
                    h = pre.pre_process(h)
                lkey = jax.random.fold_in(key, i) if key is not None else None
                if train and (v.dropout or 0.0) > 0 and lkey is not None:
                    h = apply_dropout(h, v.dropout, lkey)
                if name in outputs and hasattr(v, "loss"):
                    out_records[name] = (h, in_masks[0])
                h = self._apply_layer_vertex(
                    v, name, params, state, h, train=train, lkey=lkey,
                    mask=in_masks[0], carries=carries,
                    new_state=new_state, new_carries=new_carries)
                values[name] = h
                if in_masks[0] is not None and v.family == "rnn":
                    masks[name] = in_masks[0]
            else:
                values[name] = v.apply(ins, masks=in_masks)
                new_state[name] = state.get(name, {})
        for name in outputs:
            if name not in out_records:
                raise ValueError(f"Output '{name}' is not a loss-bearing "
                                 f"layer")
        if carries is not None:
            return out_records, new_state, new_carries
        return out_records, new_state

    def _regularization_score(self, params) -> Array:
        total = jnp.asarray(0.0)
        for name in self.topo:
            v = self.conf.vertices[name].vertex
            if not isinstance(v, Layer):
                continue
            l1 = v.l1 or 0.0
            l2 = v.l2 or 0.0
            if (l1 == 0.0 and l2 == 0.0) or not params.get(name):
                continue
            for k in v.weight_param_keys():
                if k not in params[name]:
                    continue
                w = promote_score(params[name][k])
                if l2 > 0:
                    total = total + 0.5 * l2 * jnp.sum(w * w)
                if l1 > 0:
                    total = total + l1 * jnp.sum(jnp.abs(w))
        return total

    # ------------------------------------------------------------------- fit
    def _lr_multipliers(self):
        base = self.conf.training.learning_rate
        out = {}
        for name in self.topo:
            v = self.conf.vertices[name].vertex
            lr = getattr(v, "learning_rate", None)
            # explicit 0.0 is a valid per-layer LR (freezing) — test None
            out[name] = (lr / base) if (lr is not None and base) else 1.0
        return out

    def _trainable(self):
        return {name: not isinstance(self.conf.vertices[name].vertex,
                                     FrozenLayer)
                for name in self.topo}

    def _make_train_step(self, **jit_kwargs):
        tc = self.conf.training
        lr_mult = self._lr_multipliers()
        trainable = self._trainable()

        def step(params, state, opt_state, iteration, inputs, labels, key,
                 masks):
            def loss_fn(p):
                return self._loss_fn(p, state, inputs, labels, key, masks)
            (score, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            new_params, new_opt = apply_updater(
                tc, params, grads, opt_state, iteration,
                lr_multipliers=lr_mult, trainable=trainable)
            return new_params, new_state, new_opt, score

        return jax.jit(step, donate_argnums=(0, 1, 2), **jit_kwargs)

    def _make_epoch_program(self, mb_body_factory, epochs: int,
                            **jit_kwargs):
        """Shared scanned-program scaffolding (cf. the MLN twin): inner
        scan over the minibatch pool with the body from
        ``mb_body_factory(inputs_stack, labels_stack, base_key)``,
        optional outer epochs scan."""
        def epoch(params, state, opt_state, start_iteration, inputs_stack,
                  labels_stack, base_key):
            body = mb_body_factory(inputs_stack, labels_stack, base_key)

            def one_pass(carry, _):
                return jax.lax.scan(body, carry,
                                    (inputs_stack, labels_stack))

            carry = (params, state, opt_state, start_iteration)
            if epochs == 1:
                carry, scores = one_pass(carry, None)
            else:
                carry, scores = jax.lax.scan(one_pass, carry, None,
                                             length=epochs)
            params, state, opt_state, _ = carry
            return params, state, opt_state, scores.reshape(-1)

        return jax.jit(epoch, donate_argnums=(0, 1, 2), **jit_kwargs)

    def _make_scan_fit(self, epochs: int = 1, **jit_kwargs):
        """Whole-epoch program: `lax.scan` of the minibatch step, keeping
        the per-step loop on device (the MultiLayerNetwork.fit_batched
        analog for the DAG runtime)."""
        tc = self.conf.training
        lr_mult = self._lr_multipliers()
        trainable = self._trainable()

        def factory(inputs_stack, labels_stack, base_key):
            def body(carry, il):
                params, state, opt, it = carry
                inputs, labels = il
                key = jax.random.fold_in(base_key, it)

                def loss_fn(p):
                    return self._loss_fn(p, state, inputs, labels, key,
                                         None)
                (score, new_state), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                new_params, new_opt = apply_updater(
                    tc, params, grads, opt, it,
                    lr_multipliers=lr_mult, trainable=trainable)
                return (new_params, new_state, new_opt, it + 1), score

            return body

        return self._make_epoch_program(factory, epochs, **jit_kwargs)

    def fit_batched(self, feats, labs, epochs: int = 1):
        """Train on a pre-staged stack of minibatches in ONE compiled
        program. ``feats``/``labs`` follow the same shapes fit() accepts
        (single array, list per input/output, or name->array dict), with
        an extra leading [N] batches axis; returns per-step scores
        [N * epochs] (``epochs`` repeats the staged pool in-program).

        With backprop_type='tbptt' and temporal labels ([N, B, T, C]
        everywhere), each minibatch scans its time chunks with carried
        RNN state and one update per chunk, so scores (and iteration
        counts) are per CHUNK: [N * T/L * epochs]. Non-temporal labels
        fall through to standard BPTT, matching fit()."""
        self._validate_fit_batched(epochs, allow_tbptt=True)
        inputs = self._as_input_dict(feats, self.conf.network_inputs)
        labels = self._as_input_dict(labs, self.conf.network_outputs)
        use_tbptt = (self.conf.backprop_type == "tbptt"
                     and all(v.ndim == 4 for v in labels.values()))
        if use_tbptt:
            L = self.conf.tbptt_fwd_length
            t_in = next(iter(inputs.values())).shape[2]
            for k, v in list(inputs.items()) + list(labels.items()):
                if v.ndim != 4:
                    raise ValueError(
                        f"tbptt fit_batched needs [N, B, T, F] arrays; "
                        f"{k!r} has ndim={v.ndim}")
                if v.shape[2] != t_in:
                    raise ValueError(
                        f"tbptt fit_batched needs one sequence length; "
                        f"{k!r} has T={v.shape[2]} vs {t_in}")
            if t_in % L:
                raise ValueError(
                    f"tbptt fit_batched needs T ({t_in}) divisible by "
                    f"tbptt_fwd_length ({L}); use fit() for ragged tails")
            cache_key = ("scanfit-tbptt", epochs)
            maker = self._make_scan_fit_tbptt
        else:
            cache_key = ("scanfit", epochs)
            maker = self._make_scan_fit
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            fn = maker(epochs)
            self._jit_cache[cache_key] = fn
        return self._run_scan_fit(fn, inputs, labels)

    def _validate_fit_batched(self, epochs: int,
                              allow_tbptt: bool = False) -> None:
        if not self._initialized:
            self.init()
        tc = self.conf.training
        if tc.optimization_algo not in ("stochastic_gradient_descent",
                                        "sgd"):
            raise ValueError(
                "fit_batched supports first-order optimization only; "
                f"optimization_algo={tc.optimization_algo!r} dispatches "
                "to the Solver path — use fit() instead")
        if self.conf.backprop_type == "tbptt" and not allow_tbptt:
            raise ValueError(
                "this scanned path does not implement truncated BPTT; "
                "use fit() or ComputationGraph.fit_batched")
        if epochs < 1:
            raise ValueError(f"epochs must be >= 1, got {epochs}")

    def _run_scan_fit(self, fn, inputs, labels):
        base_key = jax.random.PRNGKey(self.conf.training.seed)
        start = jnp.asarray(self.iteration_count, jnp.int32)
        self.params, self.state, self.updater_state, scores = fn(
            self.params, self.state, self.updater_state, start, inputs,
            labels, base_key)
        n = int(scores.shape[0])
        if n == 0:
            return scores
        if not self.listeners:
            self.iteration_count += n
            self.score_value = float(scores[-1])
            return scores
        host_scores = np.asarray(scores)
        for i in range(n):
            self.score_value = float(host_scores[i])
            for l in self.listeners:
                l.iteration_done(self, self.iteration_count,
                                 self.score_value)
            self.iteration_count += 1
        return scores

    def fit(self, data, labels=None, masks=None) -> None:
        """Train on a (Multi)DataSetIterator or arrays (reference:
        ComputationGraph.fit:701/783)."""
        if not self._initialized:
            self.init()
        if labels is not None:
            self._fit_batch(data, labels, masks)
            return
        for batch in data:
            feats, labs, fmask, lmask = _unpack_batch(batch)
            self._fit_batch(feats, labs, lmask)
        self.epoch_count += 1
        if hasattr(data, "reset"):
            data.reset()

    def _as_input_dict(self, data, names) -> Dict[str, Array]:
        if isinstance(data, dict):
            return {k: jnp.asarray(v) for k, v in data.items()}
        if isinstance(data, (list, tuple)):
            return {n: jnp.asarray(d) for n, d in zip(names, data)}
        return {names[0]: jnp.asarray(data)}

    def _fit_batch(self, feats, labs, masks=None) -> None:
        inputs = self._as_input_dict(feats, self.conf.network_inputs)
        labels = self._as_input_dict(labs, self.conf.network_outputs)
        mask_dict = None
        if masks is not None:
            mask_dict = self._as_input_dict(masks, self.conf.network_inputs)
        first_order = self.conf.training.optimization_algo in (
            "stochastic_gradient_descent", "sgd")
        if self.conf.backprop_type == "tbptt" and all(
                v.ndim == 3 for v in inputs.values()) and all(
                v.ndim == 3 for v in labels.values()):
            # TBPTT needs temporal labels to slice; 2D labels (e.g. via
            # LastTimeStepVertex) fall through to standard BPTT
            if not first_order:
                raise ValueError(
                    "TBPTT supports first-order optimization only "
                    "(reference runs the Solver per chunk; here the "
                    "chunk step is a compiled first-order update) — "
                    f"optimization_algo="
                    f"{self.conf.training.optimization_algo!r}")
            self._fit_tbptt(inputs, labels, mask_dict)
            return
        if not first_order:
            # Second-order path (reference: ComputationGraph training also
            # dispatches through Solver.java:48 to LBFGS/CG/LineGD)
            from deeplearning4j_tpu.train.solvers import Solver
            if self._solver is None:
                self._solver = Solver(self)

            def _notify(score):
                self.score_value = score
                for l in self.listeners:
                    l.iteration_done(self, self.iteration_count, score)
                self.iteration_count += 1

            self._solver.optimize(inputs, labels, mask_dict,
                                  iteration_callback=_notify)
            return
        shape_key = tuple(sorted((k, v.shape) for k, v in inputs.items()))
        step = self._jit_cache.get(("train", shape_key))
        if step is None:
            step = self._make_train_step()
            self._jit_cache[("train", shape_key)] = step
        key = jax.random.fold_in(
            jax.random.PRNGKey(self.conf.training.seed),
            self.iteration_count)
        self.params, self.state, self.updater_state, score = step(
            self.params, self.state, self.updater_state,
            self.iteration_count, inputs, labels, key, mask_dict)
        self.score_value = score
        for l in self.listeners:
            l.iteration_done(self, self.iteration_count, self.score_value)
        self.iteration_count += 1

    # ------------------------------------------------------------- pretrain
    def pretrain(self, data) -> None:
        """Greedy layerwise unsupervised pretraining of AE/RBM/VAE
        vertices in topological order (reference:
        ComputationGraph.pretrain:527)."""
        if not self._initialized:
            self.init()
        for name in self.topo:
            v = self.conf.vertices[name].vertex
            if isinstance(v, Layer) and v.is_pretrain_layer():
                self.pretrain_vertex(name, data)
                if hasattr(data, "reset"):
                    data.reset()

    def _make_pretrain_step(self, name: str):
        layer = self.conf.vertices[name].vertex
        tc = self.conf.training

        def vertex_input(up_params, up_state, inputs, key):
            """Forward through the frozen upstream subgraph to the
            target vertex's (preprocessed) input activation."""
            values: Dict[str, Array] = {
                k: (v.astype(self.dtype)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v)
                for k, v in inputs.items()}
            for i, n in enumerate(self.topo):
                if n == name:
                    spec = self.conf.vertices[n]
                    h = values[spec.inputs[0]]
                    pre = self._preprocessors.get(n)
                    return pre.pre_process(h) if pre is not None else h
                spec = self.conf.vertices[n]
                v = spec.vertex
                ins = [values[m] for m in spec.inputs if m in values]
                if not ins and not isinstance(v, Layer):
                    continue
                if isinstance(v, Layer):
                    h = ins[0]
                    pre = self._preprocessors.get(n)
                    if pre is not None:
                        h = pre.pre_process(h)
                    h, _ = v.apply(
                        jax.lax.stop_gradient(up_params[n]),
                        up_state.get(n, {}), h, train=False)
                    values[n] = h
                else:
                    values[n] = v.apply(ins, masks=[None] * len(ins))
            raise ValueError(f"vertex '{name}' not reached in topo order")

        def pstep(up_params, up_state, params, opt_state, iteration,
                  inputs, key):
            def loss_fn(p):
                h = vertex_input(up_params, up_state, inputs, key)
                return layer.pretrain_loss(p, h, key)

            score, grads = jax.value_and_grad(loss_fn)(params)
            new_p, new_s = apply_updater(
                tc, {name: params}, {name: grads}, {name: opt_state},
                iteration)
            return new_p[name], new_s[name], score

        return jax.jit(pstep)

    def pretrain_vertex(self, name: str, data) -> None:
        layer = self.conf.vertices[name].vertex
        if not (isinstance(layer, Layer) and layer.is_pretrain_layer()):
            return
        tc = self.conf.training
        pstep = self._jit_cache.get(("pretrain", name))
        if pstep is None:
            pstep = self._make_pretrain_step(name)
            self._jit_cache[("pretrain", name)] = pstep
        upstream = self.topo[:self.topo.index(name)]
        up_params = {n: self.params[n] for n in upstream}
        up_state = {n: self.state.get(n, {}) for n in upstream}
        it = self._pretrain_counts.get(name, 0)
        batches = data if not hasattr(data, "__array__") else [(data, None)]
        for batch in batches:
            feats, _, _, _ = _unpack_batch(batch)
            inputs = self._as_input_dict(feats, self.conf.network_inputs)
            key = jax.random.fold_in(jax.random.PRNGKey(tc.seed), it)
            (self.params[name], self.updater_state[name],
             score) = pstep(up_params, up_state, self.params[name],
                            self.updater_state[name], it, inputs, key)
            self.score_value = score
            it += 1
        self._pretrain_counts[name] = it

    # --------------------------------------------------------------- tbptt
    def _init_carries(self, batch: int) -> Dict[str, Any]:
        carries = {}
        for name in self.topo:
            v = self.conf.vertices[name].vertex
            if isinstance(v, Layer) and hasattr(v, "initial_carry") \
                    and getattr(v, "supports_streaming", True):
                carries[name] = v.initial_carry(batch, self.dtype)
        return carries

    def _tbptt_chunk_math(self):
        """The pure TBPTT chunk update over the DAG (reference:
        ComputationGraph.doTruncatedBPTT:2042) — shared by the per-chunk
        jitted path and the scanned fit_batched path."""
        tc = self.conf.training
        lr_mult = self._lr_multipliers()
        trainable = self._trainable()

        def chunk_step(params, state, opt_state, iteration, inputs,
                       labels, carries, key, masks):
            def loss_fn(p):
                out_records, new_state, new_carries = self._forward_preout(
                    p, state, inputs, key=key, masks=masks, train=True,
                    carries=carries)
                total = jnp.asarray(0.0)
                for out_name in self.conf.network_outputs:
                    layer = self.conf.vertices[out_name].vertex
                    h_in, mask = out_records[out_name]
                    total = total + promote_score(layer.loss(
                        p[out_name], h_in, labels[out_name], mask))
                total = total + self._regularization_score(p)
                new_carries = jax.tree_util.tree_map(
                    jax.lax.stop_gradient, new_carries)
                return total, (new_state, new_carries)

            (score, (new_state, new_carries)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            new_params, new_opt = apply_updater(
                tc, params, grads, opt_state, iteration,
                lr_multipliers=lr_mult, trainable=trainable)
            return new_params, new_state, new_opt, new_carries, score

        return chunk_step

    def _make_tbptt_step(self):
        """Jitted TBPTT chunk step over the DAG."""
        return jax.jit(self._tbptt_chunk_math())

    def _make_scan_fit_tbptt(self, epochs: int = 1, **jit_kwargs):
        """Whole-run TBPTT program over the DAG: inner scan over each
        minibatch's time chunks (carried RNN state reset per minibatch,
        one update per chunk), outer scans over the pool and epochs —
        the ComputationGraph counterpart of
        MultiLayerNetwork._make_scan_fit_tbptt."""
        chunk_step = self._tbptt_chunk_math()
        L = self.conf.tbptt_fwd_length

        def factory(inputs_stack, labels_stack, base_key):
            first = next(iter(inputs_stack.values()))
            b, t = first.shape[1], first.shape[2]
            s = t // L
            carries0 = self._init_carries(b)

            def to_chunks(d):
                # each [B, T, ...] -> [S, B, L, ...]
                return {k: jnp.moveaxis(
                    v.reshape((b, s, L) + v.shape[2:]), 1, 0)
                    for k, v in d.items()}

            def mb_body(carry, xy):
                params, state, opt, it = carry
                inputs, labels = xy

                def chunk_body(c2, xyc):
                    params, state, opt, it, carries = c2
                    xc, yc = xyc
                    key = jax.random.fold_in(base_key, it)
                    params, state, opt, carries, score = chunk_step(
                        params, state, opt, it, xc, yc, carries, key,
                        None)
                    return (params, state, opt, it + 1, carries), score

                (params, state, opt, it, _), scores = jax.lax.scan(
                    chunk_body, (params, state, opt, it, carries0),
                    (to_chunks(inputs), to_chunks(labels)))
                return (params, state, opt, it), scores

            return mb_body

        return self._make_epoch_program(factory, epochs, **jit_kwargs)

    def _fit_tbptt(self, inputs: Dict[str, Array],
                   labels: Dict[str, Array], masks=None) -> None:
        """Truncated BPTT over the DAG: chunk the time axis, carry RNN
        state (stop-gradient) across chunks."""
        T = next(iter(inputs.values())).shape[1]
        L = self.conf.tbptt_fwd_length
        n_chunks = math.ceil(T / L)
        batch = next(iter(inputs.values())).shape[0]
        carries = self._init_carries(batch)
        tc = self.conf.training
        # key by (batch, feature dims) — NOT total T: the same compiled
        # chunk step serves every sequence length (the chunk shapes
        # retrace inside the one wrapper, as in the MLN analog)
        shape_key = ("tbptt",) + tuple(sorted(
            (k, v.shape[0], v.shape[2:]) for k, v in inputs.items()))
        chunk_step = self._jit_cache.get(shape_key)
        if chunk_step is None:
            chunk_step = self._make_tbptt_step()
            self._jit_cache[shape_key] = chunk_step

        def time_slice(d, sl):
            return {k: v[:, sl] for k, v in d.items()}

        for c in range(n_chunks):
            sl = slice(c * L, min((c + 1) * L, T))
            key = jax.random.fold_in(jax.random.PRNGKey(tc.seed),
                                     self.iteration_count)
            (self.params, self.state, self.updater_state, carries,
             score) = chunk_step(
                self.params, self.state, self.updater_state,
                self.iteration_count, time_slice(inputs, sl),
                time_slice(labels, sl), carries, key,
                None if masks is None else time_slice(masks, sl))
            self.score_value = score
            for l in self.listeners:
                l.iteration_done(self, self.iteration_count,
                                 self.score_value)
            self.iteration_count += 1

    # ----------------------------------------------------------- streaming
    def rnn_clear_previous_state(self) -> None:
        self._rnn_carries = None

    def rnn_time_step(self, *data) -> List[Array]:
        """Stateful single/multi-step inference over the DAG (reference:
        ComputationGraph.rnnTimeStep)."""
        for name in self.topo:
            v = self.conf.vertices[name].vertex
            if isinstance(v, Layer) and hasattr(v, "initial_carry") \
                    and not getattr(v, "supports_streaming", True):
                raise ValueError(
                    f"rnn_time_step unsupported: vertex '{name}' "
                    f"({type(v).__name__}) needs the full sequence")
        if len(data) == 1:
            inputs = self._as_input_dict(data[0], self.conf.network_inputs)
        else:
            inputs = self._as_input_dict(list(data),
                                         self.conf.network_inputs)
        squeeze = next(iter(inputs.values())).ndim == 2
        if squeeze:
            inputs = {k: v[:, None, :] for k, v in inputs.items()}
        batch = next(iter(inputs.values())).shape[0]
        if getattr(self, "_rnn_carries", None) is None:
            self._rnn_carries = self._init_carries(batch)
        values, _, new_carries = self._forward(
            self.params, self.state, inputs, train=False, key=None,
            carries=self._rnn_carries)
        self._rnn_carries.update(new_carries)
        outs = [values[n] for n in self.conf.network_outputs]
        return [o[:, 0] if squeeze else o for o in outs]

    # ------------------------------------------------------------- inference
    def output(self, *data, train: bool = False) -> List[Array]:
        """Output activations for each configured output (reference:
        ComputationGraph.output)."""
        if len(data) == 1:
            inputs = self._as_input_dict(data[0], self.conf.network_inputs)
        else:
            inputs = self._as_input_dict(list(data),
                                         self.conf.network_inputs)
        fn = self._jit_cache.get(("output", train))
        if fn is None:
            def _out(params, state, inputs):
                values, _ = self._forward(params, state, inputs, train=train,
                                          key=None)
                return [values[n] for n in self.conf.network_outputs]
            fn = jax.jit(_out)
            self._jit_cache[("output", train)] = fn
        return fn(self.params, self.state, inputs)

    def output_batched(self, feats) -> List[Array]:
        """Scanned inference over a pre-staged pool: inputs with a
        leading [N] batches axis -> per-output activations [N, B, ...]
        in one compiled program (the DAG twin of
        MultiLayerNetwork.output_batched)."""
        if not self._initialized:
            self.init()
        inputs = self._as_input_dict(feats, self.conf.network_inputs)
        fn = self._jit_cache.get(("output-scan",))
        if fn is None:
            def _scan_out(params, state, inputs):
                def body(_, x):
                    values, _ = self._forward(params, state, x,
                                              train=False, key=None)
                    return None, [values[n]
                                  for n in self.conf.network_outputs]

                return jax.lax.scan(body, None, inputs)[1]

            fn = jax.jit(_scan_out)
            self._jit_cache[("output-scan",)] = fn
        return fn(self.params, self.state, inputs)

    def evaluate_batched(self, feats, labs):
        """Evaluation over a pre-staged pool — scanned forward on the
        FIRST output (the reference's evaluate semantics), one host-side
        metrics pass."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        out = np.asarray(self.output_batched(feats)[0])
        # labels stay on host: pick output 0's array without the
        # _as_input_dict device round-trip
        if isinstance(labs, dict):
            ys = np.asarray(labs[self.conf.network_outputs[0]])
        elif isinstance(labs, (list, tuple)):
            ys = np.asarray(labs[0])
        else:
            ys = np.asarray(labs)
        ev = Evaluation()
        ev.eval(ys.reshape(-1, ys.shape[-1]),
                out.reshape(-1, out.shape[-1]))
        return ev

    def feed_forward(self, data, train: bool = False) -> Dict[str, Array]:
        inputs = self._as_input_dict(data, self.conf.network_inputs)
        values, _ = self._forward(self.params, self.state, inputs,
                                  train=train, key=None)
        return values

    def score(self, feats, labs=None, masks=None) -> float:
        if labs is None:
            f, l, fm, lm = _unpack_batch(feats)
            return self.score(f, l, lm)
        inputs = self._as_input_dict(feats, self.conf.network_inputs)
        labels = self._as_input_dict(labs, self.conf.network_outputs)
        s, _ = self._loss_fn(self.params, self.state, inputs, labels, None,
                             None if masks is None else
                             self._as_input_dict(masks,
                                                 self.conf.network_inputs),
                             train=False)
        return float(s)

    def _run_evaluation(self, iterator, ev):
        """Feed the FIRST output's predictions into an IEvaluation
        (reference: ComputationGraph.evaluate uses output 0)."""
        first = self.conf.network_outputs[0]
        for batch in iterator:
            feats, labs, _, lmask = _unpack_batch(batch)
            out = self.output(feats)
            labs_d = self._as_input_dict(labs, self.conf.network_outputs)
            if isinstance(lmask, (list, tuple)):
                # MultiDataSet: per-output masks; pick output 0's,
                # mirroring the labels selection
                lmask = lmask[0]
            ev.eval(labs_d[first], out[0], mask=lmask)
        if hasattr(iterator, "reset"):
            iterator.reset()
        return ev

    def evaluate(self, iterator):
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        return self._run_evaluation(iterator, Evaluation())

    def evaluate_regression(self, iterator):
        """reference: ComputationGraph.evaluateRegression."""
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation
        return self._run_evaluation(iterator, RegressionEvaluation())

    def evaluate_roc(self, iterator, threshold_steps: int = 30):
        """reference: ComputationGraph.evaluateROC."""
        from deeplearning4j_tpu.eval.roc import ROC
        return self._run_evaluation(iterator, ROC(threshold_steps))

    def evaluate_roc_multi_class(self, iterator,
                                 threshold_steps: int = 30):
        """reference: ComputationGraph.evaluateROCMultiClass."""
        from deeplearning4j_tpu.eval.roc import ROCMultiClass
        return self._run_evaluation(iterator, ROCMultiClass(threshold_steps))

    # ------------------------------------------------------------ flat views
    def params_flat(self) -> Array:
        flat, _ = ravel_pytree(self.params)
        return flat

    def set_params_flat(self, flat) -> None:
        _, unravel = ravel_pytree(self.params)
        self.params = unravel(jnp.asarray(flat))

    def num_params(self) -> int:
        return int(self.params_flat().shape[0])

    def clone(self) -> "ComputationGraph":
        import copy
        net = ComputationGraph(copy.deepcopy(self.conf))
        net.params = jax.tree_util.tree_map(lambda a: a, self.params)
        net.state = jax.tree_util.tree_map(lambda a: a, self.state)
        net.updater_state = jax.tree_util.tree_map(lambda a: a,
                                                   self.updater_state)
        net._initialized = self._initialized
        return net

    # --------------------------------------------------- classifier surface
    def predict(self, *data) -> np.ndarray:
        """Predicted class index per example on output 0 (reference:
        ComputationGraph classifier surface)."""
        return np.asarray(self.output(*data)[0]).argmax(axis=-1)

    def f1_score(self, feats, labs) -> float:
        """Macro F1 on output 0 for one batch."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        labs_d = self._as_input_dict(labs, self.conf.network_outputs)
        ev = Evaluation()
        ev.eval(labs_d[self.conf.network_outputs[0]],
                self.output(feats)[0])
        return ev.f1()

    def score_examples(self, feats, labs, masks=None,
                       add_regularization_terms: bool = True
                       ) -> np.ndarray:
        """Per-example loss values (reference:
        ComputationGraph.scoreExamples) — one jitted+cached vmapped
        _loss_fn program, summed over all outputs like score(); masks
        exclude padded timesteps exactly as score() does."""
        inputs = self._as_input_dict(feats, self.conf.network_inputs)
        labels = self._as_input_dict(labs, self.conf.network_outputs)
        mask_d = None if masks is None else self._as_input_dict(
            masks, self.conf.network_inputs)
        key = ("score_examples", masks is not None)
        fn = self._jit_cache.get(key)
        if fn is None:
            def one(params, state, xi, yi, mi):
                s, _ = self._loss_fn(
                    params, state,
                    {k: v[None] for k, v in xi.items()},
                    {k: v[None] for k, v in yi.items()}, None,
                    None if mi is None
                    else {k: v[None] for k, v in mi.items()},
                    train=False)
                return s

            fn = jax.jit(jax.vmap(one, in_axes=(None, None, 0, 0,
                                                None if mask_d is None
                                                else 0)))
            self._jit_cache[key] = fn
        per = fn(self.params, self.state, inputs, labels, mask_d)
        if not add_regularization_terms:
            per = per - self._regularization_score(self.params)
        return np.asarray(per)

    def summary(self) -> str:
        """Printable per-vertex table in topological order (reference:
        ComputationGraph.summary)."""
        from deeplearning4j_tpu.common import (count_params,
                                               render_summary_table)
        rows = [("name", "type", "inputs", "n_params")]
        total = 0
        for name in self.topo:
            spec = self.conf.vertices[name]
            n = count_params(self.params.get(name, {}))
            total += n
            rows.append((name, type(spec.vertex).__name__,
                         ",".join(spec.inputs) or "-", f"{n:,}"))
        return render_summary_table(rows, total)
