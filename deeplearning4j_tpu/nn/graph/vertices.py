"""Graph vertices — the non-layer nodes of a ComputationGraph.

Parity with the reference's vertex set (reference:
deeplearning4j-nn/.../nn/conf/graph/*.java configs +
nn/graph/vertex/impl/*.java implementations, incl. impl/rnn/ for
LastTimeStepVertex and DuplicateToTimeSeriesVertex). The reference pairs each
config with a hand-written doForward/doBackward; here a vertex is a single
dataclass with a traced ``apply`` (autodiff supplies the backward).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import inputs as it
from deeplearning4j_tpu.nn.conf.serde import register

Array = jax.Array


class GraphVertex:
    """Base vertex: pure function of its input activations."""

    def apply(self, inputs: List[Array], masks=None) -> Array:
        raise NotImplementedError

    def output_type(self, input_types: List):
        return input_types[0]


@register
@dataclass
class MergeVertex(GraphVertex):
    """Concatenate along the feature (trailing) axis (reference:
    nn/conf/graph/MergeVertex.java)."""

    def apply(self, inputs, masks=None):
        return jnp.concatenate(inputs, axis=-1)

    def output_type(self, input_types):
        first = input_types[0]
        if isinstance(first, it.InputTypeFeedForward):
            return it.InputType.feed_forward(
                sum(t.size for t in input_types))
        if isinstance(first, it.InputTypeRecurrent):
            return it.InputType.recurrent(
                sum(t.size for t in input_types), first.time_series_length)
        if isinstance(first, it.InputTypeConvolutional):
            return it.InputType.convolutional(
                first.height, first.width,
                sum(t.channels for t in input_types))
        raise ValueError(f"MergeVertex cannot merge {first}")


@register
@dataclass
class ElementWiseVertex(GraphVertex):
    """Elementwise add/subtract/product/average/max of same-shaped inputs
    (reference: nn/conf/graph/ElementWiseVertex.java)."""
    op: str = "add"

    def apply(self, inputs, masks=None):
        op = self.op.lower()
        if op == "add":
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if op == "subtract":
            if len(inputs) != 2:
                raise ValueError("subtract needs exactly 2 inputs")
            return inputs[0] - inputs[1]
        if op in ("product", "multiply"):
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if op in ("average", "avg"):
            return sum(inputs) / float(len(inputs))
        if op == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        raise ValueError(f"Unknown ElementWiseVertex op '{self.op}'")


@register
@dataclass
class SubsetVertex(GraphVertex):
    """Feature-axis slice [from_idx, to_idx] inclusive (reference:
    nn/conf/graph/SubsetVertex.java)."""
    from_idx: int = 0
    to_idx: int = 0

    def apply(self, inputs, masks=None):
        return inputs[0][..., self.from_idx:self.to_idx + 1]

    def output_type(self, input_types):
        size = self.to_idx - self.from_idx + 1
        t = input_types[0]
        if isinstance(t, it.InputTypeRecurrent):
            return it.InputType.recurrent(size, t.time_series_length)
        return it.InputType.feed_forward(size)


@register
@dataclass
class StackVertex(GraphVertex):
    """Stack along the batch axis (reference:
    nn/conf/graph/StackVertex.java)."""

    def apply(self, inputs, masks=None):
        return jnp.concatenate(inputs, axis=0)


@register
@dataclass
class UnstackVertex(GraphVertex):
    """Take slice ``from_idx`` of ``stack_size`` equal batch-axis chunks
    (reference: nn/conf/graph/UnstackVertex.java)."""
    from_idx: int = 0
    stack_size: int = 1

    def apply(self, inputs, masks=None):
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_idx * step:(self.from_idx + 1) * step]


@register
@dataclass
class ScaleVertex(GraphVertex):
    """Multiply by a fixed scalar (reference:
    nn/conf/graph/ScaleVertex.java)."""
    scale_factor: float = 1.0

    def apply(self, inputs, masks=None):
        return inputs[0] * self.scale_factor


@register
@dataclass
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two inputs -> [B, 1] (reference:
    nn/conf/graph/L2Vertex.java)."""
    eps: float = 1e-8

    def apply(self, inputs, masks=None):
        a, b = inputs
        d = a.reshape(a.shape[0], -1) - b.reshape(b.shape[0], -1)
        return jnp.sqrt(jnp.sum(d * d, axis=-1, keepdims=True) + self.eps)

    def output_type(self, input_types):
        return it.InputType.feed_forward(1)


@register
@dataclass
class L2NormalizeVertex(GraphVertex):
    """Normalize to unit L2 norm over the feature axes (reference:
    nn/conf/graph/L2NormalizeVertex.java)."""
    eps: float = 1e-8

    def apply(self, inputs, masks=None):
        x = inputs[0]
        flat = x.reshape(x.shape[0], -1)
        norm = jnp.sqrt(jnp.sum(flat * flat, axis=-1) + self.eps)
        shape = (x.shape[0],) + (1,) * (x.ndim - 1)
        return x / norm.reshape(shape)


@register
@dataclass
class PreprocessorVertex(GraphVertex):
    """Wrap an InputPreProcessor as a vertex (reference:
    nn/conf/graph/PreprocessorVertex.java)."""
    preprocessor: Optional[object] = None

    def apply(self, inputs, masks=None):
        return self.preprocessor.pre_process(inputs[0])

    def output_type(self, input_types):
        return self.preprocessor.output_type(input_types[0])


@register
@dataclass
class LastTimeStepVertex(GraphVertex):
    """[B, T, F] -> [B, F] taking the last unmasked step (reference:
    nn/graph/vertex/impl/rnn/LastTimeStepVertex.java)."""
    mask_input: Optional[str] = None

    def apply(self, inputs, masks=None):
        x = inputs[0]
        mask = masks[0] if masks else None
        if mask is None:
            return x[:, -1]
        idx = jnp.maximum(
            jnp.sum(mask.astype(jnp.int32), axis=1) - 1, 0)  # [B]
        return jax.vmap(lambda seq, i: seq[i])(x, idx)

    def output_type(self, input_types):
        t = input_types[0]
        return it.InputType.feed_forward(t.size)


@register
@dataclass
class DuplicateToTimeSeriesVertex(GraphVertex):
    """[B, F] -> [B, T, F] broadcasting over a reference sequence's length
    (reference: nn/graph/vertex/impl/rnn/DuplicateToTimeSeriesVertex.java).
    Second input supplies T."""
    reference_input: Optional[str] = None

    def apply(self, inputs, masks=None):
        x, ref = inputs[0], inputs[1]
        t = ref.shape[1]
        return jnp.broadcast_to(x[:, None, :], (x.shape[0], t, x.shape[-1]))

    def output_type(self, input_types):
        f = input_types[0]
        r = input_types[1]
        return it.InputType.recurrent(
            f.size, getattr(r, "time_series_length", -1))


@register
@dataclass
class ReshapeVertex(GraphVertex):
    """Static reshape (keeps batch axis)."""
    shape: Sequence[int] = field(default_factory=tuple)

    def apply(self, inputs, masks=None):
        x = inputs[0]
        return x.reshape((x.shape[0],) + tuple(self.shape))
