"""Transfer learning: clone + modify trained networks.

Parity with the reference (reference:
deeplearning4j-nn/.../nn/transferlearning/TransferLearning.java:61 —
fineTuneConfiguration:75, setFeatureExtractor:86, nOutReplace:100;
FineTuneConfiguration.java; TransferLearningHelper.java): freeze everything
at/below a layer, replace output heads with re-initialized layers, override
training hyperparameters, and featurize-and-cache the frozen part so only
the unfrozen tail trains.

TPU-native notes: freezing is a trainability mask over the param pytree
(the updater skips frozen layers inside the same jitted step — no separate
"frozen" execution path), and the helper's featurization is just running the
jitted frozen-prefix forward once per batch.
"""
from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import jax

from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.nn.layers.misc import FrozenLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


@dataclass
class FineTuneConfiguration:
    """Training-hyperparameter overrides applied to the cloned network
    (reference: FineTuneConfiguration.java — only non-None fields apply)."""
    learning_rate: Optional[float] = None
    updater: Optional[str] = None
    momentum: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    seed: Optional[int] = None
    dropout: Optional[float] = None
    lr_policy: Optional[str] = None
    lr_policy_decay_rate: Optional[float] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: Optional[float] = None

    def apply_to(self, conf) -> None:
        tc = conf.training
        for name in ("learning_rate", "updater", "momentum", "l1", "l2",
                     "seed", "lr_policy", "lr_policy_decay_rate",
                     "gradient_normalization",
                     "gradient_normalization_threshold"):
            v = getattr(self, name)
            if v is not None:
                setattr(tc, name, v)
        if hasattr(conf, "vertices"):  # ComputationGraphConfiguration
            layers = [s.vertex for s in conf.vertices.values()
                      if isinstance(s.vertex, Layer)]
        else:
            layers = conf.layers
        for layer in layers:
            inner = layer.inner if isinstance(layer, FrozenLayer) else layer
            if self.learning_rate is not None:
                inner.learning_rate = self.learning_rate
                inner.bias_learning_rate = self.learning_rate
            if self.dropout is not None:
                inner.dropout = self.dropout
            if self.l1 is not None:
                inner.l1 = self.l1
            if self.l2 is not None:
                inner.l2 = self.l2


class TransferLearning:
    """Namespace matching the reference's outer class."""

    class Builder:
        """reference: TransferLearning.Builder (TransferLearning.java:61)."""

        def __init__(self, net: MultiLayerNetwork):
            if not net._initialized:
                raise ValueError("source network must be initialized")
            self._net = net
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._freeze_until: Optional[int] = None
            self._nout_replace: Dict[int, tuple] = {}
            self._remove_count = 0
            self._appended: List[Layer] = []

        def fine_tune_configuration(self, ftc: FineTuneConfiguration
                                    ) -> "TransferLearning.Builder":
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, layer_idx: int
                                  ) -> "TransferLearning.Builder":
            """Freeze layers [0..layer_idx] (reference:
            setFeatureExtractor:86)."""
            self._freeze_until = layer_idx
            return self

        def n_out_replace(self, layer_idx: int, n_out: int,
                          weight_init: str = "xavier"
                          ) -> "TransferLearning.Builder":
            """Replace layer's n_out and re-init it + the next layer's n_in
            (reference: nOutReplace:100)."""
            self._nout_replace[layer_idx] = (n_out, weight_init)
            return self

        def remove_output_layer(self) -> "TransferLearning.Builder":
            self._remove_count += 1
            return self

        def remove_layers_from_output(self, n: int
                                      ) -> "TransferLearning.Builder":
            self._remove_count += n
            return self

        def add_layer(self, layer: Layer) -> "TransferLearning.Builder":
            self._appended.append(layer)
            return self

        def build(self) -> MultiLayerNetwork:
            src = self._net
            conf = copy.deepcopy(src.conf)
            params = jax.tree_util.tree_map(lambda a: a, src.params)
            state = jax.tree_util.tree_map(lambda a: a, src.state)
            n = len(conf.layers)
            if self._remove_count:
                if self._remove_count >= n:
                    raise ValueError("cannot remove every layer")
                for i in range(n - self._remove_count, n):
                    params.pop(src.layer_names[i], None)
                    state.pop(src.layer_names[i], None)
                    conf.input_preprocessors.pop(str(i), None)
                conf.layers = conf.layers[:n - self._remove_count]

            reinit: List[int] = []
            for idx, (n_out, w_init) in sorted(self._nout_replace.items()):
                if idx >= len(conf.layers):
                    raise ValueError(f"n_out_replace index {idx} out of "
                                     f"range ({len(conf.layers)} layers)")
                layer = conf.layers[idx]
                inner = layer.inner if isinstance(layer, FrozenLayer) \
                    else layer
                inner.n_out = n_out
                inner.weight_init = w_init
                reinit.append(idx)
                if idx + 1 < len(conf.layers):
                    nxt = conf.layers[idx + 1]
                    ninner = nxt.inner if isinstance(nxt, FrozenLayer) \
                        else nxt
                    if getattr(ninner, "n_in", None) is not None:
                        ninner.n_in = n_out
                    reinit.append(idx + 1)

            for layer in self._appended:
                conf.layers.append(copy.deepcopy(layer))
                reinit.append(len(conf.layers) - 1)

            if self._freeze_until is not None:
                for i in range(min(self._freeze_until + 1,
                                   len(conf.layers))):
                    if not isinstance(conf.layers[i], FrozenLayer):
                        conf.layers[i] = FrozenLayer(
                            inner=conf.layers[i],
                            name=conf.layers[i].name)

            if self._fine_tune is not None:
                self._fine_tune.apply_to(conf)

            # re-run shape inference from scratch over the modified topology
            conf._shapes_resolved = False
            for i in range(len(conf.layers)):
                layer = conf.layers[i]
                inner = layer.inner if isinstance(layer, FrozenLayer) \
                    else layer
                if i in reinit and getattr(inner, "n_in", None) is not None \
                        and i > 0:
                    inner.n_in = None  # re-infer from upstream
            new_net = MultiLayerNetwork(conf)
            new_net.init(seed=conf.training.seed)
            # copy retained params over the fresh init (reinit'd layers and
            # appended layers keep their new random weights)
            for i in range(len(conf.layers)):
                name = new_net.layer_names[i]
                if i in reinit:
                    continue
                if name in params:
                    new_net.params[name] = params[name]
                if name in state:
                    new_net.state[name] = state[name]
            return new_net


class _GraphBuilder:
    """Transfer learning over a ComputationGraph (reference:
    TransferLearning.GraphBuilder — setFeatureExtractor(vertexName)
    freezes the named vertices and everything upstream; nOutReplace,
    removeVertexAndConnections, addLayer, setOutputs)."""

    def __init__(self, graph):
        if not graph._initialized:
            raise ValueError("source graph must be initialized")
        self._graph = graph
        self._fine_tune: Optional[FineTuneConfiguration] = None
        self._freeze_at: List[str] = []
        self._nout_replace: Dict[str, tuple] = {}
        self._removed: List[str] = []
        self._added: List[tuple] = []
        self._new_outputs: Optional[List[str]] = None

    def fine_tune_configuration(self, ftc: FineTuneConfiguration
                                ) -> "_GraphBuilder":
        self._fine_tune = ftc
        return self

    def set_feature_extractor(self, *vertex_names: str) -> "_GraphBuilder":
        self._freeze_at.extend(vertex_names)
        return self

    def n_out_replace(self, vertex_name: str, n_out: int,
                      weight_init: str = "xavier") -> "_GraphBuilder":
        self._nout_replace[vertex_name] = (n_out, weight_init)
        return self

    def remove_vertex_and_connections(self, name: str) -> "_GraphBuilder":
        self._removed.append(name)
        return self

    def add_layer(self, name: str, layer: Layer,
                  *inputs: str) -> "_GraphBuilder":
        self._added.append((name, layer, list(inputs)))
        return self

    def set_outputs(self, *names: str) -> "_GraphBuilder":
        self._new_outputs = list(names)
        return self

    def _upstream_closure(self, conf, names: List[str]) -> set:
        out = set()
        stack = list(names)
        while stack:
            n = stack.pop()
            if n in out or n not in conf.vertices:
                continue
            out.add(n)
            stack.extend(conf.vertices[n].inputs)
        return out

    def build(self):
        from deeplearning4j_tpu.nn.graph.computation_graph import \
            ComputationGraph
        src = self._graph
        conf = copy.deepcopy(src.conf)
        params = jax.tree_util.tree_map(lambda a: a, src.params)
        state = jax.tree_util.tree_map(lambda a: a, src.state)
        reinit: set = set()

        for name in self._removed:
            if name not in conf.vertices:
                raise ValueError(f"unknown vertex '{name}'")
            del conf.vertices[name]
            params.pop(name, None)
            state.pop(name, None)
            conf.network_outputs = [o for o in conf.network_outputs
                                    if o != name]
            # strip the edges too (reference: removeVertexAndConnections)
            for spec in conf.vertices.values():
                spec.inputs = [i for i in spec.inputs if i != name]

        for name, (n_out, w_init) in self._nout_replace.items():
            if name not in conf.vertices:
                raise ValueError(f"unknown vertex '{name}'")
            v = conf.vertices[name].vertex
            inner = v.inner if isinstance(v, FrozenLayer) else v
            inner.n_out = n_out
            inner.weight_init = w_init
            reinit.add(name)
            for cname, spec in conf.vertices.items():
                if name in spec.inputs and isinstance(spec.vertex, Layer):
                    cv = spec.vertex
                    cinner = cv.inner if isinstance(cv, FrozenLayer) else cv
                    if getattr(cinner, "n_in", None) is not None:
                        cinner.n_in = n_out
                    reinit.add(cname)

        for name, layer, inputs in self._added:
            from deeplearning4j_tpu.nn.conf.configuration import \
                GraphVertexSpec
            conf.vertices[name] = GraphVertexSpec(
                vertex=copy.deepcopy(layer), inputs=inputs)
            conf.vertices[name].vertex.name = name
            reinit.add(name)

        if self._new_outputs is not None:
            conf.network_outputs = list(self._new_outputs)

        if self._freeze_at:
            for name in self._upstream_closure(conf, self._freeze_at):
                spec = conf.vertices.get(name)
                if spec is not None and isinstance(spec.vertex, Layer) \
                        and not isinstance(spec.vertex, FrozenLayer):
                    spec.vertex = FrozenLayer(inner=spec.vertex,
                                              name=spec.vertex.name)

        if self._fine_tune is not None:
            self._fine_tune.apply_to(conf)

        for name, spec in conf.vertices.items():
            if not spec.inputs:
                raise ValueError(
                    f"vertex '{name}' has no inputs after transfer "
                    "surgery — rewire it (add_layer/remove it) before "
                    "build()")
        conf.topological_order()  # validate the rewired DAG
        new_graph = ComputationGraph(conf).init(seed=conf.training.seed)
        for name in conf.vertices:
            if name in reinit:
                continue
            if name in params:
                new_graph.params[name] = params[name]
            if name in state:
                new_graph.state[name] = state[name]
        return new_graph


TransferLearning.GraphBuilder = _GraphBuilder


class TransferLearningHelper:
    """Featurize-and-cache workflow (reference:
    TransferLearningHelper.java): split the network at the last frozen
    layer; `featurize` runs the frozen prefix, `fit_featurized` trains only
    the unfrozen tail on cached features."""

    def __init__(self, net: MultiLayerNetwork,
                 frozen_until: Optional[int] = None):
        if frozen_until is not None:
            net = (TransferLearning.Builder(net)
                   .set_feature_extractor(frozen_until).build())
        self.net = net
        self.frozen_until = -1
        for i, layer in enumerate(net.layers):
            if isinstance(layer, FrozenLayer):
                self.frozen_until = i
        if self.frozen_until < 0:
            raise ValueError("network has no frozen layers")
        # built once: keeps the tail's updater state (Adam moments) and jit
        # cache alive across fit_featurized calls
        self._tail = self._build_tail()

    def featurize(self, x):
        """Activations at the frozen/unfrozen boundary."""
        acts = self.net.feed_forward(x, train=False)
        return acts[self.frozen_until]

    def unfrozen_graph(self) -> MultiLayerNetwork:
        """The standalone network over the unfrozen tail (shares param
        arrays with the composite net until the first fit)."""
        return self._tail

    def _build_tail(self) -> MultiLayerNetwork:
        conf = copy.deepcopy(self.net.conf)
        tail_layers = conf.layers[self.frozen_until + 1:]
        conf.layers = tail_layers
        conf.input_preprocessors = {
            str(int(k) - self.frozen_until - 1): v
            for k, v in conf.input_preprocessors.items()
            if int(k) > self.frozen_until}
        conf.input_type = None
        conf._shapes_resolved = True  # shapes already resolved in the parent
        tail = MultiLayerNetwork(conf)
        tail.params = {}
        tail.state = {}
        for j, i in enumerate(range(self.frozen_until + 1,
                                    len(self.net.layers))):
            src_name = self.net.layer_names[i]
            dst_name = tail.layer_names[j]
            tail.params[dst_name] = self.net.params[src_name]
            tail.state[dst_name] = self.net.state[src_name]
        from deeplearning4j_tpu.train.updaters import init_updater_state
        tail.updater_state = init_updater_state(conf.training, tail.params)
        tail._initialized = True
        return tail

    def fit_featurized(self, features, labels) -> None:
        """Train the tail on featurized input, then write updated tail
        params back into the composite network."""
        self._tail.fit(features, labels)
        for j, i in enumerate(range(self.frozen_until + 1,
                                    len(self.net.layers))):
            src_name = self._tail.layer_names[j]
            dst_name = self.net.layer_names[i]
            self.net.params[dst_name] = self._tail.params[src_name]
            self.net.state[dst_name] = self._tail.state[src_name]

    def output_from_featurized(self, features):
        return self._tail.output(features)
