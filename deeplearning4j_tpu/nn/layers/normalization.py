"""Normalization layers: BatchNormalization, LocalResponseNormalization.

Reference: deeplearning4j-nn/.../nn/layers/normalization/
BatchNormalization.java:55 (cuDNN helper plug point) and
LocalResponseNormalization.java; conf classes in nn/conf/layers/. Running
statistics are non-trainable state threaded through the jitted step (the
functional replacement for the reference's mutable mean/var params), and the
whole normalization fuses into neighboring ops under XLA — no helper
indirection needed.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf import inputs as it
from deeplearning4j_tpu.nn.conf.serde import register
from deeplearning4j_tpu.nn.layers.base import BaseLayer, Layer

Array = jax.Array


@register
@dataclass
class BatchNormalization(BaseLayer):
    """Batch norm over the trailing (feature/channel) axis — works for both
    [B, F] dense and [B, H, W, C] conv activations (NHWC makes the channel
    axis trailing in both cases, unlike the reference's NCHW special-casing).
    ``decay`` matches the reference's moving-average decay; ``eps`` its
    epsilon; ``lock_gamma_beta`` freezes scale/shift at 1/0."""
    n_out: Optional[int] = None
    decay: float = 0.9
    eps: float = 1e-5
    gamma: float = 1.0
    beta: float = 0.0
    lock_gamma_beta: bool = False
    _family: str = "ff"

    @property
    def family(self) -> str:
        return self._family

    @property
    def input_family(self) -> str:
        # 'any': normalizes whatever family arrives (NHWC puts the
        # channel/feature axis last for ff, cnn AND rnn activations) —
        # must not trigger a preprocessor before update_input_type has
        # seen the real input type (shape inference queries input_family
        # first)
        return "any"

    def update_input_type(self, input_type):
        if isinstance(input_type, it.InputTypeConvolutional):
            self.n_out = input_type.channels
            self._family = "cnn"
        elif isinstance(input_type, it.InputTypeFeedForward):
            self.n_out = input_type.size
            self._family = "ff"
        elif isinstance(input_type, it.InputTypeRecurrent):
            self.n_out = input_type.size
            self._family = "rnn"
        else:
            raise ValueError(f"BatchNormalization cannot take {input_type}")
        return input_type

    def init_params(self, key, dtype=jnp.float32) -> Dict[str, Array]:
        if self.lock_gamma_beta:
            return {}
        return {"gamma": jnp.full((self.n_out,), self.gamma, dtype),
                "beta": jnp.full((self.n_out,), self.beta, dtype)}

    def init_state(self, dtype=jnp.float32) -> Dict[str, Array]:
        return {"mean": jnp.zeros((self.n_out,), dtype),
                "var": jnp.ones((self.n_out,), dtype)}

    def weight_param_keys(self):
        return ()

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        axes = tuple(range(x.ndim - 1))
        if train:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            new_state = {
                "mean": self.decay * state["mean"] + (1 - self.decay) * mean,
                "var": self.decay * state["var"] + (1 - self.decay) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        xhat = (x - mean) / jnp.sqrt(var + self.eps)
        if self.lock_gamma_beta:
            y = self.gamma * xhat + self.beta
        else:
            y = params["gamma"] * xhat + params["beta"]
        if self.activation:
            y = get_activation(self.activation)(y)
        return y, new_state


@register
@dataclass
class LocalResponseNormalization(Layer):
    """Across-channel LRN (reference:
    nn/layers/normalization/LocalResponseNormalization.java; AlexNet-style
    k + alpha*sum(x^2) over a window of n channels, raised to beta)."""
    k: float = 2.0
    n: float = 5.0
    alpha: float = 1e-4
    beta: float = 0.75

    @property
    def family(self) -> str:
        return "cnn"

    def weight_param_keys(self):
        return ()

    def update_input_type(self, input_type):
        return input_type

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        # x: [B, H, W, C]; window over channel axis.
        half = int(self.n) // 2
        sq = x * x
        # sum over channel window via padded cumsum-free reduce_window
        summed = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add,
            window_dimensions=(1, 1, 1, int(self.n)),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), (0, 0), (0, 0), (half, half)))
        denom = (self.k + self.alpha * summed) ** self.beta
        return x / denom, state
