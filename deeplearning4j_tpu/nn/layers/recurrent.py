"""Recurrent layers: LSTM, GravesLSTM (peepholes), GravesBidirectionalLSTM.

Reference: deeplearning4j-nn/.../nn/layers/recurrent/LSTMHelpers.java
(forward time loop :161, BPTT reverse loop :333, Graves/peephole formulation
per the weight layout at :59), GravesLSTM.java:94,142,
GravesBidirectionalLSTM.java:96-224, BaseRecurrentLayer.java (stateMap for
rnnTimeStep streaming inference).

TPU-native design: the per-timestep Java loop becomes `lax.scan` with all four
gates computed in ONE [*, 4H] matmul per step (MXU-friendly), the input
projection x·W for all timesteps hoisted out of the scan as a single batched
matmul, and autodiff-through-scan replacing the hand-written BPTT loop. Gate
order in the packed 4H axis: [i, f, g(cell), o].
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf import inputs as it
from deeplearning4j_tpu.nn.conf.serde import register
from deeplearning4j_tpu.nn.layers.base import BaseLayer
from deeplearning4j_tpu.nn.weights import init_weights

Array = jax.Array


@register
@dataclass
class LSTM(BaseLayer):
    """Standard LSTM (no peepholes) over [B, T, F] -> [B, T, H]."""
    n_in: Optional[int] = None
    n_out: Optional[int] = None
    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"

    peephole: bool = False

    @property
    def family(self) -> str:
        return "rnn"

    def update_input_type(self, input_type):
        if isinstance(input_type, it.InputTypeRecurrent):
            if self.n_in is None:
                self.n_in = input_type.size
            return it.InputType.recurrent(self.n_out,
                                          input_type.time_series_length)
        raise ValueError(f"{type(self).__name__} needs recurrent input, "
                         f"got {input_type}")

    def init_params(self, key, dtype=jnp.float32) -> Dict[str, Array]:
        k1, k2, k3 = jax.random.split(key, 3)
        h = self.n_out
        scheme = self.weight_init or "xavier"
        w = init_weights(k1, (self.n_in, 4 * h), self.n_in, h, scheme,
                         self.dist, dtype)
        rw = init_weights(k2, (h, 4 * h), h, h, scheme, self.dist, dtype)
        b = jnp.zeros((4 * h,), dtype)
        # forget-gate bias init (reference: conf field forgetGateBiasInit)
        b = b.at[h:2 * h].set(self.forget_gate_bias_init)
        params = {"W": w, "RW": rw, "b": b}
        if self.peephole:
            params["pI"] = jnp.zeros((h,), dtype)
            params["pF"] = jnp.zeros((h,), dtype)
            params["pO"] = jnp.zeros((h,), dtype)
        return params

    def weight_param_keys(self):
        return ("W", "RW")

    def _gates(self, params, xw_t, h_prev, c_prev):
        """One step's gate math. xw_t: [B, 4H] precomputed input projection."""
        z = xw_t + jnp.matmul(h_prev, params["RW"]) + params["b"]
        return self._gates_from_z(params, z, c_prev)

    def _gates_from_z(self, params, z, c_prev):
        """Gate math from a fully-formed pre-activation z [B, 4H]
        (input projection + recurrence + bias already summed) — the
        entry point the cross-layer wavefront uses so its fused GEMMs
        share this exact cell (peepholes, activations and all)."""
        zi, zf, zg, zo = jnp.split(z, 4, axis=-1)
        gate = get_activation(self.gate_activation)
        act = get_activation(self.activation or "tanh")
        if self.peephole:
            i = gate(zi + c_prev * params["pI"])
            f = gate(zf + c_prev * params["pF"])
        else:
            i = gate(zi)
            f = gate(zf)
        g = act(zg)
        c = f * c_prev + i * g
        if self.peephole:
            o = gate(zo + c * params["pO"])
        else:
            o = gate(zo)
        h = o * act(c)
        return h, c

    # Unidirectional LSTMs carry (h, c) across rnn_time_step calls and TBPTT
    # chunks; bidirectional overrides this to False — its backward pass needs
    # the full sequence (the reference likewise throws from rnnTimeStep on
    # GravesBidirectionalLSTM).
    supports_streaming = True

    def initial_carry(self, batch: int, dtype=jnp.float32):
        h = jnp.zeros((batch, self.n_out), dtype)
        c = jnp.zeros((batch, self.n_out), dtype)
        return (h, c)

    def scan_sequence(self, params, x, carry=None, mask=None, reverse=False):
        """Run the full sequence: x [B, T, F] -> (outputs [B, T, H], carry).

        The input projection for ALL timesteps is one big matmul outside the
        scan (the reference computes x_t·W inside its Java time loop,
        LSTMHelpers.java:161 — hoisting it is the TPU win)."""
        b = x.shape[0]
        if carry is None:
            carry = self.initial_carry(b, x.dtype)
        # Fused Pallas path (the accelerated-LSTM analog of the
        # reference's cuDNN helper plug point; ops/lstm.py) — whole
        # recurrence in one kernel, weights/h/c pinned in VMEM.
        from deeplearning4j_tpu.ops.lstm import (fused_lstm_available,
                                                 fused_lstm_scan)
        if fused_lstm_available(x, self.n_out, mask,
                                self.gate_activation,
                                self.activation or "tanh"):
            return fused_lstm_scan(params, x, carry, reverse=reverse)
        xw = jnp.matmul(x, params["W"])  # [B, T, 4H]
        xw_t = jnp.swapaxes(xw, 0, 1)    # [T, B, 4H] time-major for scan
        if mask is not None:
            mask_t = jnp.swapaxes(mask.astype(x.dtype), 0, 1)[..., None]
        else:
            mask_t = None

        def step(c, inp):
            if mask_t is None:
                xw_step = inp
                m = None
            else:
                xw_step, m = inp
            h_prev, c_prev = c
            h, cc = self._gates(params, xw_step, h_prev, c_prev)
            if m is not None:
                # masked steps pass state through unchanged, output 0
                h_keep = m * h + (1 - m) * h_prev
                c_keep = m * cc + (1 - m) * c_prev
                return (h_keep, c_keep), m * h
            return (h, cc), h

        xs = xw_t if mask_t is None else (xw_t, mask_t)
        carry, ys = lax.scan(step, carry, xs, reverse=reverse)
        return jnp.swapaxes(ys, 0, 1), carry  # back to [B, T, H]

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        ys, _ = self.scan_sequence(params, x, mask=mask)
        return ys, state

    def step(self, params, carry, x_t):
        """Single-timestep inference (reference: rnnTimeStep,
        MultiLayerNetwork.java:2234 / BaseRecurrentLayer stateMap)."""
        xw_t = jnp.matmul(x_t, params["W"])
        h_prev, c_prev = carry
        h, c = self._gates(params, xw_t, h_prev, c_prev)
        return (h, c), h


def wavefront_scan_stack(layers, plist, x, carries=None):
    """Run a STACK of unidirectional LSTM layers as one wavefront scan
    (measured r4: 1.14x at B=1024, 1.28x at B=8192 on the 2x200
    char-RNN vs per-layer sequential scans —
    benchmarks/lstm_stack_experiment.py).

    Schedule: T + n - 1 steps; at step s, layer j advances to time
    s - j, consuming h_{j-1}[s-j] — exactly the carry layer j-1 holds
    BEFORE its own update this step. Layer j's recurrence and layer
    j+1's input projection therefore share one operand and fuse into a
    single [B,H]x[H,8H] GEMM per layer (n wide GEMMs per step instead
    of 2n narrow ones over 2·sum(T) sequential steps). An exact
    reordering of the per-layer scans: each layer's cell math runs
    through its own _gates_from_z (peepholes/activations preserved),
    off-wavefront lanes are liveness-masked so states and final
    carries equal the sequential schedule's.

    x: [B, T, F] -> (outputs of the LAST layer [B, T, H_last],
    [per-layer (h, c) final carries]).
    """
    n = len(layers)
    b, t = x.shape[0], x.shape[1]
    xw0 = jnp.matmul(x, plist[0]["W"])            # hoisted, [B, T, 4H0]
    xw0t = jnp.swapaxes(xw0, 0, 1)
    pad = jnp.zeros((n - 1,) + xw0t.shape[1:], xw0t.dtype)
    xs = jnp.concatenate([xw0t, pad], axis=0)     # [T+n-1, B, 4H0]
    if carries is None:
        carries = [l.initial_carry(b, x.dtype) for l in layers]
    fused_w = []
    for j in range(n):
        if j + 1 < n:
            fused_w.append(jnp.concatenate(
                [plist[j]["RW"], plist[j + 1]["W"]], axis=1))
        else:
            fused_w.append(plist[j]["RW"])

    def step(carry, inp):
        xw, s = inp
        hs = [c[0] for c in carry]
        cs = [c[1] for c in carry]
        gem = [jnp.matmul(hs[j], fused_w[j]) for j in range(n)]
        inputs = [xw] + [gem[j - 1][:, 4 * layers[j - 1].n_out:]
                         for j in range(1, n)]
        new = []
        for j, lay in enumerate(layers):
            z = (inputs[j] + gem[j][:, :4 * lay.n_out]
                 + plist[j]["b"])
            h_new, c_new = lay._gates_from_z(plist[j], z, cs[j])
            live = jnp.logical_and(s >= j, s < t + j)
            new.append((jnp.where(live, h_new, hs[j]),
                        jnp.where(live, c_new, cs[j])))
        return tuple(new), new[-1][0]

    carry, ys = lax.scan(step, tuple(carries),
                         (xs, jnp.arange(t + n - 1)))
    return jnp.swapaxes(ys[n - 1:], 0, 1), list(carry)


def wavefront_eligible_run(layers, names, start, *, train, mask,
                           carries, preprocessors, enabled=True):
    """Longest run of fusable LSTM layers beginning at ``start`` (>=2
    indices, else []). Fusable: plain unidirectional LSTM/GravesLSTM
    (supports_streaming), no mask, no inter-layer preprocessor or
    (train-time) dropout inside the run, and the streaming-carries
    dict either covers the whole run or none of it. ``enabled=False``
    (the instance-level switch, e.g. MultiLayerNetwork.lstm_wavefront)
    or DL4JTPU_WAVEFRONT=0 disables."""
    import os
    if (not enabled
            or os.environ.get("DL4JTPU_WAVEFRONT", "1") == "0"
            or mask is not None):
        return []
    def fusable(lay):
        return isinstance(lay, LSTM) and lay.supports_streaming
    if not fusable(layers[start]):
        return []
    run = [start]
    for j in range(start + 1, len(layers)):
        lay = layers[j]
        if not fusable(lay):
            break
        if preprocessors.get(str(j)) is not None:
            break
        if train and (lay.dropout or 0.0) > 0:
            break
        run.append(j)
    if len(run) < 2:
        return []
    if carries is not None:
        inside = [names[j] in carries for j in run]
        if any(inside) and not all(inside):
            return []
    return run


@register
@dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections — the reference's Graves formulation
    (GravesLSTM.java, LSTMHelpers weight layout :59 appends 3 peephole
    columns to the recurrent weights; here they are separate [H] vectors,
    which shards cleaner under tensor parallelism)."""

    def __post_init__(self):
        self.peephole = True

    def weight_param_keys(self):
        return ("W", "RW")


@register
@dataclass
class GravesBidirectionalLSTM(LSTM):
    """Bidirectional Graves LSTM (reference:
    GravesBidirectionalLSTM.java:96-224). ``mode``='add' sums forward and
    backward activations (the reference's behavior); 'concat' concatenates
    (doubling output size)."""
    mode: str = "add"

    supports_streaming = False  # backward direction needs the full sequence

    def __post_init__(self):
        self.peephole = True

    def update_input_type(self, input_type):
        out = super().update_input_type(input_type)
        if self.mode == "concat":
            return it.InputType.recurrent(2 * self.n_out,
                                          out.time_series_length)
        return out

    def init_params(self, key, dtype=jnp.float32) -> Dict[str, Array]:
        kf, kb = jax.random.split(key)
        fwd = super().init_params(kf, dtype)
        bwd = super().init_params(kb, dtype)
        params = {f"F{k}": v for k, v in fwd.items()}
        params.update({f"B{k}": v for k, v in bwd.items()})
        return params

    def weight_param_keys(self):
        return ("FW", "FRW", "BW", "BRW")

    def _split_dir(self, params, prefix):
        return {k[1:]: v for k, v in params.items() if k.startswith(prefix)}

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        fwd_p = self._split_dir(params, "F")
        bwd_p = self._split_dir(params, "B")
        ys_f, _ = self.scan_sequence(fwd_p, x, mask=mask, reverse=False)
        ys_b, _ = self.scan_sequence(bwd_p, x, mask=mask, reverse=True)
        if self.mode == "concat":
            return jnp.concatenate([ys_f, ys_b], axis=-1), state
        return ys_f + ys_b, state
