"""Feed-forward layers: Dense, Embedding, AutoEncoder.

Reference: deeplearning4j-nn/.../nn/layers/feedforward/{dense,embedding,
autoencoder}/ and conf classes nn/conf/layers/{DenseLayer,EmbeddingLayer,
AutoEncoder}.java. The reference's dense forward is
``input.mmul(W).addiRowVector(b)`` through JNI GEMM
(nn/layers/BaseLayer.java:378); here it is a traced einsum on the trailing
axis — which also lets dense layers operate timestep-wise on [B, T, F]
sequences without the reference's FeedForwardToRnn reshaping, and keeps the
matmul on the MXU in one fused XLA program.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf import inputs as it
from deeplearning4j_tpu.nn.conf.serde import register
from deeplearning4j_tpu.nn.layers.base import BaseLayer
from deeplearning4j_tpu.nn.weights import init_weights

Array = jax.Array


@register
@dataclass
class DenseLayer(BaseLayer):
    n_in: Optional[int] = None
    n_out: Optional[int] = None

    def update_input_type(self, input_type):
        if isinstance(input_type, it.InputTypeFeedForward):
            if self.n_in is None:
                self.n_in = input_type.size
            return it.InputType.feed_forward(self.n_out)
        if isinstance(input_type, it.InputTypeRecurrent):
            # dense applied per-timestep
            if self.n_in is None:
                self.n_in = input_type.size
            return it.InputType.recurrent(self.n_out,
                                          input_type.time_series_length)
        raise ValueError(f"{type(self).__name__} cannot take input "
                         f"{input_type}")

    def init_params(self, key, dtype=jnp.float32) -> Dict[str, Array]:
        wkey, _ = jax.random.split(key)
        w = init_weights(wkey, (self.n_in, self.n_out), self.n_in, self.n_out,
                         self.weight_init or "xavier", self.dist, dtype)
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": w, "b": b}

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        z = jnp.matmul(x, params["W"]) + params["b"]
        return get_activation(self.activation or "sigmoid")(z), state

    def pre_output(self, params, x):
        return jnp.matmul(x, params["W"]) + params["b"]


@register
@dataclass
class EmbeddingLayer(BaseLayer):
    """Index -> vector lookup (reference: nn/layers/feedforward/embedding/
    EmbeddingLayer.java — mathematically equivalent to a dense layer on
    one-hot input, implemented as a gather, which XLA lowers to an efficient
    dynamic-slice on TPU)."""
    n_in: Optional[int] = None   # vocabulary size
    n_out: Optional[int] = None  # embedding dim

    def update_input_type(self, input_type):
        if isinstance(input_type, it.InputTypeFeedForward):
            if self.n_in is None:
                self.n_in = input_type.size
            return it.InputType.feed_forward(self.n_out)
        raise ValueError(f"EmbeddingLayer cannot take input {input_type}")

    def init_params(self, key, dtype=jnp.float32) -> Dict[str, Array]:
        wkey, _ = jax.random.split(key)
        w = init_weights(wkey, (self.n_in, self.n_out), self.n_in, self.n_out,
                         self.weight_init or "xavier", self.dist, dtype)
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": w, "b": b}

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        # x: integer indices [B] or [B, 1] (the reference takes a column of
        # indices), or one-hot [B, n_in].
        if jnp.issubdtype(x.dtype, jnp.floating) and x.ndim >= 2 \
                and x.shape[-1] == self.n_in:
            z = jnp.matmul(x, params["W"]) + params["b"]
        else:
            idx = x.astype(jnp.int32)
            if idx.ndim >= 2 and idx.shape[-1] == 1:
                idx = idx[..., 0]
            z = params["W"][idx] + params["b"]
        return get_activation(self.activation or "identity")(z), state


@register
@dataclass
class AutoEncoder(BaseLayer):
    """Denoising autoencoder pretrain layer (reference:
    nn/layers/feedforward/autoencoder/AutoEncoder.java). Forward (supervised
    path) is encode(); pretraining reconstructs corrupted input — see
    MultiLayerNetwork.pretrain."""
    n_in: Optional[int] = None
    n_out: Optional[int] = None
    corruption_level: float = 0.3
    sparsity: float = 0.0

    def is_pretrain_layer(self) -> bool:
        return True

    def update_input_type(self, input_type):
        if isinstance(input_type, it.InputTypeFeedForward):
            if self.n_in is None:
                self.n_in = input_type.size
            return it.InputType.feed_forward(self.n_out)
        raise ValueError(f"AutoEncoder cannot take input {input_type}")

    def init_params(self, key, dtype=jnp.float32) -> Dict[str, Array]:
        wkey, _ = jax.random.split(key)
        w = init_weights(wkey, (self.n_in, self.n_out), self.n_in, self.n_out,
                         self.weight_init or "xavier", self.dist, dtype)
        return {"W": w,
                "b": jnp.full((self.n_out,), self.bias_init, dtype),
                "vb": jnp.zeros((self.n_in,), dtype)}

    def encode(self, params, x):
        act = get_activation(self.activation or "sigmoid")
        return act(jnp.matmul(x, params["W"]) + params["b"])

    def decode(self, params, h):
        act = get_activation(self.activation or "sigmoid")
        return act(jnp.matmul(h, params["W"].T) + params["vb"])

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        return self.encode(params, x), state

    def pretrain_loss(self, params, x, key):
        """Reconstruction cross-entropy on corrupted input."""
        if self.corruption_level > 0 and key is not None:
            keep = jax.random.bernoulli(key, 1.0 - self.corruption_level,
                                        x.shape)
            corrupted = jnp.where(keep, x, 0.0)
        else:
            corrupted = x
        recon = self.decode(params, self.encode(params, corrupted))
        eps = 1e-7
        recon = jnp.clip(recon, eps, 1 - eps)
        return -jnp.mean(jnp.sum(
            x * jnp.log(recon) + (1 - x) * jnp.log(1 - recon), axis=-1))
