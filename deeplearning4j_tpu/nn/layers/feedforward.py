"""Feed-forward layers: Dense, Embedding, AutoEncoder.

Reference: deeplearning4j-nn/.../nn/layers/feedforward/{dense,embedding,
autoencoder}/ and conf classes nn/conf/layers/{DenseLayer,EmbeddingLayer,
AutoEncoder}.java. The reference's dense forward is
``input.mmul(W).addiRowVector(b)`` through JNI GEMM
(nn/layers/BaseLayer.java:378); here it is a traced einsum on the trailing
axis — which also lets dense layers operate timestep-wise on [B, T, F]
sequences without the reference's FeedForwardToRnn reshaping, and keeps the
matmul on the MXU in one fused XLA program.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf import inputs as it
from deeplearning4j_tpu.nn.conf.serde import register
from deeplearning4j_tpu.nn.layers.base import BaseLayer
from deeplearning4j_tpu.nn.weights import init_weights

Array = jax.Array


@register
@dataclass
class DenseLayer(BaseLayer):
    n_in: Optional[int] = None
    n_out: Optional[int] = None

    def update_input_type(self, input_type):
        if isinstance(input_type, it.InputTypeFeedForward):
            if self.n_in is None:
                self.n_in = input_type.size
            return it.InputType.feed_forward(self.n_out)
        if isinstance(input_type, it.InputTypeRecurrent):
            # dense applied per-timestep
            if self.n_in is None:
                self.n_in = input_type.size
            return it.InputType.recurrent(self.n_out,
                                          input_type.time_series_length)
        raise ValueError(f"{type(self).__name__} cannot take input "
                         f"{input_type}")

    def init_params(self, key, dtype=jnp.float32) -> Dict[str, Array]:
        wkey, _ = jax.random.split(key)
        w = init_weights(wkey, (self.n_in, self.n_out), self.n_in, self.n_out,
                         self.weight_init or "xavier", self.dist, dtype)
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": w, "b": b}

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        z = jnp.matmul(x, params["W"]) + params["b"]
        return get_activation(self.activation or "sigmoid")(z), state

    def pre_output(self, params, x):
        return jnp.matmul(x, params["W"]) + params["b"]


@register
@dataclass
class EmbeddingLayer(BaseLayer):
    """Index -> vector lookup (reference: nn/layers/feedforward/embedding/
    EmbeddingLayer.java — mathematically equivalent to a dense layer on
    one-hot input, implemented as a gather, which XLA lowers to an efficient
    dynamic-slice on TPU)."""
    n_in: Optional[int] = None   # vocabulary size
    n_out: Optional[int] = None  # embedding dim

    def update_input_type(self, input_type):
        if isinstance(input_type, it.InputTypeFeedForward):
            if self.n_in is None:
                self.n_in = input_type.size
            return it.InputType.feed_forward(self.n_out)
        raise ValueError(f"EmbeddingLayer cannot take input {input_type}")

    def init_params(self, key, dtype=jnp.float32) -> Dict[str, Array]:
        wkey, _ = jax.random.split(key)
        w = init_weights(wkey, (self.n_in, self.n_out), self.n_in, self.n_out,
                         self.weight_init or "xavier", self.dist, dtype)
        b = jnp.full((self.n_out,), self.bias_init, dtype)
        return {"W": w, "b": b}

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        # x: integer indices [B] or [B, 1] (the reference takes a column of
        # indices), or one-hot [B, n_in].
        if jnp.issubdtype(x.dtype, jnp.floating) and x.ndim >= 2 \
                and x.shape[-1] == self.n_in:
            z = jnp.matmul(x, params["W"]) + params["b"]
        else:
            idx = x.astype(jnp.int32)
            if idx.ndim >= 2 and idx.shape[-1] == 1:
                idx = idx[..., 0]
            z = params["W"][idx] + params["b"]
        return get_activation(self.activation or "identity")(z), state


@register
@dataclass
class AutoEncoder(BaseLayer):
    """Denoising autoencoder pretrain layer (reference:
    nn/layers/feedforward/autoencoder/AutoEncoder.java). Forward (supervised
    path) is encode(); pretraining reconstructs corrupted input — see
    MultiLayerNetwork.pretrain."""
    n_in: Optional[int] = None
    n_out: Optional[int] = None
    corruption_level: float = 0.3
    sparsity: float = 0.0

    def is_pretrain_layer(self) -> bool:
        return True

    def update_input_type(self, input_type):
        if isinstance(input_type, it.InputTypeFeedForward):
            if self.n_in is None:
                self.n_in = input_type.size
            return it.InputType.feed_forward(self.n_out)
        raise ValueError(f"AutoEncoder cannot take input {input_type}")

    def init_params(self, key, dtype=jnp.float32) -> Dict[str, Array]:
        wkey, _ = jax.random.split(key)
        w = init_weights(wkey, (self.n_in, self.n_out), self.n_in, self.n_out,
                         self.weight_init or "xavier", self.dist, dtype)
        return {"W": w,
                "b": jnp.full((self.n_out,), self.bias_init, dtype),
                "vb": jnp.zeros((self.n_in,), dtype)}

    def encode(self, params, x):
        act = get_activation(self.activation or "sigmoid")
        return act(jnp.matmul(x, params["W"]) + params["b"])

    def decode(self, params, h):
        act = get_activation(self.activation or "sigmoid")
        return act(jnp.matmul(h, params["W"].T) + params["vb"])

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        return self.encode(params, x), state

    def pretrain_loss(self, params, x, key):
        """Reconstruction cross-entropy on corrupted input."""
        if self.corruption_level > 0 and key is not None:
            keep = jax.random.bernoulli(key, 1.0 - self.corruption_level,
                                        x.shape)
            corrupted = jnp.where(keep, x, 0.0)
        else:
            corrupted = x
        recon = self.decode(params, self.encode(params, corrupted))
        eps = 1e-7
        recon = jnp.clip(recon, eps, 1 - eps)
        return -jnp.mean(jnp.sum(
            x * jnp.log(recon) + (1 - x) * jnp.log(1 - recon), axis=-1))


@register
@dataclass
class RBM(BaseLayer):
    """Restricted Boltzmann machine pretrain layer (reference:
    nn/layers/feedforward/rbm/RBM.java — CD-k contrastive divergence;
    conf nn/conf/layers/RBM.java with Bernoulli/Gaussian units).

    TPU formulation: CD-1 as autodiff over the free-energy difference
    F(v_data) − F(v_sample) with the Gibbs sample stop-gradiented — the
    gradient of that surrogate IS the CD-1 update, but it rides the same
    jitted pretrain step as the autoencoder instead of hand-written
    positive/negative phase matmuls."""
    n_in: Optional[int] = None
    n_out: Optional[int] = None
    visible_unit: str = "binary"   # 'binary' | 'gaussian'
    hidden_unit: str = "binary"
    k: int = 1                     # CD-k Gibbs steps

    def is_pretrain_layer(self) -> bool:
        return True

    def update_input_type(self, input_type):
        if isinstance(input_type, it.InputTypeFeedForward):
            if self.n_in is None:
                self.n_in = input_type.size
            return it.InputType.feed_forward(self.n_out)
        raise ValueError(f"RBM cannot take input {input_type}")

    def init_params(self, key, dtype=jnp.float32) -> Dict[str, Array]:
        wkey, _ = jax.random.split(key)
        w = init_weights(wkey, (self.n_in, self.n_out), self.n_in,
                         self.n_out, self.weight_init or "xavier",
                         self.dist, dtype)
        return {"W": w, "b": jnp.zeros((self.n_out,), dtype),
                "vb": jnp.zeros((self.n_in,), dtype)}

    def _prop_up(self, params, v):
        return jax.nn.sigmoid(jnp.matmul(v, params["W"]) + params["b"])

    def _prop_down(self, params, h):
        mean = jnp.matmul(h, params["W"].T) + params["vb"]
        return mean if self.visible_unit == "gaussian" \
            else jax.nn.sigmoid(mean)

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        # supervised forward = hidden activation probabilities (reference:
        # RBM.activate)
        return self._prop_up(params, x), state

    def _free_energy(self, params, v):
        """F(v) = −v·vb − Σ softplus(vW + b) (binary visible); gaussian
        visible adds the quadratic term."""
        wx_b = jnp.matmul(v, params["W"]) + params["b"]
        hidden = jnp.sum(jax.nn.softplus(wx_b), axis=-1)
        if self.visible_unit == "gaussian":
            vis = 0.5 * jnp.sum((v - params["vb"]) ** 2, axis=-1)
            return vis - hidden
        return -jnp.matmul(v, params["vb"]) - hidden

    def pretrain_loss(self, params, x, key):
        v = x
        for step in range(self.k):
            key, k1, k2 = jax.random.split(key, 3)
            h_prob = self._prop_up(params, v)
            h = (jax.random.bernoulli(k1, h_prob).astype(x.dtype)
                 if self.hidden_unit == "binary" else h_prob)
            v = self._prop_down(params, h)
            if self.visible_unit == "binary":
                v = jax.random.bernoulli(k2, v).astype(x.dtype)
        v_model = jax.lax.stop_gradient(v)
        return jnp.mean(self._free_energy(params, x)
                        - self._free_energy(params, v_model))
