"""Layer base classes.

The reference splits every layer into a serializable config
(deeplearning4j-nn/.../nn/conf/layers/*.java) and a runtime implementation
with hand-written `activate`/`backpropGradient`
(deeplearning4j-nn/.../nn/layers/**, nn/api/Layer.java:37-309). In a JAX
design the split disappears: a layer is one dataclass that (a) serializes to
JSON, (b) initializes its parameter pytree, and (c) defines a pure, traceable
forward — autodiff replaces `backpropGradient`, and the param-view protocol
(Model.setParamsViewArray, nn/api/Model.java) becomes the params pytree +
`ravel_pytree` for flat views.

Apply contract::

    y, new_state = layer.apply(params, state, x, train=..., key=..., mask=...)

``state`` carries non-trainable buffers (batchnorm running stats); stateless
layers return it unchanged. ``mask`` is an optional [B] or [B, T] {0,1} array
(the reference's feedForwardMaskArray, nn/api/Layer.java:309).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


@dataclass
class Layer:
    """Base config for every layer type.

    Fields with ``None`` defaults inherit the global value from
    `NeuralNetConfiguration` when the layer is added to a network (the
    reference's global-vs-layer override semantics,
    NeuralNetConfiguration.Builder javadoc)."""
    name: Optional[str] = None
    dropout: Optional[float] = None  # drop prob applied to layer INPUT
    l1: Optional[float] = None
    l2: Optional[float] = None
    learning_rate: Optional[float] = None  # per-layer LR override
    bias_learning_rate: Optional[float] = None

    # -- family / shape inference ------------------------------------------
    @property
    def family(self) -> str:
        """Output activation family: 'ff' | 'cnn' | 'rnn'."""
        return "ff"

    @property
    def input_family(self) -> str:
        """Expected input family (for auto preprocessor insertion)."""
        return self.family

    def update_input_type(self, input_type):
        """Resolve nIn from ``input_type`` (mutating, like the reference's
        `setNIn`) and return this layer's output InputType."""
        return input_type

    # -- params / state -----------------------------------------------------
    def init_params(self, key: jax.Array, dtype=jnp.float32
                    ) -> Dict[str, Array]:
        return {}

    def init_state(self, dtype=jnp.float32) -> Dict[str, Array]:
        return {}

    def weight_param_keys(self) -> Tuple[str, ...]:
        """Parameter names subject to l1/l2 regularization (weights, not
        biases — matching the reference's DefaultParamInitializer split)."""
        return ("W",)

    # -- forward ------------------------------------------------------------
    def apply(self, params: Dict[str, Array], state: Dict[str, Array],
              x: Array, *, train: bool = False,
              key: Optional[jax.Array] = None,
              mask: Optional[Array] = None
              ) -> Tuple[Array, Dict[str, Array]]:
        return x, state

    def is_pretrain_layer(self) -> bool:
        return False


@dataclass
class BaseLayer(Layer):
    """Base for layers with weights + activation (the reference's
    nn/conf/layers/BaseLayer.java fields)."""
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    dist: Optional[dict] = None
    bias_init: float = 0.0


def apply_dropout(x: Array, rate: float, key: jax.Array) -> Array:
    """Inverted dropout on a layer's input (reference: util/Dropout.java
    applied from BaseLayer.applyDropOutIfNecessary, nn/layers/BaseLayer.java:497).
    ``rate`` is the drop probability."""
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
