"""Convolution and pooling layers.

Reference: deeplearning4j-nn/.../nn/layers/convolution/ConvolutionLayer.java
(im2col at :177, GEMM at :185, col2im backprop :203, cuDNN helper plug point
:69-76), subsampling/SubsamplingLayer.java, and conf classes
nn/conf/layers/{ConvolutionLayer,Convolution1DLayer,SubsamplingLayer,
Subsampling1DLayer,ZeroPaddingLayer}.java.

TPU-native design: no im2col and no helper indirection — `lax.conv` lowers
straight to the XLA convolution HLO, which the TPU compiler maps onto the MXU
(this *is* the cuDNN-helper equivalent; there is nothing to plug in). Layout
is NHWC / HWIO, XLA:TPU's preferred tiling. Pooling is `lax.reduce_window`.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf import inputs as it
from deeplearning4j_tpu.nn.conf.serde import register
from deeplearning4j_tpu.nn.layers.base import BaseLayer, Layer
from deeplearning4j_tpu.nn.weights import init_weights

Array = jax.Array

_DIMENSION_NUMBERS = ("NHWC", "HWIO", "NHWC")


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def conv_out_size(size: int, k: int, s: int, p: int, mode: str) -> int:
    """Output spatial size (reference: util/ConvolutionUtils.java +
    KernelValidationUtil). 'same' keeps ceil(size/stride); 'strict'/'truncate'
    use the standard (size - k + 2p)/s + 1 (strict additionally requires exact
    divisibility, validated at config time)."""
    if mode == "same":
        return -(-size // s)
    if mode == "strict" and (size - k + 2 * p) % s != 0:
        raise ValueError(
            f"ConvolutionMode.Strict: (size={size} - k={k} + 2*p={p}) not "
            f"divisible by stride {s}")
    return (size - k + 2 * p) // s + 1


def _conv_padding(mode: str, padding: Tuple[int, int]):
    if mode == "same":
        return "SAME"
    return [(padding[0], padding[0]), (padding[1], padding[1])]


@register
@dataclass
class ConvolutionLayer(BaseLayer):
    """2-D convolution, NHWC activations, HWIO kernel."""
    n_in: Optional[int] = None   # input channels
    n_out: Optional[int] = None  # output channels
    kernel_size: Sequence[int] = (5, 5)
    stride: Sequence[int] = (1, 1)
    padding: Sequence[int] = (0, 0)
    convolution_mode: str = "truncate"  # 'strict' | 'truncate' | 'same'
    dilation: Sequence[int] = (1, 1)

    @property
    def family(self) -> str:
        return "cnn"

    def update_input_type(self, input_type):
        if not isinstance(input_type, it.InputTypeConvolutional):
            raise ValueError(f"ConvolutionLayer needs convolutional input, "
                             f"got {input_type}")
        if self.n_in is None:
            self.n_in = input_type.channels
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        dh, dw = _pair(self.dilation)
        # effective kernel under dilation: k + (k-1)(d-1), matching the
        # rhs_dilation passed to lax.conv_general_dilated in apply()
        ekh = kh + (kh - 1) * (dh - 1)
        ekw = kw + (kw - 1) * (dw - 1)
        oh = conv_out_size(input_type.height, ekh, sh, ph,
                           self.convolution_mode)
        ow = conv_out_size(input_type.width, ekw, sw, pw,
                           self.convolution_mode)
        return it.InputType.convolutional(oh, ow, self.n_out)

    def init_params(self, key, dtype=jnp.float32) -> Dict[str, Array]:
        kh, kw = _pair(self.kernel_size)
        fan_in = self.n_in * kh * kw
        fan_out = self.n_out * kh * kw
        wkey, _ = jax.random.split(key)
        w = init_weights(wkey, (kh, kw, self.n_in, self.n_out), fan_in,
                         fan_out, self.weight_init or "xavier", self.dist,
                         dtype)
        return {"W": w, "b": jnp.full((self.n_out,), self.bias_init, dtype)}

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        w = params["W"].astype(x.dtype)
        # bf16 convs still accumulate in f32 on the MXU (hardware property;
        # preferred_element_type would only widen the *output*, and its
        # transpose rule rejects the f32-cotangent/bf16-operand mix)
        z = lax.conv_general_dilated(
            x, w,
            window_strides=_pair(self.stride),
            padding=_conv_padding(self.convolution_mode,
                                  _pair(self.padding)),
            rhs_dilation=_pair(self.dilation),
            dimension_numbers=_DIMENSION_NUMBERS,
        ).astype(x.dtype) + params["b"].astype(x.dtype)
        return get_activation(self.activation or "identity")(z), state


@register
@dataclass
class Convolution1DLayer(ConvolutionLayer):
    """1-D convolution over [B, T, C] sequences (reference:
    nn/conf/layers/Convolution1DLayer.java — implemented there as a 2-D conv
    with width 1; here a direct 1-D conv)."""

    @property
    def family(self) -> str:
        return "rnn"

    @property
    def input_family(self) -> str:
        return "rnn"

    def update_input_type(self, input_type):
        if not isinstance(input_type, it.InputTypeRecurrent):
            raise ValueError("Convolution1DLayer needs recurrent input")
        if self.n_in is None:
            self.n_in = input_type.size
        k = self.kernel_size if isinstance(self.kernel_size, int) \
            else self.kernel_size[0]
        s = self.stride if isinstance(self.stride, int) else self.stride[0]
        p = self.padding if isinstance(self.padding, int) else self.padding[0]
        t = input_type.time_series_length
        ot = conv_out_size(t, k, s, p, self.convolution_mode) if t > 0 else -1
        return it.InputType.recurrent(self.n_out, ot)

    def init_params(self, key, dtype=jnp.float32) -> Dict[str, Array]:
        k = self.kernel_size if isinstance(self.kernel_size, int) \
            else self.kernel_size[0]
        fan_in = self.n_in * k
        fan_out = self.n_out * k
        wkey, _ = jax.random.split(key)
        w = init_weights(wkey, (k, self.n_in, self.n_out), fan_in, fan_out,
                         self.weight_init or "xavier", self.dist, dtype)
        return {"W": w, "b": jnp.full((self.n_out,), self.bias_init, dtype)}

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        k = self.kernel_size if isinstance(self.kernel_size, int) \
            else self.kernel_size[0]
        s = self.stride if isinstance(self.stride, int) else self.stride[0]
        p = self.padding if isinstance(self.padding, int) else self.padding[0]
        pad = "SAME" if self.convolution_mode == "same" else [(p, p)]
        z = lax.conv_general_dilated(
            x, params["W"].astype(x.dtype), window_strides=(s,), padding=pad,
            dimension_numbers=("NWC", "WIO", "NWC"),
        ).astype(x.dtype) + params["b"].astype(x.dtype)
        return get_activation(self.activation or "identity")(z), state


@register
@dataclass
class SubsamplingLayer(Layer):
    """2-D pooling: max | avg | pnorm (reference:
    nn/layers/convolution/subsampling/SubsamplingLayer.java, cuDNN helper
    plug point :76 — here reduce_window, fused by XLA)."""
    pooling_type: str = "max"
    kernel_size: Sequence[int] = (2, 2)
    stride: Sequence[int] = (2, 2)
    padding: Sequence[int] = (0, 0)
    convolution_mode: str = "truncate"
    pnorm: int = 2

    @property
    def family(self) -> str:
        return "cnn"

    def update_input_type(self, input_type):
        if not isinstance(input_type, it.InputTypeConvolutional):
            raise ValueError("SubsamplingLayer needs convolutional input")
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        oh = conv_out_size(input_type.height, kh, sh, ph,
                           self.convolution_mode)
        ow = conv_out_size(input_type.width, kw, sw, pw,
                           self.convolution_mode)
        return it.InputType.convolutional(oh, ow, input_type.channels)

    def weight_param_keys(self):
        return ()

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        window = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        if self.convolution_mode == "same":
            pad = "SAME"
        else:
            pad = ((0, 0), (ph, ph), (pw, pw), (0, 0))
        ptype = self.pooling_type.lower()
        if ptype == "max":
            init = -jnp.inf
            y = lax.reduce_window(x, init, lax.max, window, strides, pad)
        elif ptype in ("avg", "mean"):
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
            y = s / (kh * kw)
        elif ptype == "pnorm":
            p = float(self.pnorm)
            s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window,
                                  strides, pad)
            y = s ** (1.0 / p)
        else:
            raise ValueError(f"Unknown pooling type '{self.pooling_type}'")
        return y, state


@register
@dataclass
class Subsampling1DLayer(SubsamplingLayer):
    """1-D pooling over [B, T, C] (reference:
    nn/conf/layers/Subsampling1DLayer.java)."""

    @property
    def family(self) -> str:
        return "rnn"

    @property
    def input_family(self) -> str:
        return "rnn"

    def update_input_type(self, input_type):
        if not isinstance(input_type, it.InputTypeRecurrent):
            raise ValueError("Subsampling1DLayer needs recurrent input")
        k = self.kernel_size if isinstance(self.kernel_size, int) \
            else self.kernel_size[0]
        s = self.stride if isinstance(self.stride, int) else self.stride[0]
        p = self.padding if isinstance(self.padding, int) else self.padding[0]
        t = input_type.time_series_length
        ot = conv_out_size(t, k, s, p, self.convolution_mode) if t > 0 else -1
        return it.InputType.recurrent(input_type.size, ot)

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        k = self.kernel_size if isinstance(self.kernel_size, int) \
            else self.kernel_size[0]
        s = self.stride if isinstance(self.stride, int) else self.stride[0]
        p = self.padding if isinstance(self.padding, int) else self.padding[0]
        window = (1, k, 1)
        strides = (1, s, 1)
        pad = "SAME" if self.convolution_mode == "same" \
            else ((0, 0), (p, p), (0, 0))
        ptype = self.pooling_type.lower()
        if ptype == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pad)
        elif ptype in ("avg", "mean"):
            y = lax.reduce_window(x, 0.0, lax.add, window, strides, pad) / k
        elif ptype == "pnorm":
            pw = float(self.pnorm)
            y = lax.reduce_window(jnp.abs(x) ** pw, 0.0, lax.add, window,
                                  strides, pad) ** (1.0 / pw)
        else:
            raise ValueError(f"Unknown pooling type '{self.pooling_type}'")
        return y, state


@register
@dataclass
class ZeroPaddingLayer(Layer):
    """Spatial zero padding (reference: nn/conf/layers/ZeroPaddingLayer.java,
    nn/layers/convolution/ZeroPaddingLayer.java)."""
    padding: Sequence[int] = (1, 1)  # (ph, pw) or (top, bottom, left, right)

    @property
    def family(self) -> str:
        return "cnn"

    def weight_param_keys(self):
        return ()

    def _pads(self):
        p = self.padding
        if len(p) == 2:
            return (p[0], p[0], p[1], p[1])
        return tuple(p)

    def update_input_type(self, input_type):
        if not isinstance(input_type, it.InputTypeConvolutional):
            raise ValueError("ZeroPaddingLayer needs convolutional input")
        t, b, l, r = self._pads()
        return it.InputType.convolutional(input_type.height + t + b,
                                          input_type.width + l + r,
                                          input_type.channels)

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        t, b, l, r = self._pads()
        y = jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0)))
        return y, state
