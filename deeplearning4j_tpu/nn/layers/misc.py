"""Utility layers: ActivationLayer, DropoutLayer, GlobalPoolingLayer,
FrozenLayer wrapper.

Reference: deeplearning4j-nn/.../nn/conf/layers/{ActivationLayer,
DropoutLayer,GlobalPoolingLayer}.java, nn/layers/pooling/GlobalPoolingLayer
(incl. masked pooling via util/MaskedReductionUtil.java), and
nn/layers/FrozenLayer.java (used by transfer learning).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf import inputs as it
from deeplearning4j_tpu.nn.conf.serde import register
from deeplearning4j_tpu.nn.layers.base import Layer, apply_dropout

Array = jax.Array


@register
@dataclass
class ActivationLayer(Layer):
    activation: str = "relu"
    _family: str = "ff"

    @property
    def family(self):
        return self._family

    @property
    def input_family(self):
        # passthrough: applies elementwise to whatever family arrives —
        # input_family is queried before update_input_type runs, so it
        # must not claim 'ff' and trigger a flattening preprocessor
        return "any"

    def weight_param_keys(self):
        return ()

    def update_input_type(self, input_type):
        if isinstance(input_type, it.InputTypeConvolutional):
            self._family = "cnn"
        elif isinstance(input_type, it.InputTypeRecurrent):
            self._family = "rnn"
        else:
            self._family = "ff"
        return input_type

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        return get_activation(self.activation)(x), state


@register
@dataclass
class DropoutLayer(Layer):
    """Standalone dropout layer; rate is the drop probability."""
    rate: float = 0.5
    _family: str = "ff"

    @property
    def family(self):
        return self._family

    @property
    def input_family(self):
        return "any"  # elementwise passthrough, as ActivationLayer

    def weight_param_keys(self):
        return ()

    def update_input_type(self, input_type):
        if isinstance(input_type, it.InputTypeConvolutional):
            self._family = "cnn"
        elif isinstance(input_type, it.InputTypeRecurrent):
            self._family = "rnn"
        else:
            self._family = "ff"
        return input_type

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        if train and self.rate > 0 and key is not None:
            x = apply_dropout(x, self.rate, key)
        return x, state


@register
@dataclass
class GlobalPoolingLayer(Layer):
    """Pool over time ([B, T, F] -> [B, F]) or space ([B, H, W, C] -> [B, C]).
    Types: max | avg | sum | pnorm. Honors sequence masks (the reference's
    MaskedReductionUtil semantics: masked steps excluded from the
    reduction)."""
    pooling_type: str = "max"
    pnorm: int = 2
    collapse_dimensions: bool = True
    _in_family: str = "any"

    @property
    def family(self):
        return "ff"

    @property
    def input_family(self):
        # 'any': pools whatever family arrives (rnn time axis or cnn
        # spatial axes) — no preprocessor should be auto-inserted.
        return self._in_family

    def weight_param_keys(self):
        return ()

    def update_input_type(self, input_type):
        if isinstance(input_type, it.InputTypeRecurrent):
            self._in_family = "rnn"
            return it.InputType.feed_forward(input_type.size)
        if isinstance(input_type, it.InputTypeConvolutional):
            self._in_family = "cnn"
            return it.InputType.feed_forward(input_type.channels)
        raise ValueError(f"GlobalPoolingLayer cannot take {input_type}")

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        if x.ndim == 3:
            axes = (1,)
        elif x.ndim == 4:
            axes = (1, 2)
        else:
            raise ValueError("GlobalPoolingLayer needs 3-D or 4-D input")
        ptype = self.pooling_type.lower()
        if mask is not None and x.ndim == 3:
            m = mask.astype(x.dtype)[..., None]  # [B, T, 1]
            if ptype == "max":
                neg = jnp.finfo(x.dtype).min
                y = jnp.max(jnp.where(m > 0, x, neg), axis=1)
            elif ptype in ("avg", "mean"):
                y = jnp.sum(x * m, axis=1) / jnp.maximum(
                    jnp.sum(m, axis=1), 1.0)
            elif ptype == "sum":
                y = jnp.sum(x * m, axis=1)
            elif ptype == "pnorm":
                p = float(self.pnorm)
                y = jnp.sum((jnp.abs(x) * m) ** p, axis=1) ** (1.0 / p)
            else:
                raise ValueError(self.pooling_type)
            return y, state
        if ptype == "max":
            y = jnp.max(x, axis=axes)
        elif ptype in ("avg", "mean"):
            y = jnp.mean(x, axis=axes)
        elif ptype == "sum":
            y = jnp.sum(x, axis=axes)
        elif ptype == "pnorm":
            p = float(self.pnorm)
            y = jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p)
        else:
            raise ValueError(self.pooling_type)
        return y, state


@register
@dataclass
class FrozenLayer(Layer):
    """Wrapper marking an inner layer's params as non-trainable (reference:
    nn/layers/FrozenLayer.java; used by TransferLearning.setFeatureExtractor).
    Gradients are stopped via a trainability mask in the updater, so the inner
    layer still traces normally."""
    inner: Optional[Layer] = None

    @property
    def family(self):
        return self.inner.family

    @property
    def input_family(self):
        return self.inner.input_family

    def update_input_type(self, input_type):
        return self.inner.update_input_type(input_type)

    def init_params(self, key, dtype=jnp.float32):
        return self.inner.init_params(key, dtype)

    def init_state(self, dtype=jnp.float32):
        return self.inner.init_state(dtype)

    def weight_param_keys(self):
        return self.inner.weight_param_keys()

    def apply(self, params, state, x, *, train=False, key=None, mask=None):
        # Inference-mode inner apply: frozen layers don't update BN stats.
        return self.inner.apply(params, state, x, train=False, key=key,
                                mask=mask)
