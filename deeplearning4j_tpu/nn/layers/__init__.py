from deeplearning4j_tpu.nn.layers.base import Layer, BaseLayer  # noqa: F401
from deeplearning4j_tpu.nn.layers.feedforward import (  # noqa: F401
    DenseLayer,
    EmbeddingLayer,
    AutoEncoder,
)
from deeplearning4j_tpu.nn.layers.output import (  # noqa: F401
    OutputLayer,
    RnnOutputLayer,
    LossLayer,
    CenterLossOutputLayer,
)
from deeplearning4j_tpu.nn.layers.convolution import (  # noqa: F401
    ConvolutionLayer,
    Convolution1DLayer,
    SubsamplingLayer,
    Subsampling1DLayer,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.layers.normalization import (  # noqa: F401
    BatchNormalization,
    LocalResponseNormalization,
)
from deeplearning4j_tpu.nn.layers.misc import (  # noqa: F401
    ActivationLayer,
    DropoutLayer,
    GlobalPoolingLayer,
)
from deeplearning4j_tpu.nn.layers.attention import (  # noqa: F401
    LayerNormalization,
    MultiHeadAttention,
    TransformerBlock,
)
from deeplearning4j_tpu.nn.layers.recurrent import (  # noqa: F401
    GravesLSTM,
    LSTM,
    GravesBidirectionalLSTM,
)
from deeplearning4j_tpu.nn.layers.variational import (  # noqa: F401
    VariationalAutoencoder,
)
