"""Output and loss layers.

Reference: deeplearning4j-nn/.../nn/layers/BaseOutputLayer.java,
OutputLayer.java, LossLayer.java, recurrent/RnnOutputLayer.java,
training/CenterLossOutputLayer.java:49 and conf classes in nn/conf/layers/.
An output layer is a dense layer + loss function; `computeScore` becomes the
loss term of the jitted step's scalar objective, and the hand-written error
signal (`backpropGradient`) is replaced by autodiff.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf import inputs as it
from deeplearning4j_tpu.nn.conf.serde import register
from deeplearning4j_tpu.nn.layers.base import BaseLayer
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
from deeplearning4j_tpu.nn.lossfunctions import get_loss
from deeplearning4j_tpu.nn.weights import init_weights

Array = jax.Array


@register
@dataclass
class OutputLayer(DenseLayer):
    """Dense + loss. Default activation softmax / loss MCXENT, matching the
    reference's defaults."""
    loss_function: str = "mcxent"

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        z = self.pre_output(params, x)
        return get_activation(self.activation or "softmax")(z), state

    def loss(self, params, x, labels, mask=None):
        z = self.pre_output(params, x)
        fn = get_loss(self.loss_function)
        return fn(labels, z, self.activation or "softmax", mask)


@register
@dataclass
class RnnOutputLayer(OutputLayer):
    """Per-timestep output over [B, T, F] sequences (reference:
    nn/layers/recurrent/RnnOutputLayer.java — the reference reshapes to 2-D
    and back; operating on the trailing axis makes that a no-op here)."""

    @property
    def family(self) -> str:
        return "rnn"

    @property
    def input_family(self) -> str:
        return "rnn"

    def update_input_type(self, input_type):
        if isinstance(input_type, it.InputTypeRecurrent):
            if self.n_in is None:
                self.n_in = input_type.size
            return it.InputType.recurrent(self.n_out,
                                          input_type.time_series_length)
        if isinstance(input_type, it.InputTypeFeedForward):
            if self.n_in is None:
                self.n_in = input_type.size
            return it.InputType.recurrent(self.n_out)
        raise ValueError(f"RnnOutputLayer cannot take input {input_type}")


@register
@dataclass
class LossLayer(BaseLayer):
    """Loss without parameters (reference: nn/layers/LossLayer.java)."""
    loss_function: str = "mcxent"

    def update_input_type(self, input_type):
        return input_type

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        return get_activation(self.activation or "softmax")(x), state

    def weight_param_keys(self):
        return ()

    def loss(self, params, x, labels, mask=None):
        fn = get_loss(self.loss_function)
        return fn(labels, x, self.activation or "softmax", mask)


@register
@dataclass
class CenterLossOutputLayer(OutputLayer):
    """Output layer with an auxiliary center-loss term pulling features
    toward per-class centers (reference:
    nn/layers/training/CenterLossOutputLayer.java:49 and conf
    nn/conf/layers/CenterLossOutputLayer.java). Centers are non-trainable
    state updated with rate ``alpha`` toward the batch feature means, and the
    center distance joins the loss scaled by ``lambda_``."""
    alpha: float = 0.05
    lambda_: float = 2e-4

    def init_state(self, dtype=jnp.float32) -> Dict[str, Array]:
        return {"centers": jnp.zeros((self.n_out, self.n_in), dtype)}

    def loss(self, params, x, labels, mask=None, state=None):
        base = super().loss(params, x, labels, mask)
        if state is None:
            return base
        centers = state["centers"]
        assigned = jnp.matmul(labels, centers)  # [B, n_in]
        center_l = jnp.mean(jnp.sum((x - assigned) ** 2, axis=-1))
        return base + 0.5 * self.lambda_ * center_l

    def update_centers(self, state, x, labels):
        centers = state["centers"]
        counts = jnp.sum(labels, axis=0)  # [n_out]
        sums = jnp.matmul(labels.T, x)    # [n_out, n_in]
        means = sums / jnp.maximum(counts[:, None], 1.0)
        seen = (counts > 0)[:, None]
        new_centers = jnp.where(seen,
                                centers + self.alpha * (means - centers),
                                centers)
        return {**state, "centers": new_centers}
