"""Attention layers: LayerNormalization, MultiHeadAttention, TransformerBlock.

NET-NEW capability: the reference has no attention anywhere (SURVEY.md §5.7 —
its only long-sequence mechanism is truncated BPTT), but the task requires
long-context sequence/context parallelism, which needs attention. These
layers are designed for sharding from the start:

- head dim is a real axis ([B, T, H, Dh]) so tensor parallelism shards H
  over the 'model' mesh axis with zero layout churn;
- the functional core (`dot_product_attention`) takes explicit query/key
  position offsets so sequence-parallel callers (ring attention,
  parallel/ring.py) can apply causal masks on global positions while holding
  only a local block;
- matmuls are laid out [*, T, Dh] x [*, Dh, S] — MXU-shaped, bfloat16-safe
  (softmax accumulates in >=f32; f64 inputs keep f64).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf import inputs as it
from deeplearning4j_tpu.nn.conf.serde import register
from deeplearning4j_tpu.nn.layers.base import BaseLayer, Layer, apply_dropout
from deeplearning4j_tpu.nn.weights import init_weights

Array = jax.Array

NEG_INF = -1e30


def dot_product_attention(q: Array, k: Array, v: Array, *,
                          causal: bool = False,
                          mask: Optional[Array] = None,
                          q_offset=0, kv_offset=0,
                          scale: Optional[float] = None) -> Array:
    """Scaled dot-product attention.

    q: [B, T, H, Dh]; k, v: [B, S, H, Dh] -> [B, T, H, Dh].
    ``mask``: optional [B, S] {0,1} key-validity mask.
    ``q_offset``/``kv_offset``: global positions of q[0] / k[0] — causal
    masking compares global positions, enabling blockwise/ring callers.
    Scores and softmax accumulate in at least float32 (f64 inputs keep
    f64 — the gradient-check suites run whole nets in float64).
    """
    dh = q.shape[-1]
    # dh is static — python math keeps scale concrete under jit (the
    # pallas dispatch below needs a weak-typed float)
    scale = (dh ** -0.5) if scale is None else scale
    # Pallas fast path (ops/flash_attention.py) — the cuDNN-helper
    # pattern: kernel when eligible, this jnp path as the fallback.
    # Offsets must be concrete (custom_vjp statics); traced offsets
    # (shard_map ring callers) take the fallback.
    if isinstance(q_offset, int) and isinstance(kv_offset, int) \
            and isinstance(scale, (int, float)):
        from deeplearning4j_tpu.ops.flash_attention import (
            flash_attention, flash_attention_available)
        if flash_attention_available(q, k, mask):
            return flash_attention(q, k, v, causal=causal,
                                   q_offset=q_offset, kv_offset=kv_offset,
                                   scale=float(scale))
    # [B, H, T, S] — accumulate in >=f32 (f64 inputs keep f64: the
    # gradient-check suites run the whole net in float64)
    acc = jnp.promote_types(q.dtype, jnp.float32)
    scores = jnp.einsum("bthd,bshd->bhts", q, k,
                        preferred_element_type=acc) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = kv_offset + jnp.arange(k.shape[1])
        cm = qpos[:, None] >= kpos[None, :]  # [T, S]
        scores = jnp.where(cm[None, None], scores, NEG_INF)
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :].astype(bool), scores,
                           NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", weights.astype(v.dtype), v)
    return out


def _ln_dtype(dtype):
    return jnp.promote_types(dtype, jnp.float32)


def layer_norm(x: Array, gamma: Array, beta: Array,
               eps: float = 1e-5) -> Array:
    xf = x.astype(_ln_dtype(x.dtype))
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * gamma + beta).astype(x.dtype)


@register
@dataclass
class LayerNormalization(Layer):
    """Per-feature layer norm over the last axis (net-new vs the reference,
    which only has BatchNormalization)."""
    n_out: Optional[int] = None
    eps: float = 1e-5
    _family: str = "ff"

    @property
    def family(self):
        return self._family

    @property
    def input_family(self):
        return self._family

    def weight_param_keys(self):
        return ()

    def update_input_type(self, input_type):
        if isinstance(input_type, it.InputTypeRecurrent):
            self._family = "rnn"
            self.n_out = self.n_out or input_type.size
        elif isinstance(input_type, it.InputTypeFeedForward):
            self._family = "ff"
            self.n_out = self.n_out or input_type.size
        else:
            raise ValueError("LayerNormalization needs ff/rnn input")
        return input_type

    def init_params(self, key, dtype=jnp.float32):
        return {"gamma": jnp.ones((self.n_out,), _ln_dtype(dtype)),
                "beta": jnp.zeros((self.n_out,), _ln_dtype(dtype))}

    def apply(self, params, state, x, *, train=False, key=None, mask=None):
        return layer_norm(x, params["gamma"], params["beta"], self.eps), state


@register
@dataclass
class MultiHeadAttention(BaseLayer):
    """Self-attention over [B, T, D] -> [B, T, D]."""
    n_in: Optional[int] = None
    n_out: Optional[int] = None
    n_heads: int = 4
    causal: bool = False
    attn_dropout: float = 0.0

    @property
    def family(self):
        return "rnn"

    def update_input_type(self, input_type):
        if not isinstance(input_type, it.InputTypeRecurrent):
            raise ValueError("MultiHeadAttention needs recurrent input")
        if self.n_in is None:
            self.n_in = input_type.size
        if self.n_out is None:
            self.n_out = self.n_in
        if self.n_out % self.n_heads:
            raise ValueError(f"n_out {self.n_out} not divisible by n_heads "
                             f"{self.n_heads}")
        return it.InputType.recurrent(self.n_out,
                                      input_type.time_series_length)

    def init_params(self, key, dtype=jnp.float32):
        kq, kk, kv, ko = jax.random.split(key, 4)
        d, o = self.n_in, self.n_out
        scheme = self.weight_init or "xavier"

        def w(k, shape, fi, fo):
            return init_weights(k, shape, fi, fo, scheme, self.dist, dtype)

        return {"Wq": w(kq, (d, o), d, o), "Wk": w(kk, (d, o), d, o),
                "Wv": w(kv, (d, o), d, o), "Wo": w(ko, (o, o), o, o),
                "bq": jnp.zeros((o,), dtype), "bk": jnp.zeros((o,), dtype),
                "bv": jnp.zeros((o,), dtype), "bo": jnp.zeros((o,), dtype)}

    def weight_param_keys(self):
        return ("Wq", "Wk", "Wv", "Wo")

    def _heads(self, x, w, b):
        y = jnp.matmul(x, w.astype(x.dtype)) + b.astype(x.dtype)
        b_, t = y.shape[0], y.shape[1]
        return y.reshape(b_, t, self.n_heads, self.n_out // self.n_heads)

    def apply(self, params, state, x, *, train=False, key=None, mask=None):
        q = self._heads(x, params["Wq"], params["bq"])
        k = self._heads(x, params["Wk"], params["bk"])
        v = self._heads(x, params["Wv"], params["bv"])
        out = dot_product_attention(q, k, v, causal=self.causal, mask=mask)
        b_, t = out.shape[0], out.shape[1]
        out = out.reshape(b_, t, self.n_out)
        out = jnp.matmul(out, params["Wo"].astype(x.dtype)) \
            + params["bo"].astype(x.dtype)
        if train and self.attn_dropout > 0 and key is not None:
            out = apply_dropout(out, self.attn_dropout, key)
        return out, state


@register
@dataclass
class TransformerBlock(BaseLayer):
    """Pre-LN transformer block: x + MHA(LN(x)); x + MLP(LN(x)).

    One config class rather than a vertex subgraph: the block is the unit of
    pipeline parallelism (stacked blocks shard over the 'pipe' axis) and of
    `lax.scan` over depth, so it must be a single traced function.
    """
    n_in: Optional[int] = None
    n_heads: int = 4
    mlp_ratio: int = 4
    causal: bool = True
    eps: float = 1e-5

    @property
    def family(self):
        return "rnn"

    def update_input_type(self, input_type):
        if not isinstance(input_type, it.InputTypeRecurrent):
            raise ValueError("TransformerBlock needs recurrent input")
        if self.n_in is None:
            self.n_in = input_type.size
        if self.n_in % self.n_heads:
            raise ValueError("n_in not divisible by n_heads")
        return input_type

    @property
    def n_out(self):
        return self.n_in

    def init_params(self, key, dtype=jnp.float32):
        d = self.n_in
        f = d * self.mlp_ratio
        ks = jax.random.split(key, 6)
        scheme = self.weight_init or "xavier"

        def w(k, shape, fi, fo):
            return init_weights(k, shape, fi, fo, scheme, self.dist, dtype)

        return {
            "Wq": w(ks[0], (d, d), d, d), "Wk": w(ks[1], (d, d), d, d),
            "Wv": w(ks[2], (d, d), d, d), "Wo": w(ks[3], (d, d), d, d),
            "W1": w(ks[4], (d, f), d, f), "W2": w(ks[5], (f, d), f, d),
            "b1": jnp.zeros((f,), dtype), "b2": jnp.zeros((d,), dtype),
            # LN params stay >=f32 (bf16 LN scales lose precision); f64
            # nets keep f64 so gradient checks see full precision
            "ln1g": jnp.ones((d,), _ln_dtype(dtype)),
            "ln1b": jnp.zeros((d,), _ln_dtype(dtype)),
            "ln2g": jnp.ones((d,), _ln_dtype(dtype)),
            "ln2b": jnp.zeros((d,), _ln_dtype(dtype)),
        }

    def weight_param_keys(self):
        return ("Wq", "Wk", "Wv", "Wo", "W1", "W2")

    def apply(self, params, state, x, *, train=False, key=None, mask=None):
        d = self.n_in
        h = layer_norm(x, params["ln1g"], params["ln1b"], self.eps)

        def heads(y):
            b_, t = y.shape[0], y.shape[1]
            return y.reshape(b_, t, self.n_heads, d // self.n_heads)

        q = heads(jnp.matmul(h, params["Wq"].astype(h.dtype)))
        k = heads(jnp.matmul(h, params["Wk"].astype(h.dtype)))
        v = heads(jnp.matmul(h, params["Wv"].astype(h.dtype)))
        a = dot_product_attention(q, k, v, causal=self.causal, mask=mask)
        b_, t = a.shape[0], a.shape[1]
        x = x + jnp.matmul(a.reshape(b_, t, d),
                           params["Wo"].astype(x.dtype))
        h = layer_norm(x, params["ln2g"], params["ln2b"], self.eps)
        h = get_activation("gelu")(jnp.matmul(h, params["W1"].astype(h.dtype))
                                   + params["b1"].astype(h.dtype))
        x = x + jnp.matmul(h, params["W2"].astype(x.dtype)) \
            + params["b2"].astype(x.dtype)
        return x, state
