"""Variational autoencoder layer.

Reference: deeplearning4j-nn/.../nn/layers/variational/
VariationalAutoencoder.java (1,095 LoC) + conf
nn/conf/layers/variational/{VariationalAutoencoder,Gaussian...}.java.
A pretrain layer: encoder MLP -> (mean, log-var) -> reparameterized sample ->
decoder MLP -> pluggable reconstruction distribution; unsupervised loss is
-ELBO. In the supervised forward pass the layer outputs the latent mean (same
as the reference's activate()).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf import inputs as it
from deeplearning4j_tpu.nn.conf.serde import register
from deeplearning4j_tpu.nn.layers.base import BaseLayer
from deeplearning4j_tpu.nn.weights import init_weights

Array = jax.Array


@register
@dataclass
class VariationalAutoencoder(BaseLayer):
    n_in: Optional[int] = None
    n_out: Optional[int] = None          # latent size
    encoder_layer_sizes: Tuple[int, ...] = (100,)
    decoder_layer_sizes: Tuple[int, ...] = (100,)
    # "gaussian" | "bernoulli" | "exponential", or a composite: a sequence
    # of (feature_count, kind) pairs modeling successive feature slices
    # with different distributions (reference: nn/conf/layers/variational/
    # CompositeReconstructionDistribution.java — addDistribution(size, dist))
    reconstruction_distribution: Any = "gaussian"
    pzx_activation: str = "identity"
    num_samples: int = 1

    def is_pretrain_layer(self) -> bool:
        return True

    def update_input_type(self, input_type):
        if isinstance(input_type, it.InputTypeFeedForward):
            if self.n_in is None:
                self.n_in = input_type.size
            return it.InputType.feed_forward(self.n_out)
        raise ValueError(f"VAE cannot take input {input_type}")

    def _components(self) -> Tuple[Tuple[int, str], ...]:
        """Normalize to ((feature_count, kind), ...); a plain string kind
        covers all n_in features."""
        rd = self.reconstruction_distribution
        if isinstance(rd, str):
            return ((self.n_in, rd),)
        comps = tuple((int(n), str(k)) for n, k in rd)
        total = sum(n for n, _ in comps)
        if total != self.n_in:
            raise ValueError(
                f"Composite reconstruction distribution covers {total} "
                f"features but n_in={self.n_in}")
        return comps

    @staticmethod
    def _params_per_feature(kind: str) -> int:
        return 1 if kind == "bernoulli" else 2

    def _recon_out_size(self) -> int:
        return sum(n * self._params_per_feature(k)
                   for n, k in self._components())

    def init_params(self, key, dtype=jnp.float32) -> Dict[str, Array]:
        params: Dict[str, Array] = {}
        scheme = self.weight_init or "xavier"
        sizes_enc = [self.n_in, *self.encoder_layer_sizes]
        n_keys = (len(self.encoder_layer_sizes)
                  + len(self.decoder_layer_sizes) + 4)
        keys = jax.random.split(key, n_keys)
        ki = 0
        for i in range(len(sizes_enc) - 1):
            params[f"eW{i}"] = init_weights(
                keys[ki], (sizes_enc[i], sizes_enc[i + 1]), sizes_enc[i],
                sizes_enc[i + 1], scheme, self.dist, dtype); ki += 1
            params[f"eb{i}"] = jnp.zeros((sizes_enc[i + 1],), dtype)
        last_enc = sizes_enc[-1]
        params["muW"] = init_weights(keys[ki], (last_enc, self.n_out),
                                     last_enc, self.n_out, scheme, self.dist,
                                     dtype); ki += 1
        params["mub"] = jnp.zeros((self.n_out,), dtype)
        params["lvW"] = init_weights(keys[ki], (last_enc, self.n_out),
                                     last_enc, self.n_out, scheme, self.dist,
                                     dtype); ki += 1
        params["lvb"] = jnp.zeros((self.n_out,), dtype)
        sizes_dec = [self.n_out, *self.decoder_layer_sizes]
        for i in range(len(sizes_dec) - 1):
            params[f"dW{i}"] = init_weights(
                keys[ki], (sizes_dec[i], sizes_dec[i + 1]), sizes_dec[i],
                sizes_dec[i + 1], scheme, self.dist, dtype); ki += 1
            params[f"db{i}"] = jnp.zeros((sizes_dec[i + 1],), dtype)
        last_dec = sizes_dec[-1]
        out_size = self._recon_out_size()
        params["xW"] = init_weights(keys[ki], (last_dec, out_size), last_dec,
                                    out_size, scheme, self.dist, dtype)
        params["xb"] = jnp.zeros((out_size,), dtype)
        return params

    def weight_param_keys(self):
        keys = ["muW", "lvW", "xW"]
        keys += [f"eW{i}" for i in range(len(self.encoder_layer_sizes))]
        keys += [f"dW{i}" for i in range(len(self.decoder_layer_sizes))]
        return tuple(keys)

    def _encode(self, params, x):
        act = get_activation(self.activation or "tanh")
        h = x
        for i in range(len(self.encoder_layer_sizes)):
            h = act(jnp.matmul(h, params[f"eW{i}"]) + params[f"eb{i}"])
        mu = jnp.matmul(h, params["muW"]) + params["mub"]
        mu = get_activation(self.pzx_activation)(mu)
        logvar = jnp.matmul(h, params["lvW"]) + params["lvb"]
        return mu, logvar

    def _decode(self, params, z):
        act = get_activation(self.activation or "tanh")
        h = z
        for i in range(len(self.decoder_layer_sizes)):
            h = act(jnp.matmul(h, params[f"dW{i}"]) + params[f"db{i}"])
        return jnp.matmul(h, params["xW"]) + params["xb"]

    def apply(self, params, state, x, *, train=False, key=None, mask=None
              ) -> Tuple[Array, Dict]:
        mu, _ = self._encode(params, x)
        return mu, state

    @staticmethod
    def _component_log_prob(kind: str, raw, x):
        """Per-example log p(x|raw) for one distribution over one feature
        slice; ``raw`` carries params_per_feature(kind) params per feature."""
        eps = 1e-7
        if kind == "bernoulli":
            p = jnp.clip(jax.nn.sigmoid(raw), eps, 1 - eps)
            return jnp.sum(x * jnp.log(p) + (1 - x) * jnp.log(1 - p),
                           axis=-1)
        if kind == "gaussian":
            mean, logvar = jnp.split(raw, 2, axis=-1)
            var = jnp.exp(logvar)
            return jnp.sum(
                -0.5 * (jnp.log(2 * jnp.pi) + logvar
                        + (x - mean) ** 2 / var), axis=-1)
        if kind == "exponential":
            # rate = exp(gamma); log p = gamma - rate*x
            gamma, _ = jnp.split(raw, 2, axis=-1)
            return jnp.sum(gamma - jnp.exp(gamma) * x, axis=-1)
        raise ValueError(f"Unknown reconstruction distribution '{kind}'")

    def _recon_log_prob(self, recon_raw, x):
        total = 0.0
        x_off = raw_off = 0
        for n, kind in self._components():
            width = n * self._params_per_feature(kind)
            total = total + self._component_log_prob(
                kind, recon_raw[..., raw_off:raw_off + width],
                x[..., x_off:x_off + n])
            x_off += n
            raw_off += width
        return total

    def pretrain_loss(self, params, x, key):
        """-ELBO = -E[log p(x|z)] + KL(q(z|x) || N(0,1))."""
        mu, logvar = self._encode(params, x)
        total = 0.0
        for s in range(self.num_samples):
            eps = jax.random.normal(jax.random.fold_in(key, s), mu.shape,
                                    mu.dtype)
            z = mu + jnp.exp(0.5 * logvar) * eps
            recon_raw = self._decode(params, z)
            total = total + self._recon_log_prob(recon_raw, x)
        log_px = total / self.num_samples
        kl = 0.5 * jnp.sum(jnp.exp(logvar) + mu ** 2 - 1.0 - logvar, axis=-1)
        return jnp.mean(kl - log_px)

    def reconstruction_prob(self, params, x, key, num_samples=None):
        """Importance-sampled reconstruction probability (reference:
        VariationalAutoencoder.reconstructionProbability)."""
        n = num_samples or self.num_samples
        mu, logvar = self._encode(params, x)
        logps = []
        for s in range(n):
            eps = jax.random.normal(jax.random.fold_in(key, s), mu.shape,
                                    mu.dtype)
            z = mu + jnp.exp(0.5 * logvar) * eps
            recon_raw = self._decode(params, z)
            logps.append(self._recon_log_prob(recon_raw, x))
        return jax.nn.logsumexp(jnp.stack(logps), axis=0) - jnp.log(float(n))
