"""Streaming inference / online training routes.

Parity with the reference's dl4j-streaming module (reference:
deeplearning4j-scaleout/dl4j-streaming/.../kafka/NDArrayPublisher.java,
NDArrayConsumer.java and routes/DL4jServeRouteBuilder.java — Camel
routes wiring Kafka topics through a model for online inference or
incremental fit). Kafka/Camel are cluster middleware, not part of the
training system; the equivalent here is a broker-agnostic in-process
pub/sub with the same topology (topics, publishers, consumers, a serve
route pumping input-topic arrays through the model onto an output
topic). A real deployment would back `Topic` with its broker of choice;
the route logic is unchanged.
"""
from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, Dict, List, Optional

import numpy as np

log = logging.getLogger("deeplearning4j_tpu")


class Topic:
    """A named stream of ndarrays (the Kafka-topic role)."""

    def __init__(self, name: str, maxsize: int = 1024):
        self.name = name
        self._q: "queue.Queue" = queue.Queue(maxsize)

    def put(self, arr: np.ndarray, timeout: Optional[float] = None) -> None:
        self._q.put(np.asarray(arr), timeout=timeout)

    def get(self, timeout: Optional[float] = None) -> np.ndarray:
        return self._q.get(timeout=timeout)

    def empty(self) -> bool:
        return self._q.empty()


class TopicRegistry:
    _topics: Dict[str, Topic] = {}
    _lock = threading.Lock()

    @classmethod
    def topic(cls, name: str) -> Topic:
        with cls._lock:
            if name not in cls._topics:
                cls._topics[name] = Topic(name)
            return cls._topics[name]


class NDArrayPublisher:
    """Reference: kafka/NDArrayPublisher.java."""

    def __init__(self, topic: str):
        self._topic = TopicRegistry.topic(topic)

    def publish(self, arr: np.ndarray) -> None:
        self._topic.put(arr)


class NDArrayConsumer:
    """Reference: kafka/NDArrayConsumer.java."""

    def __init__(self, topic: str):
        self._topic = TopicRegistry.topic(topic)

    def consume(self, timeout: Optional[float] = 5.0) -> np.ndarray:
        return self._topic.get(timeout=timeout)


class DL4jServeRoute:
    """Online-inference route (reference: routes/
    DL4jServeRouteBuilder.java): consume arrays from `input_topic`, run
    `model.output`, publish predictions to `output_topic`. `start()`
    spawns the pump thread; `stop()` drains and joins."""

    def __init__(self, model, input_topic: str, output_topic: str,
                 transform: Optional[Callable] = None):
        self.model = model
        self.consumer = NDArrayConsumer(input_topic)
        self.publisher = NDArrayPublisher(output_topic)
        self.transform = transform
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _publish_interruptible(self, out: np.ndarray) -> None:
        """Bounded put that keeps observing the stop flag — a stalled
        output consumer must not wedge the pump past stop()."""
        while not self._stop.is_set():
            try:
                self.publisher._topic.put(out, timeout=0.1)
                return
            except queue.Full:
                continue

    def _pump(self) -> None:
        while not self._stop.is_set():
            try:
                arr = self.consumer.consume(timeout=0.1)
            except queue.Empty:
                continue
            try:
                if self.transform is not None:
                    arr = self.transform(arr)
                out = self.model.output(arr)
                if isinstance(out, list):
                    out = out[0]
            except Exception:
                # per-exchange error handling (the Camel route's
                # equivalent): log and keep serving
                log.exception("serve route: dropping bad input batch")
                continue
            self._publish_interruptible(np.asarray(out))

    def start(self) -> None:
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


class DL4jTrainingRoute:
    """Online-training route: consume (features, labels) pairs and fit
    incrementally (the reference's training-route variant of
    DL4jServeRouteBuilder)."""

    def __init__(self, model, features_topic: str, labels_topic: str):
        self.model = model
        self.features = NDArrayConsumer(features_topic)
        self.labels = NDArrayConsumer(labels_topic)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _pump(self) -> None:
        pending_x: Optional[np.ndarray] = None
        while not self._stop.is_set():
            try:
                if pending_x is None:
                    pending_x = self.features.consume(timeout=0.1)
                # keep the feature batch until its labels arrive —
                # dropping it would misalign every later (x, y) pair
                y = self.labels.consume(timeout=0.1)
            except queue.Empty:
                continue
            x, pending_x = pending_x, None
            try:
                self.model.fit(x, y)
            except Exception:
                log.exception("training route: dropping bad batch")

    def start(self) -> None:
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
