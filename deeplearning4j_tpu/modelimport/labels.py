"""ImageNet label table + prediction decoding.

Parity with the reference's `Utils/ImageNetLabels.java` (reference:
deeplearning4j-modelimport/.../trainedmodels/Utils/ImageNetLabels.java)
and `TrainedModels.decodePredictions` (TrainedModels.java:128-160).
The reference fetches Keras's `imagenet_class_index.json` from S3 at
first use and keeps `label = entry[1]` per class index; this analog
resolves the same JSON through a local-first chain (zero-egress
containers cannot download, and even online the file should be
cached):

1. an explicit ``path=`` argument,
2. ``$DL4JTPU_IMAGENET_INDEX``,
3. Keras's own cache (``~/.keras/models/imagenet_class_index.json`` —
   present on any machine that ever ran
   ``keras...decode_predictions``),
4. this framework's cache dir (``~/.dl4j_tpu/imagenet_class_index.json``
   — the reference's ``~/.dl4j/trainedmodels`` analog),
5. download from the reference's URL (``ImageNetLabels.jsonUrl``) into
   cache 4 — raising a clear error when the network is unreachable.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

# the exact URL the reference hardcodes (ImageNetLabels.java:17)
JSON_URL = ("https://s3.amazonaws.com/deep-learning-models/"
            "image-models/imagenet_class_index.json")
_CACHE_DIR = os.path.join(os.path.expanduser("~"), ".dl4j_tpu")


class ImageNetLabels:
    """Lazy ImageNet class-index table (1000 entries); mirrors the
    reference's static getLabels()/getLabel(n) surface plus the wnid
    (synset id) the Keras JSON also carries."""

    _labels: Optional[List[str]] = None
    _wnids: Optional[List[str]] = None
    # resolved file that populated _labels ("<download>" for the URL
    # path): the in-memory cache is only valid while the EFFECTIVE
    # source (path arg / env var / fallback chain) still resolves to
    # the same file — without this key, a load(path=...) would
    # permanently hijack later default loads, and pointing the env var
    # at a different existing file would keep serving the stale table
    # (advisor r4)
    _source: Optional[str] = None

    @classmethod
    def _candidate_paths(cls, path: Optional[str]) -> List[str]:
        cands = []
        if path:
            cands.append(path)
        env = os.environ.get("DL4JTPU_IMAGENET_INDEX")
        if env:
            cands.append(env)
        home = os.path.expanduser("~")
        cands.append(os.path.join(home, ".keras", "models",
                                  "imagenet_class_index.json"))
        cands.append(os.path.join(_CACHE_DIR,
                                  "imagenet_class_index.json"))
        return cands

    @classmethod
    def load(cls, path: Optional[str] = None) -> List[str]:
        """Resolve and parse the class-index JSON (see module doc for
        the chain). Idempotent while the effective source is stable:
        the in-memory cache is keyed on the resolved file, so a
        load(path=...) or a changed $DL4JTPU_IMAGENET_INDEX re-parses
        from the newly resolved source instead of serving stale data.
        An EXPLICITLY named source (path= or the env var) that does
        not exist raises instead of silently falling through to a
        cache that may hold a different table — validated BEFORE the
        in-memory cache short-circuit, so setting a bad env var after
        a successful load still errors instead of silently serving
        the previously cached table."""
        for name, explicit in (("path argument", path),
                               ("$DL4JTPU_IMAGENET_INDEX",
                                os.environ.get(
                                    "DL4JTPU_IMAGENET_INDEX"))):
            if explicit and not os.path.exists(explicit):
                raise FileNotFoundError(
                    f"{name} names {explicit!r}, which does not exist "
                    "(refusing to fall back to a cached table that "
                    "may differ)")
        # in-memory cache is valid when nothing explicit is requested
        # (a prior explicit load keeps serving top_k/decode_predictions)
        # OR when the explicit source is the same file that populated
        # it; a DIFFERENT explicit file re-parses (advisor r4: a
        # changed env var must not serve the stale table)
        explicit = path or os.environ.get("DL4JTPU_IMAGENET_INDEX")
        if cls._labels is not None and (
                explicit is None
                or os.path.abspath(explicit) == cls._source):
            return cls._labels
        tried = []
        for cand in cls._candidate_paths(path):
            if os.path.exists(cand):
                with open(cand) as f:
                    out = cls._parse(json.load(f))
                cls._source = os.path.abspath(cand)
                return out
            tried.append(cand)
        # last resort: the reference's download (ImageNetLabels.java)
        try:
            from urllib.request import urlopen
            with urlopen(JSON_URL, timeout=20) as r:
                data = json.load(r)
            os.makedirs(_CACHE_DIR, exist_ok=True)
            with open(os.path.join(_CACHE_DIR,
                                   "imagenet_class_index.json"),
                      "w") as f:
                json.dump(data, f)
            out = cls._parse(data)
            cls._source = "<download>"
            return out
        except Exception as e:
            raise FileNotFoundError(
                "imagenet_class_index.json not found locally and the "
                f"download failed ({type(e).__name__}: {e}). Looked "
                f"in: {tried}. Provide the standard Keras class-index "
                "JSON via path=, $DL4JTPU_IMAGENET_INDEX, or place it "
                f"in {_CACHE_DIR}/ (source URL: {JSON_URL})."
            ) from e

    @classmethod
    def _parse(cls, data: dict) -> List[str]:
        n = len(data)
        labels = [""] * n
        wnids = [""] * n
        for k, (wnid, label) in data.items():
            labels[int(k)] = label       # reference: jsonMap.get(i)[1]
            wnids[int(k)] = wnid
        cls._labels, cls._wnids = labels, wnids
        return labels

    @classmethod
    def get_labels(cls) -> List[str]:
        return cls.load()

    @classmethod
    def get_label(cls, n: int) -> str:
        return cls.load()[n]

    @classmethod
    def get_wnid(cls, n: int) -> str:
        cls.load()
        return cls._wnids[n]


def get_predicted_classes(predictions) -> np.ndarray:
    """Argmax class index per row — the reference's
    `getPredictedClasses`-style API (BaseOutputLayer semantics applied
    to zoo predictions). predictions: [batch, n_classes]."""
    return np.argmax(np.asarray(predictions), axis=-1)


def top_k(predictions, k: int = 5,
          labels: Optional[Sequence[str]] = None
          ) -> List[List[Tuple[int, str, float]]]:
    """Per batch row, the top-k (class_index, label, probability)
    tuples, descending. ``labels`` defaults to the ImageNet table."""
    # a single unbatched [n_classes] vector is a batch of one (the
    # reference's INDArray contract is 2-D; r4 review)
    p = np.atleast_2d(np.asarray(predictions, dtype=np.float64))
    if labels is None:
        labels = ImageNetLabels.get_labels()
    out = []
    for row in p:
        idx = np.argsort(-row)[:k]
        out.append([(int(i), labels[int(i)], float(row[i]))
                    for i in idx])
    return out


def decode_predictions(predictions, top: int = 5,
                       labels: Optional[Sequence[str]] = None) -> str:
    """The reference's TrainedModels.decodePredictions string format:
    per batch row, the top-k matches as '<percent>%, <label>' lines
    (TrainedModels.java:128 — "%3f%%, " + label)."""
    p = np.atleast_2d(np.asarray(predictions))
    desc = ""
    multi = p.shape[0] > 1
    for batch, picks in enumerate(top_k(p, k=top, labels=labels)):
        desc += "Predictions for batch "
        if multi:
            desc += str(batch)
        desc += " :"
        for i, label, prob in picks:
            desc += "\n\t" + "%3f" % (prob * 100) + "%, " + label
    return desc
