"""Keras model import: HDF5 / JSON → MultiLayerNetwork or ComputationGraph.

Parity with the reference's deeplearning4j-modelimport module:
`KerasModelImport` entry points (reference: KerasModelImport.java:48-231),
`KerasModel`/`KerasSequentialModel` (KerasModel.java,
KerasSequentialModel.java) and the 14 per-layer mappers
(layers/Keras*.java): Dense, Convolution, Pooling, GlobalPooling,
BatchNormalization, Activation, Dropout, Embedding, Flatten, Input, Loss,
Lstm, Merge, ZeroPadding. Both Keras 1 (`nb_filter`, `border_mode`,
`dim_ordering`, per-gate LSTM weights) and Keras 2 (`filters`,
`padding`, fused gate blocks) config/weight formats are handled, matching
the reference's dual support (KerasLayer.java keras_version dispatch).

TPU-first divergence: the reference converts everything to NCHW
(KerasLayer dim-ordering conversion, TensorFlowCnnToFeedForwardPreProcessor)
because libnd4j convs are channels-first. This framework's activations are
NHWC — the layout XLA:TPU tiles best — so TensorFlow-Keras kernels (HWIO)
copy through with **no transpose** and Theano-ordering kernels (OIHW) are
permuted once at import. Inference inputs are NHWC.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.modelimport.hdf5 import Hdf5Archive
from deeplearning4j_tpu.nn.conf.configuration import (
    NeuralNetConfiguration, MultiLayerConfiguration,
    ComputationGraphConfiguration)
from deeplearning4j_tpu.nn.conf import inputs as it
from deeplearning4j_tpu.nn.conf.preprocessors import \
    CnnToFeedForwardPreProcessor
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.nn.layers.feedforward import (DenseLayer,
                                                      EmbeddingLayer)
from deeplearning4j_tpu.nn.layers.convolution import (
    ConvolutionLayer, Convolution1DLayer, SubsamplingLayer,
    Subsampling1DLayer, ZeroPaddingLayer)
from deeplearning4j_tpu.nn.layers.normalization import BatchNormalization
from deeplearning4j_tpu.nn.layers.misc import (ActivationLayer, DropoutLayer,
                                               GlobalPoolingLayer)
from deeplearning4j_tpu.nn.layers.recurrent import LSTM
from deeplearning4j_tpu.nn.layers.output import OutputLayer, RnnOutputLayer
from deeplearning4j_tpu.nn.graph.vertices import (MergeVertex,
                                                  ElementWiseVertex)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.graph.computation_graph import ComputationGraph


class InvalidKerasConfigurationException(ValueError):
    """Reference: exceptions/InvalidKerasConfigurationException.java."""


class UnsupportedKerasConfigurationException(ValueError):
    """Reference: exceptions/UnsupportedKerasConfigurationException.java."""


# ---------------------------------------------------------------------------
# activation / loss name mapping (reference: KerasLayer.mapActivation,
# KerasLossLayer loss mapping)
# ---------------------------------------------------------------------------

_ACTIVATIONS = {
    "linear": "identity", "relu": "relu", "sigmoid": "sigmoid",
    "tanh": "tanh", "softmax": "softmax", "softplus": "softplus",
    "softsign": "softsign", "elu": "elu", "selu": "selu",
    "hard_sigmoid": "hardsigmoid", "leakyrelu": "leakyrelu",
    "leaky_relu": "leakyrelu", "gelu": "gelu", "swish": "swish",
}

_LOSSES = {
    "categorical_crossentropy": "mcxent",
    "sparse_categorical_crossentropy": "mcxent",
    "binary_crossentropy": "xent",
    "mean_squared_error": "mse", "mse": "mse",
    "mean_absolute_error": "mae", "mae": "mae",
    "mean_absolute_percentage_error": "mape", "mape": "mape",
    "mean_squared_logarithmic_error": "msle", "msle": "msle",
    "hinge": "hinge", "squared_hinge": "squared_hinge",
    "kullback_leibler_divergence": "kl_divergence",
    "kld": "kl_divergence",
    "poisson": "poisson", "cosine_proximity": "cosine_proximity",
}


def map_activation(name: str) -> str:
    if name not in _ACTIVATIONS:
        raise UnsupportedKerasConfigurationException(
            f"Unknown Keras activation '{name}'")
    return _ACTIVATIONS[name]


def map_loss(name: str) -> str:
    if name not in _LOSSES:
        raise UnsupportedKerasConfigurationException(
            f"Unknown Keras loss '{name}'")
    return _LOSSES[name]


# ---------------------------------------------------------------------------
# per-layer config mapping (reference: layers/Keras*.java)
# ---------------------------------------------------------------------------

def _cfg(layer: Dict) -> Dict:
    return layer.get("config", {})


def _k1(cfg: Dict, k2_name: str, k1_name: str, default=None):
    """Fetch a config field under its Keras-2 name, falling back to the
    Keras-1 name (reference: KerasLayer version dispatch)."""
    if k2_name in cfg:
        return cfg[k2_name]
    return cfg.get(k1_name, default)


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return int(v[0]), int(v[1] if len(v) > 1 else v[0])
    return int(v), int(v)


def _padding_mode(cfg: Dict) -> str:
    mode = _k1(cfg, "padding", "border_mode", "valid")
    if mode == "same":
        return "same"
    if mode == "valid":
        return "truncate"
    raise UnsupportedKerasConfigurationException(
        f"Unsupported Keras padding mode '{mode}'")


def _dim_ordering(cfg: Dict) -> str:
    """'tf' (channels_last / HWIO kernels) or 'th' (channels_first / OIHW)."""
    v = _k1(cfg, "data_format", "dim_ordering", "channels_last")
    if v in ("channels_last", "tf", "default"):
        return "tf"
    if v in ("channels_first", "th"):
        return "th"
    raise UnsupportedKerasConfigurationException(f"dim ordering '{v}'")


def map_keras_layer(class_name: str, layer: Dict) -> Optional[Layer]:
    """Map one Keras layer dict to a framework Layer config; returns None
    for structural layers absorbed elsewhere (Input, Flatten, Reshape —
    the reference turns Flatten into a preprocessor, KerasFlatten.java;
    here family-change shape inference inserts it automatically)."""
    cfg = _cfg(layer)
    name = cfg.get("name") or layer.get("name")

    if class_name in ("InputLayer", "Flatten", "Reshape", "Masking"):
        return None

    if class_name == "Dense":
        act = map_activation(cfg.get("activation", "linear"))
        n_out = _k1(cfg, "units", "output_dim")
        return DenseLayer(name=name, n_out=int(n_out), activation=act)

    if class_name == "Activation":
        return ActivationLayer(name=name,
                               activation=map_activation(cfg["activation"]))

    if class_name in ("Dropout", "SpatialDropout2D", "SpatialDropout1D"):
        # reference maps dropout rate p -> dropOut retain semantics
        return DropoutLayer(name=name, rate=float(_k1(cfg, "rate", "p")))

    if class_name in ("Conv2D", "Convolution2D"):
        filters = int(_k1(cfg, "filters", "nb_filter"))
        if "kernel_size" in cfg:
            kh, kw = _pair(cfg["kernel_size"])
        else:  # Keras 1
            kh, kw = int(cfg["nb_row"]), int(cfg["nb_col"])
        sh, sw = _pair(_k1(cfg, "strides", "subsample", (1, 1)))
        act = map_activation(cfg.get("activation", "linear"))
        return ConvolutionLayer(name=name, n_out=filters,
                                kernel_size=(kh, kw), stride=(sh, sw),
                                convolution_mode=_padding_mode(cfg),
                                activation=act)

    if class_name in ("Conv1D", "Convolution1D"):
        filters = int(_k1(cfg, "filters", "nb_filter"))
        k = _k1(cfg, "kernel_size", "filter_length")
        k = int(k[0] if isinstance(k, (list, tuple)) else k)
        s = _k1(cfg, "strides", "subsample_length", 1)
        s = int(s[0] if isinstance(s, (list, tuple)) else s)
        act = map_activation(cfg.get("activation", "linear"))
        return Convolution1DLayer(name=name, n_out=filters,
                                  kernel_size=(k,), stride=(s,),
                                  convolution_mode=_padding_mode(cfg),
                                  activation=act)

    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        ptype = "max" if class_name.startswith("Max") else "avg"
        kh, kw = _pair(_k1(cfg, "pool_size", "pool_size", (2, 2)))
        strides = _k1(cfg, "strides", "strides")
        sh, sw = _pair(strides) if strides is not None else (kh, kw)
        return SubsamplingLayer(name=name, pooling_type=ptype,
                                kernel_size=(kh, kw), stride=(sh, sw),
                                convolution_mode=_padding_mode(cfg))

    if class_name in ("MaxPooling1D", "AveragePooling1D"):
        ptype = "max" if class_name.startswith("Max") else "avg"
        k = _k1(cfg, "pool_size", "pool_length", 2)
        k = int(k[0] if isinstance(k, (list, tuple)) else k)
        s = _k1(cfg, "strides", "stride")
        if s is None:
            s = k
        s = int(s[0] if isinstance(s, (list, tuple)) else s)
        return Subsampling1DLayer(name=name, pooling_type=ptype,
                                  kernel_size=(k,), stride=(s,),
                                  convolution_mode=_padding_mode(cfg))

    if class_name in ("GlobalMaxPooling1D", "GlobalAveragePooling1D",
                      "GlobalMaxPooling2D", "GlobalAveragePooling2D"):
        ptype = "max" if "Max" in class_name else "avg"
        return GlobalPoolingLayer(name=name, pooling_type=ptype)

    if class_name == "BatchNormalization":
        # activation explicitly identity: Keras BN has no fused
        # activation, and leaving it unset would inherit the config
        # DSL's DL4J-style 'sigmoid' default (round-3 bug: every
        # imported BN silently sigmoided its output)
        return BatchNormalization(
            name=name, activation="identity",
            decay=float(_k1(cfg, "momentum", "momentum", 0.99)),
            eps=float(cfg.get("epsilon", 1e-3)))

    if class_name == "Embedding":
        n_in = int(_k1(cfg, "input_dim", "input_dim"))
        n_out = int(_k1(cfg, "output_dim", "output_dim"))
        return EmbeddingLayer(name=name, n_in=n_in, n_out=n_out,
                              activation="identity")

    if class_name == "LSTM":
        n_out = int(_k1(cfg, "units", "output_dim"))
        act = map_activation(cfg.get("activation", "tanh"))
        gate = map_activation(_k1(cfg, "recurrent_activation",
                                  "inner_activation", "hard_sigmoid"))
        fb = 1.0 if _k1(cfg, "unit_forget_bias", "forget_bias_init",
                        True) else 0.0
        return LSTM(name=name, n_out=n_out, activation=act,
                    gate_activation=gate, forget_gate_bias_init=fb)

    if class_name == "ZeroPadding2D":
        pad = cfg.get("padding", (1, 1))
        if isinstance(pad, (list, tuple)) and pad and \
                isinstance(pad[0], (list, tuple)):
            (t, b), (l, r) = pad
            return ZeroPaddingLayer(name=name, padding=(t, b, l, r))
        ph, pw = _pair(pad)
        return ZeroPaddingLayer(name=name, padding=(ph, pw))

    raise UnsupportedKerasConfigurationException(
        f"Unsupported Keras layer type '{class_name}'")


def map_merge_vertex(class_name: str, layer: Dict):
    cfg = _cfg(layer)
    if class_name in ("Concatenate", "Merge") and \
            cfg.get("mode", "concat") in ("concat", "concatenate", None):
        return MergeVertex()
    if class_name == "Add" or (class_name == "Merge"
                               and cfg.get("mode") == "sum"):
        return ElementWiseVertex(op="add")
    if class_name == "Subtract":
        return ElementWiseVertex(op="subtract")
    if class_name == "Multiply" or (class_name == "Merge"
                                    and cfg.get("mode") == "mul"):
        return ElementWiseVertex(op="product")
    if class_name == "Average" or (class_name == "Merge"
                                   and cfg.get("mode") == "ave"):
        return ElementWiseVertex(op="average")
    if class_name == "Maximum":
        return ElementWiseVertex(op="max")
    raise UnsupportedKerasConfigurationException(
        f"Unsupported Keras merge '{class_name}'")


_MERGE_CLASSES = ("Merge", "Add", "Subtract", "Multiply", "Average",
                  "Maximum", "Concatenate")


def _input_type_from_shape(shape, dim_ordering: str = "tf"):
    """batch_input_shape (None, ...) → InputType."""
    dims = [d for d in shape[1:]]
    if len(dims) == 1:
        return it.InputType.feed_forward(dims[0])
    if len(dims) == 2:
        return it.InputType.recurrent(dims[1], dims[0])
    if len(dims) == 3:
        if dim_ordering == "th":
            c, h, w = dims
        else:
            h, w, c = dims
        return it.InputType.convolutional(h, w, c)
    raise UnsupportedKerasConfigurationException(
        f"Cannot infer input type from shape {shape}")


# ---------------------------------------------------------------------------
# weight conversion (reference: KerasLayer.getWeightsFromHdf5 + per-layer
# setWeights; gate order & transposes)
# ---------------------------------------------------------------------------

def _short(weight_name: str) -> str:
    """'model/dense_1/kernel:0' → 'kernel'."""
    base = weight_name.split("/")[-1]
    return base.split(":")[0]


def _find(short: Dict[str, np.ndarray], *names: str):
    """Resolve a canonical weight name against both modern
    ('kernel', 'W') and Keras-1 flat ('dense_1_W') naming: exact key
    first, then '<layer>_<name>' suffix match."""
    for n in names:
        if n in short:
            return short[n]
    for n in names:
        for k, v in short.items():
            if k.endswith("_" + n):
                return v
    return None


def convert_weights(framework_layer: Layer, kweights: Dict[str, np.ndarray],
                    dim_ordering: str = "tf", keras_major: int = 2
                    ) -> Tuple[Dict[str, np.ndarray],
                               Dict[str, np.ndarray]]:
    """Map a Keras layer's weight dict onto (params, state) for the
    corresponding framework layer. Handles Keras-1 per-gate LSTM weights,
    Theano OIHW kernels, and BN running stats.

    Kernel layout depends on BOTH the ordering and the Keras era
    (reference: KerasLayer.java keras_version dispatch +
    KerasConvolution weight layout handling): Keras-1 'th' stored
    Theano OIHW kernels, but Keras-2 ``channels_first`` models still
    store HWIO — for those only the activation layout differs, and
    transposing the kernel would corrupt it."""
    short = {_short(k): v for k, v in kweights.items()}
    params: Dict[str, np.ndarray] = {}
    state: Dict[str, np.ndarray] = {}

    if isinstance(framework_layer, BatchNormalization):
        params["gamma"] = _find(short, "gamma")
        params["beta"] = _find(short, "beta")
        state["mean"] = _find(short, "moving_mean", "running_mean")
        var = _find(short, "moving_variance")
        if var is None:
            # Keras 1 stored std for some backends; DL4J treats it as var
            var = _find(short, "running_std")
        state["var"] = var
        return ({k: v for k, v in params.items() if v is not None},
                {k: v for k, v in state.items() if v is not None})

    if isinstance(framework_layer, LSTM):
        if "kernel" in short:  # Keras 2 fused blocks, gate order i,f,c,o
            params["W"] = short["kernel"]
            params["RW"] = short["recurrent_kernel"]
            if "bias" in short:
                params["b"] = short["bias"]
        else:  # Keras 1 per-gate: W_i U_i b_i W_c U_c b_c W_f U_f b_f W_o...
            def gate(prefix):
                for k, v in short.items():
                    if k.endswith(prefix) or k == prefix:
                        return v
                raise InvalidKerasConfigurationException(
                    f"LSTM weight '{prefix}' missing; have {list(short)}")
            # our gate order: i, f, g(c), o (recurrent.py _gates)
            params["W"] = np.concatenate(
                [gate("W_i"), gate("W_f"), gate("W_c"), gate("W_o")], axis=1)
            params["RW"] = np.concatenate(
                [gate("U_i"), gate("U_f"), gate("U_c"), gate("U_o")], axis=1)
            params["b"] = np.concatenate(
                [gate("b_i"), gate("b_f"), gate("b_c"), gate("b_o")], axis=0)
        return params, state

    if isinstance(framework_layer, (ConvolutionLayer,)):
        w = _find(short, "kernel", "W")
        if w is None:
            raise InvalidKerasConfigurationException(
                f"Conv weights missing; have {list(short)}")
        if w.ndim == 4 and dim_ordering == "th" and keras_major < 2:
            w = np.transpose(w, (2, 3, 1, 0))  # OIHW → HWIO
        if isinstance(framework_layer, Convolution1DLayer) and w.ndim == 3:
            # Keras Conv1D kernel [k, in, out] → our [1, k, in, out]
            w = w[None, :, :, :]
        params["W"] = w
        b = _find(short, "bias", "b")
        if b is not None:
            params["b"] = b
        return params, state

    if isinstance(framework_layer, EmbeddingLayer):
        emb = _find(short, "embeddings", "W")
        params["W"] = emb
        params["b"] = np.zeros(emb.shape[1], emb.dtype)
        return params, state

    if isinstance(framework_layer, DenseLayer):  # includes OutputLayer
        params["W"] = _find(short, "kernel", "W")
        b = _find(short, "bias", "b")
        if b is not None:
            params["b"] = b
        return params, state

    return params, state


# ---------------------------------------------------------------------------
# model-level import (reference: KerasSequentialModel.java, KerasModel.java)
# ---------------------------------------------------------------------------

def _model_config_from_archive(archive: Hdf5Archive) -> Dict:
    cfg = archive.read_attribute_as_json("model_config")
    if cfg is None:
        raise InvalidKerasConfigurationException(
            "HDF5 file has no 'model_config' attribute (weights-only file? "
            "pass the architecture JSON separately)")
    return cfg


def _sequential_layers(model_config: Dict) -> List[Dict]:
    cfg = model_config.get("config")
    if isinstance(cfg, list):  # Keras 1 / early 2
        return cfg
    return cfg["layers"]


class KerasSequentialModel:
    """Sequential Keras JSON → MultiLayerConfiguration
    (reference: KerasSequentialModel.java)."""

    def __init__(self, model_config: Dict,
                 training_config: Optional[Dict] = None,
                 enforce_training_config: bool = False):
        if model_config.get("class_name") not in ("Sequential",):
            raise InvalidKerasConfigurationException(
                f"Not a Sequential model: {model_config.get('class_name')}")
        self.layer_configs = _sequential_layers(model_config)
        self.training_config = training_config
        if enforce_training_config and training_config is None:
            # reference: KerasModel.java enforceTrainingConfig — fail fast
            # when the file was saved without compile() information
            raise InvalidKerasConfigurationException(
                "enforce_training_config=True but the file has no "
                "'training_config' attribute (model was not compiled "
                "before saving)")
        self.layers: List[Layer] = []
        self.keras_names: List[str] = []
        self.dim_ordering = "tf"
        self.input_type = None
        # dense layers whose preceding (dropped) Flatten declared
        # channels_first: Keras-2's Flatten already transposed to HWC
        # order there, so the th dense-row permutation must NOT apply
        self.hwc_flatten_dense: set = set()
        self._build()

    def _loss(self) -> Optional[str]:
        if not self.training_config:
            return None
        loss = self.training_config.get("loss")
        if isinstance(loss, dict):
            loss = next(iter(loss.values()))
        if isinstance(loss, dict):  # keras serialized loss object
            loss = loss.get("config", {}).get("name", loss.get("class_name"))
            loss = str(loss).lower()
        return map_loss(loss) if loss else None

    def _build(self) -> None:
        # dim ordering first, from ANY layer that declares it: the input
        # shape is usually on an InputLayer that precedes the conv layer
        # carrying data_format, and NCHW shapes must not be read as NHWC
        # (reference: KerasModel resolves dimOrdering across all layers
        # before building input types)
        for lc in self.layer_configs:
            cfg = _cfg(lc)
            if "dim_ordering" in cfg or "data_format" in cfg:
                self.dim_ordering = _dim_ordering(cfg)
                break
        pending_hwc_flatten = False
        for lc in self.layer_configs:
            cname = lc["class_name"]
            cfg = _cfg(lc)
            shape = cfg.get("batch_input_shape")
            if shape is not None and self.input_type is None:
                self.input_type = _input_type_from_shape(
                    shape, self.dim_ordering)
            mapped = map_keras_layer(cname, lc)
            if mapped is None:
                if cname == "Flatten" and ("data_format" in cfg
                                           or "dim_ordering" in cfg) \
                        and _dim_ordering(cfg) == "th":
                    pending_hwc_flatten = True
                continue
            # Fallback numbering is 0-based over *mapped* layers
            # (layer_0 for the first unnamed mapped layer). Real Keras
            # files always carry names; this only affects synthetic
            # configs, and the round-2 renumbering is intentional.
            name = (cfg.get("name") or lc.get("name")
                    or f"layer_{len(self.layers)}")
            if pending_hwc_flatten:
                if isinstance(mapped, DenseLayer):
                    self.hwc_flatten_dense.add(name)
                    pending_hwc_flatten = False
                elif isinstance(mapped, (DropoutLayer, ActivationLayer,
                                         BatchNormalization)):
                    pass  # elementwise/order-preserving: Dense may follow
                else:
                    # A layer that may reorder or reshape features between
                    # the channels_first Flatten and the Dense would make
                    # the CHW→HWC dense-row permutation silently wrong —
                    # fail loudly instead (advisor round-2 finding).
                    raise UnsupportedKerasConfigurationException(
                        f"layer '{name}' ({cname}) between a "
                        "channels_first Flatten and its Dense consumer; "
                        "cannot prove the flattened feature order is "
                        "preserved")
            self.layers.append(mapped)
            self.keras_names.append(name)
        loss = self._loss()
        if loss and self.layers and \
                type(self.layers[-1]) in (DenseLayer,):
            last = self.layers[-1]
            # reference: KerasLoss appends an OutputLayer when a training
            # config is present (KerasModel.java getTrainingConfig path)
            self.layers[-1] = OutputLayer(
                name=last.name, n_in=last.n_in, n_out=last.n_out,
                activation=last.activation, loss_function=loss)

    def multi_layer_configuration(self) -> MultiLayerConfiguration:
        conf = NeuralNetConfiguration(seed=12345).list(*self.layers)
        if self.input_type is not None:
            conf.set_input_type(self.input_type)
        return conf


class KerasModel:
    """Functional Keras JSON → ComputationGraphConfiguration
    (reference: KerasModel.java)."""

    def __init__(self, model_config: Dict,
                 training_config: Optional[Dict] = None,
                 enforce_training_config: bool = False):
        if model_config.get("class_name") not in ("Model", "Functional"):
            raise InvalidKerasConfigurationException(
                f"Not a functional model: {model_config.get('class_name')}")
        cfg = model_config["config"]
        self.layer_configs = cfg["layers"]
        self.input_names = [n[0] for n in cfg["input_layers"]]
        self.output_names = [n[0] for n in cfg["output_layers"]]
        self.training_config = training_config
        if enforce_training_config and training_config is None:
            raise InvalidKerasConfigurationException(
                "enforce_training_config=True but the file has no "
                "'training_config' attribute (model was not compiled "
                "before saving)")
        self.dim_ordering = "tf"
        self.builder = NeuralNetConfiguration(seed=12345).graph_builder()
        self.keras_layer_names: List[str] = []
        self._skipped: Dict[str, str] = {}  # skipped layer → its input
        self.hwc_flatten_dense: set = set()
        self._build()

    @staticmethod
    def _inbound(lc: Dict) -> List[str]:
        nodes = lc.get("inbound_nodes", [])
        if not nodes:
            return []
        node = nodes[0]
        if isinstance(node, dict):  # keras 3 style {"args": ...}
            raise UnsupportedKerasConfigurationException(
                "Keras 3 saved-model JSON not supported; re-save in "
                "Keras 2 / TF-Keras HDF5 format")
        return [inb[0] for inb in node]

    def _resolve(self, name: str) -> str:
        while name in self._skipped:
            name = self._skipped[name]
        return name

    def _build(self) -> None:
        input_types = {}
        # dim ordering first, from any layer declaring it (input shapes
        # usually precede the conv layer carrying data_format)
        for lc in self.layer_configs:
            cfg = _cfg(lc)
            if "dim_ordering" in cfg or "data_format" in cfg:
                self.dim_ordering = _dim_ordering(cfg)
                break
        hwc_flattens: set = set()
        for lc in self.layer_configs:
            cname = lc["class_name"]
            cfg = _cfg(lc)
            name = lc.get("name") or cfg.get("name")
            raw_inbound = self._inbound(lc)
            inbound = [self._resolve(n) for n in raw_inbound]
            if cname == "InputLayer":
                shape = cfg.get("batch_input_shape")
                if shape is not None:
                    input_types[name] = _input_type_from_shape(
                        shape, self.dim_ordering)
                continue
            if cname in _MERGE_CLASSES:
                if any(n in hwc_flattens for n in raw_inbound):
                    # a merge after a channels_first Flatten recombines
                    # features — the CHW→HWC dense-row permutation for
                    # any downstream Dense becomes unprovable (same
                    # contract as the layer-between guard below)
                    raise UnsupportedKerasConfigurationException(
                        f"merge '{name}' ({cname}) consumes a "
                        "channels_first Flatten output; cannot prove "
                        "the flattened feature order for downstream "
                        "Dense layers")
                self.builder.add_vertex(name, map_merge_vertex(cname, lc),
                                        *inbound)
                continue
            mapped = map_keras_layer(cname, lc)
            if mapped is None:
                # structural layer: route around it
                self._skipped[name] = inbound[0]
                if cname == "Flatten" and ("data_format" in cfg
                                           or "dim_ordering" in cfg) \
                        and _dim_ordering(cfg) == "th":
                    hwc_flattens.add(name)
                continue
            hwc_upstream = any(n in hwc_flattens for n in raw_inbound)
            if isinstance(mapped, DenseLayer) and hwc_upstream:
                self.hwc_flatten_dense.add(name)
            elif isinstance(mapped, (DropoutLayer, ActivationLayer,
                                     BatchNormalization)) \
                    and hwc_upstream:
                # elementwise/order-preserving: downstream Dense is
                # still HWC-ordered
                hwc_flattens.add(name)
            elif hwc_upstream:
                # same contract as the Sequential builder: a layer that
                # may reorder features between the channels_first
                # Flatten and its Dense consumer makes the CHW→HWC
                # dense-row permutation unprovable — fail loudly
                raise UnsupportedKerasConfigurationException(
                    f"layer '{name}' ({cname}) between a channels_first "
                    "Flatten and its Dense consumer; cannot prove the "
                    "flattened feature order is preserved")
            self.builder.add_layer(name, mapped, *inbound)
            self.keras_layer_names.append(name)
        self.builder.add_inputs(*self.input_names)
        self.builder.set_input_types(**input_types)
        outputs = [self._resolve(n) for n in self.output_names]
        self.builder.set_outputs(*outputs)
        self._apply_training_config(outputs)

    def _loss_for(self, output_name: str) -> Optional[str]:
        """Loss for one output from training_config; Keras stores either a
        single loss or a dict keyed by output layer name (reference:
        KerasModel.java getTrainingConfig loss handling)."""
        if not self.training_config:
            return None
        loss = self.training_config.get("loss")
        if isinstance(loss, dict) and not {"class_name", "config"} <= \
                set(loss):
            loss = loss.get(output_name) or next(iter(loss.values()), None)
        if isinstance(loss, dict):  # serialized loss object
            loss = loss.get("config", {}).get("name", loss.get("class_name"))
            loss = str(loss).lower()
        return map_loss(loss) if loss else None

    def _apply_training_config(self, outputs: List[str]) -> None:
        """Turn each output Dense vertex into a loss-bearing OutputLayer so
        the imported graph can fit()/score() (the sequential path does the
        same; reference: KerasLoss appended output layers)."""
        for oname in outputs:
            loss = self._loss_for(oname)
            if loss is None:
                continue
            spec = self.builder._conf.vertices.get(oname)
            if spec is None:
                continue
            v = spec.vertex
            if type(v) is DenseLayer:
                spec.vertex = OutputLayer(
                    name=v.name, n_in=v.n_in, n_out=v.n_out,
                    activation=v.activation, loss_function=loss,
                    dropout=v.dropout, l1=v.l1, l2=v.l2,
                    learning_rate=v.learning_rate,
                    bias_learning_rate=v.bias_learning_rate)

    def computation_graph_configuration(self) -> ComputationGraphConfiguration:
        return self.builder.build()


# ---------------------------------------------------------------------------
# weight copying
# ---------------------------------------------------------------------------

def _weight_root(archive: Hdf5Archive):
    if archive.has_group("model_weights"):
        return archive.root["model_weights"]
    return archive.root


def _find_layer_group(root, keras_name: str):
    if keras_name in root:
        g = root[keras_name]
        # TF-Keras nests again: model_weights/dense_1/dense_1/{kernel,bias}
        return g
    return None


def keras_major_version(archive: Hdf5Archive) -> int:
    """1 or 2 from the file's keras_version attribute (reference:
    KerasModelUtils.determineKerasMajorVersion). Keras 2 always writes
    the attribute; a file without one is Keras-1-era."""
    v = archive.read_attribute_as_string("keras_version")
    if not v:
        return 1
    try:
        return int(str(v).split(".")[0])
    except ValueError:
        return 2


def _chw_to_hwc_rows(W: np.ndarray, h: int, w: int, c: int) -> np.ndarray:
    """Permute Dense rows from Keras channels-first flatten order (C,H,W)
    to this framework's NHWC flatten order (H,W,C). The reference is
    NCHW-native and permutes for 'tf' models instead (its
    CnnToFeedForwardPreProcessor carries the Keras dim ordering); here
    the mirror image applies to 'th'/channels_first models."""
    hh, ww, cc = np.meshgrid(np.arange(h), np.arange(w), np.arange(c),
                             indexing="ij")
    perm = (cc * h * w + hh * w + ww).reshape(-1)
    return W[perm]


def _dense_flatten_fix(net, layer_index: int, pname: str,
                       params: Dict[str, np.ndarray]) -> None:
    """Apply the th-flatten row permutation when this Dense consumes a
    flattened conv map (detected via the auto-inserted cnn→ff
    preprocessor: index-keyed on a MultiLayerConfiguration, vertex-name-
    keyed on a ComputationGraph)."""
    pre = getattr(net.conf, "input_preprocessors", {}).get(str(layer_index))
    if pre is None:
        pre = getattr(net, "_preprocessors", {}).get(pname)
    if isinstance(pre, CnnToFeedForwardPreProcessor) and "W" in params:
        h, w, c = pre.height, pre.width, pre.channels
        if params["W"].shape[0] == h * w * c:
            params["W"] = _chw_to_hwc_rows(params["W"], h, w, c)


def copy_weights_to_network(archive: Hdf5Archive, net,
                            layers: List[Layer], keras_names: List[str],
                            dim_ordering: str = "tf",
                            hwc_flatten_dense: frozenset = frozenset()
                            ) -> None:
    """Copy HDF5 weights into an initialized network by Keras layer name
    (reference: KerasModel.copyWeightsToModel / helpers.KerasModelUtils).
    ``hwc_flatten_dense``: dense layers Keras already reordered to HWC
    via Flatten(channels_first) — exempt from the th row permutation."""
    keras_major = keras_major_version(archive)
    root = _weight_root(archive)
    for i, (layer, kname) in enumerate(zip(layers, keras_names)):
        group = _find_layer_group(root, kname)
        if group is None:
            if layer.init_params.__func__ is Layer.init_params:
                continue  # parameterless layer
            raise InvalidKerasConfigurationException(
                f"No weights for layer '{kname}' in HDF5 file")
        kweights = archive.layer_weights(group)
        if not kweights:
            continue
        params, state = convert_weights(layer, kweights, dim_ordering,
                                        keras_major)
        if dim_ordering == "th" and isinstance(layer, DenseLayer) \
                and kname not in hwc_flatten_dense:
            _dense_flatten_fix(net, i, layer.name or kname, params)
        pname = layer.name or kname
        tgt = net.params.get(pname)
        if tgt is None:
            raise InvalidKerasConfigurationException(
                f"Network has no params entry '{pname}'")
        for k, v in params.items():
            if k in tgt and tuple(tgt[k].shape) != tuple(v.shape):
                raise InvalidKerasConfigurationException(
                    f"Shape mismatch for {pname}.{k}: model "
                    f"{tuple(tgt[k].shape)} vs file {tuple(v.shape)}")
            tgt[k] = jnp.asarray(v, dtype=net.dtype)
        if state:
            st = net.state.setdefault(pname, {})
            for k, v in state.items():
                st[k] = jnp.asarray(v, dtype=net.dtype)


# ---------------------------------------------------------------------------
# public entry points (reference: KerasModelImport.java:48-231)
# ---------------------------------------------------------------------------

def import_keras_sequential_model_and_weights(
        path: str, enforce_training_config: bool = False
        ) -> MultiLayerNetwork:
    """HDF5 with architecture + weights → MultiLayerNetwork
    (reference: KerasModelImport.importKerasSequentialModelAndWeights)."""
    with Hdf5Archive(path) as archive:
        mc = _model_config_from_archive(archive)
        tc = archive.read_attribute_as_json("training_config")
        km = KerasSequentialModel(mc, tc, enforce_training_config)
        conf = km.multi_layer_configuration()
        net = MultiLayerNetwork(conf).init()
        copy_weights_to_network(archive, net, net.layers, km.keras_names,
                                km.dim_ordering,
                                frozenset(km.hwc_flatten_dense))
        return net


def import_keras_model_and_weights(path: str,
                                   enforce_training_config: bool = False
                                   ) -> ComputationGraph:
    """HDF5 functional model + weights → ComputationGraph
    (reference: KerasModelImport.importKerasModelAndWeights:101)."""
    with Hdf5Archive(path) as archive:
        mc = _model_config_from_archive(archive)
        if mc.get("class_name") == "Sequential":
            raise InvalidKerasConfigurationException(
                "File holds a Sequential model; use "
                "import_keras_sequential_model_and_weights")
        tc = archive.read_attribute_as_json("training_config")
        km = KerasModel(mc, tc, enforce_training_config)
        conf = km.computation_graph_configuration()
        net = ComputationGraph(conf).init()
        layers = [conf.vertices[n].vertex for n in km.keras_layer_names]
        copy_weights_to_network(archive, net, layers, km.keras_layer_names,
                                km.dim_ordering,
                                frozenset(km.hwc_flatten_dense))
        return net


def import_keras_model_auto(path: str,
                            enforce_training_config: bool = False):
    """Dispatch on the file's model_config class: Sequential →
    MultiLayerNetwork, functional → ComputationGraph (the reference's
    ModelGuesser-style convenience on top of KerasModelImport)."""
    with Hdf5Archive(path) as archive:
        mc = _model_config_from_archive(archive)
    if mc.get("class_name") == "Sequential":
        return import_keras_sequential_model_and_weights(
            path, enforce_training_config)
    return import_keras_model_and_weights(path, enforce_training_config)


def import_keras_model_configuration(json_path_or_str: str):
    """Architecture-only JSON → configuration (reference:
    KerasModelImport.importKerasModelConfiguration / Sequential variant)."""
    s = json_path_or_str
    if not s.lstrip().startswith("{"):
        with open(s) as f:
            s = f.read()
    mc = json.loads(s)
    if mc.get("class_name") == "Sequential":
        return KerasSequentialModel(mc).multi_layer_configuration()
    return KerasModel(mc).computation_graph_configuration()


def import_keras_model_and_weights_separate(json_path: str, h5_path: str):
    """JSON architecture + weights-only HDF5 (reference:
    KerasModelImport.importKerasModelAndWeights(json, h5) variants)."""
    with open(json_path) as f:
        mc = json.loads(f.read())
    with Hdf5Archive(h5_path) as archive:
        if mc.get("class_name") == "Sequential":
            km = KerasSequentialModel(mc)
            net = MultiLayerNetwork(km.multi_layer_configuration()).init()
            copy_weights_to_network(archive, net, net.layers,
                                    km.keras_names, km.dim_ordering,
                                    frozenset(km.hwc_flatten_dense))
            return net
        kg = KerasModel(mc)
        conf = kg.computation_graph_configuration()
        netg = ComputationGraph(conf).init()
        layers = [conf.vertices[n].vertex for n in kg.keras_layer_names]
        copy_weights_to_network(archive, netg, layers, kg.keras_layer_names,
                                kg.dim_ordering,
                                frozenset(kg.hwc_flatten_dense))
        return netg
