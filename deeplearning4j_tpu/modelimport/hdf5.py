"""HDF5 archive reader for Keras model files.

Parity with the reference's `Hdf5Archive`
(reference: deeplearning4j-modelimport/.../Hdf5Archive.java:22-35), which
binds libhdf5 through JavaCPP JNI. Here the native half is h5py's C
extension over libhdf5 — same library, same role, without a bespoke JNI
shim. The API mirrors the reference's: read JSON attributes
(`model_config`, `training_config`), walk groups, read datasets.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

try:
    import h5py
    HAVE_H5PY = True
except ImportError:  # pragma: no cover - baked into the image
    HAVE_H5PY = False


def _to_str(v) -> str:
    if isinstance(v, bytes):
        return v.decode("utf-8")
    if isinstance(v, np.ndarray) and v.dtype.kind == "S":
        return v.tobytes().decode("utf-8")
    return str(v)


class Hdf5Archive:
    """Read-only view of a Keras .h5 file (reference: Hdf5Archive.java)."""

    def __init__(self, path: str):
        if not HAVE_H5PY:
            raise ImportError("h5py is required for Keras HDF5 import")
        self._f = h5py.File(path, "r")

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "Hdf5Archive":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- attributes --------------------------------------------------------
    def read_attribute_as_json(self, name: str,
                               *group_path: str) -> Optional[Dict]:
        """Reference: Hdf5Archive.readAttributeAsJson."""
        g = self._group(*group_path)
        if g is None or name not in g.attrs:
            return None
        return json.loads(_to_str(g.attrs[name]))

    def read_attribute_as_string(self, name: str,
                                 *group_path: str) -> Optional[str]:
        g = self._group(*group_path)
        if g is None or name not in g.attrs:
            return None
        return _to_str(g.attrs[name])

    def read_attribute_as_string_list(self, name: str,
                                      *group_path: str) -> List[str]:
        g = self._group(*group_path)
        if g is None or name not in g.attrs:
            return []
        return [_to_str(v) for v in g.attrs[name]]

    # -- groups / datasets -------------------------------------------------
    def _group(self, *path: str):
        g: Any = self._f
        for p in path:
            if p not in g:
                return None
            g = g[p]
        return g

    def has_group(self, *path: str) -> bool:
        return self._group(*path) is not None

    def groups(self, *path: str) -> List[str]:
        g = self._group(*path)
        if g is None:
            return []
        return [k for k in g.keys() if isinstance(g[k], h5py.Group)]

    def datasets(self, *path: str) -> List[str]:
        g = self._group(*path)
        if g is None:
            return []
        return [k for k in g.keys() if isinstance(g[k], h5py.Dataset)]

    def read_dataset(self, *path: str) -> np.ndarray:
        """Read a dataset by path; the last component may itself contain
        '/' separators (Keras weight names like 'dense_1/kernel:0')."""
        g: Any = self._f
        for p in path:
            g = g[p]
        return np.asarray(g)

    def layer_weights(self, layer_group) -> Dict[str, np.ndarray]:
        """All datasets under a layer group keyed by their Keras weight
        name (attr `weight_names`), e.g. {'dense_1/kernel:0': array}."""
        out: Dict[str, np.ndarray] = {}
        names = [_to_str(n) for n in layer_group.attrs.get("weight_names",
                                                           [])]
        if names:
            for n in names:
                out[n] = np.asarray(layer_group[n])
            return out
        # Keras 1 files have no weight_names on some groups: walk datasets
        def visit(name, obj):
            if isinstance(obj, h5py.Dataset):
                out[name] = np.asarray(obj)
        layer_group.visititems(visit)
        return out

    @property
    def root(self):
        return self._f
