"""Keras model import (reference: deeplearning4j-modelimport module)."""
from deeplearning4j_tpu.modelimport.keras import (
    import_keras_model_and_weights,
    import_keras_sequential_model_and_weights,
    import_keras_model_configuration,
    import_keras_model_and_weights_separate,
    import_keras_model_auto,
    KerasModel, KerasSequentialModel,
    InvalidKerasConfigurationException,
    UnsupportedKerasConfigurationException,
)
from deeplearning4j_tpu.modelimport.hdf5 import Hdf5Archive
from deeplearning4j_tpu.modelimport.labels import (ImageNetLabels,
                                                   decode_predictions,
                                                   get_predicted_classes,
                                                   top_k)
from deeplearning4j_tpu.modelimport.trained_models import (vgg16,
                                                           vgg16_preprocess,
                                                           load_vgg16,
                                                           resnet50)

__all__ = [
    "import_keras_model_and_weights",
    "import_keras_sequential_model_and_weights",
    "import_keras_model_configuration",
    "import_keras_model_and_weights_separate",
    "import_keras_model_auto",
    "KerasModel", "KerasSequentialModel", "Hdf5Archive",
    "InvalidKerasConfigurationException",
    "UnsupportedKerasConfigurationException",
    "vgg16", "vgg16_preprocess", "load_vgg16", "resnet50",
    "ImageNetLabels", "decode_predictions", "get_predicted_classes",
    "top_k",
]
