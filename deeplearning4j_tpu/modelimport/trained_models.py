"""Pretrained-model helpers: VGG16 architecture + ImageNet preprocessing.

Parity with the reference's trained-models utilities (reference:
deeplearning4j-modelimport/.../trainedmodels/TrainedModels.java:16-18,
TrainedModelHelper.java, Utils/ImageNetLabels.java). The reference
downloads DL4J-converted Keras VGG16 weights from hard-coded URLs
(TrainedModels.java:38-41); here the architecture builders are always
available and `load_vgg16_weights(path)` imports a locally provided Keras
HDF5 file (zero-egress environments cannot download).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.nn.conf.configuration import (
    NeuralNetConfiguration, MultiLayerConfiguration)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
from deeplearning4j_tpu.nn.layers.convolution import (ConvolutionLayer,
                                                      SubsamplingLayer)
from deeplearning4j_tpu.nn.layers.output import OutputLayer

# ImageNet channel means used by VGG preprocessing (BGR order in the
# original caffe weights; reference: TrainedModels.VGG16 getPreProcessor)
VGG_MEAN_RGB = np.array([123.68, 116.779, 103.939], dtype=np.float32)


def _conv(n_out: int, name: str) -> ConvolutionLayer:
    return ConvolutionLayer(name=name, n_out=n_out, kernel_size=(3, 3),
                            stride=(1, 1), convolution_mode="same",
                            activation="relu")


def _pool(name: str) -> SubsamplingLayer:
    return SubsamplingLayer(name=name, pooling_type="max",
                            kernel_size=(2, 2), stride=(2, 2))


def vgg16(num_classes: int = 1000, include_top: bool = True,
          height: int = 224, width: int = 224, channels: int = 3,
          learning_rate: float = 0.01, seed: int = 12345,
          dtype: str = "bfloat16") -> MultiLayerConfiguration:
    """VGG16 (Simonyan & Zisserman 2014) as a sequential configuration —
    the reference's canonical Keras-import benchmark model
    (BASELINE.md: "ComputationGraph VGG16 via Keras import"). NHWC
    activations; convs are 3x3 'same', bf16 by default for the MXU."""
    blocks = [
        (2, 64), (2, 128), (3, 256), (3, 512), (3, 512),
    ]
    layers = []
    for bi, (reps, ch) in enumerate(blocks, start=1):
        for ri in range(1, reps + 1):
            layers.append(_conv(ch, f"block{bi}_conv{ri}"))
        layers.append(_pool(f"block{bi}_pool"))
    if include_top:
        layers.append(DenseLayer(name="fc1", n_out=4096, activation="relu"))
        layers.append(DenseLayer(name="fc2", n_out=4096, activation="relu"))
        layers.append(OutputLayer(name="predictions", n_out=num_classes,
                                  activation="softmax",
                                  loss_function="mcxent"))
    conf = NeuralNetConfiguration(
        seed=seed, learning_rate=learning_rate, updater="nesterovs",
        weight_init="relu", dtype=dtype,
    ).list(*layers)
    conf.set_input_type(InputType.convolutional(height, width, channels))
    return conf


def vgg16_preprocess(images: np.ndarray) -> np.ndarray:
    """Subtract ImageNet channel means from NHWC uint8/float images
    (reference: TrainedModels.VGG16 VGG16ImagePreProcessor)."""
    return np.asarray(images, np.float32) - VGG_MEAN_RGB


def load_vgg16(h5_path: str):
    """Import VGG16 weights from a local Keras HDF5 file
    (reference flow: TrainedModelHelper → KerasModelImport)."""
    from deeplearning4j_tpu.modelimport.keras import \
        import_keras_model_auto
    return import_keras_model_auto(h5_path)


def resnet50(num_classes: int = 1000, height: int = 224, width: int = 224,
             channels: int = 3, learning_rate: float = 0.01,
             seed: int = 12345, dtype: str = "bfloat16"):
    """ResNet-50 (He et al. 2015) as a ComputationGraph configuration —
    the reference's other canonical Keras-import benchmark model
    (BASELINE.md: "ComputationGraph VGG16/ResNet-50 via Keras import";
    residual adds map to ElementWiseVertex, reference:
    nn/conf/graph/ElementWiseVertex.java). NHWC activations, bottleneck
    blocks [3,4,6,3], batch norm after every conv, bf16 by default for
    the MXU."""
    from deeplearning4j_tpu.nn.layers.misc import (ActivationLayer,
                                                   GlobalPoolingLayer)
    from deeplearning4j_tpu.nn.layers.normalization import (
        BatchNormalization)
    from deeplearning4j_tpu.nn.graph.vertices import ElementWiseVertex

    b = (NeuralNetConfiguration(seed=seed, learning_rate=learning_rate,
                                updater="nesterovs", momentum=0.9,
                                weight_init="relu", dtype=dtype,
                                activation="identity")
         .graph_builder()
         .add_inputs("input")
         .set_input_types(input=InputType.convolutional(height, width,
                                                        channels)))

    def conv(name, n_out, k, stride, src):
        b.add_layer(name, ConvolutionLayer(
            n_out=n_out, kernel_size=(k, k), stride=(stride, stride),
            convolution_mode="same", activation="identity"), src)
        return name

    def bn(name, src, relu):
        b.add_layer(name, BatchNormalization(
            activation="relu" if relu else "identity"), src)
        return name

    # stem: 7x7/2 conv + BN/relu + 3x3/2 max pool
    prev = bn("bn_conv1", conv("conv1", 64, 7, 2, "input"), relu=True)
    b.add_layer("pool1", SubsamplingLayer(
        pooling_type="max", kernel_size=(3, 3), stride=(2, 2),
        convolution_mode="same"), prev)
    prev = "pool1"

    stages = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2),
              (3, 512, 2048, 2)]
    for si, (reps, mid, out_ch, first_stride) in enumerate(stages,
                                                           start=2):
        for ri in range(reps):
            n = f"s{si}b{ri + 1}"
            stride = first_stride if ri == 0 else 1
            x = bn(f"{n}_bn1", conv(f"{n}_c1", mid, 1, stride, prev),
                   relu=True)
            x = bn(f"{n}_bn2", conv(f"{n}_c2", mid, 3, 1, x), relu=True)
            x = bn(f"{n}_bn3", conv(f"{n}_c3", out_ch, 1, 1, x),
                   relu=False)
            if ri == 0:  # projection shortcut on the stage's first block
                shortcut = bn(f"{n}_bnp",
                              conv(f"{n}_proj", out_ch, 1, stride, prev),
                              relu=False)
            else:
                shortcut = prev
            b.add_vertex(f"{n}_add", ElementWiseVertex(op="add"), x,
                         shortcut)
            b.add_layer(f"{n}_out", ActivationLayer(activation="relu"),
                        f"{n}_add")
            prev = f"{n}_out"

    b.add_layer("avg_pool", GlobalPoolingLayer(pooling_type="avg"), prev)
    b.add_layer("fc1000", OutputLayer(n_out=num_classes,
                                      activation="softmax",
                                      loss_function="mcxent"), "avg_pool")
    b.set_outputs("fc1000")
    return b.build()
