"""Score calculators for early stopping.

Parity with the reference (reference:
deeplearning4j-nn/.../earlystopping/scorecalc/DataSetLossCalculator.java,
DataSetLossCalculatorCG.java): average model loss over a held-out iterator.
"""
from __future__ import annotations

from deeplearning4j_tpu.nn.multilayer import _unpack_batch


class ScoreCalculator:
    def calculate_score(self, net) -> float:
        raise NotImplementedError


class DataSetLossCalculator(ScoreCalculator):
    """Mean loss over an evaluation iterator, weighted by batch size."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, net) -> float:
        total = 0.0
        n = 0
        for batch in self.iterator:
            feats, labels, fmask, lmask = _unpack_batch(batch)
            batch_n = int(feats.shape[0])
            mask = lmask if lmask is not None else fmask
            total += net.score(feats, labels, mask) * batch_n
            n += batch_n
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        return total / n if (self.average and n) else total
