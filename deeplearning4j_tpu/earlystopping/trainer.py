"""Early-stopping training loop.

Parity with the reference (reference:
deeplearning4j-nn/.../earlystopping/trainer/BaseEarlyStoppingTrainer.java,
EarlyStoppingTrainer.java, EarlyStoppingGraphTrainer.java): per-epoch fit
over the training iterator with iteration-condition checks per minibatch,
score calculation every N epochs, best-model tracking via the saver, and a
structured result.
"""
from __future__ import annotations

import logging
import math
from typing import Dict

from deeplearning4j_tpu.earlystopping.config import (
    EarlyStoppingConfiguration, EarlyStoppingResult)
from deeplearning4j_tpu.earlystopping.termination import \
    MaxEpochsTerminationCondition
from deeplearning4j_tpu.nn.multilayer import _unpack_batch

log = logging.getLogger("deeplearning4j_tpu")


class EarlyStoppingListener:
    """Callbacks around the early-stopping loop (reference:
    earlystopping/listener/EarlyStoppingListener.java: onStart,
    onEpoch, onCompletion)."""

    def on_start(self, config, net) -> None:
        pass

    def on_epoch(self, epoch: int, score: float, config, net) -> None:
        pass

    def on_completion(self, result) -> None:
        pass


class BaseEarlyStoppingTrainer:

    def __init__(self, config: EarlyStoppingConfiguration, net, train_iter,
                 listener: "EarlyStoppingListener" = None):
        self.config = config
        self.net = net
        self.train_iter = train_iter
        self.listener = listener

    def set_listener(self, listener: "EarlyStoppingListener") -> None:
        self.listener = listener

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        if self.listener is not None:
            self.listener.on_start(cfg, self.net)
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        for c in cfg.iteration_termination_conditions:
            c.initialize()
        score_vs_epoch: Dict[int, float] = {}
        best_score = math.inf
        best_epoch = -1
        epoch = 0
        reason, details = "Error", "loop never ran"
        while True:
            stop_iter = None
            for batch in self.train_iter:
                self._fit_batch(batch)
                last = float(self.net.score_value)
                for c in cfg.iteration_termination_conditions:
                    if c.terminate(last):
                        stop_iter = c
                        break
                if stop_iter is not None:
                    break
            if hasattr(self.train_iter, "reset"):
                self.train_iter.reset()
            if stop_iter is not None:
                reason = "IterationTerminationCondition"
                details = repr(stop_iter)
                break

            # On epochs where the calculator is skipped, do NOT fall back to
            # the last train-minibatch loss: mixing train-batch and
            # validation scores would corrupt best-model selection and feed
            # the epoch conditions an inconsistent metric.
            evaluated = (cfg.score_calculator is None
                         or epoch % cfg.evaluate_every_n_epochs == 0)
            if evaluated:
                if cfg.score_calculator is not None:
                    score = float(
                        cfg.score_calculator.calculate_score(self.net))
                else:
                    score = float(self.net.score_value)
                score_vs_epoch[epoch] = score
                if self.listener is not None:
                    self.listener.on_epoch(epoch, score, cfg, self.net)
                if score < best_score:
                    best_score = score
                    best_epoch = epoch
                    cfg.model_saver.save_best_model(self.net, score)
                    log.info("early stopping: new best score %.6f at "
                             "epoch %d", score, epoch)
            if cfg.save_last_model:
                cfg.model_saver.save_latest_model(
                    self.net, float(self.net.score_value))

            stop_epoch = None
            for c in cfg.epoch_termination_conditions:
                # score-based conditions only see real (evaluated) scores;
                # score-free ones (requires_score=False) fire on any epoch
                if not evaluated and getattr(c, "requires_score", True):
                    continue
                if c.terminate(epoch, score if evaluated else math.inf):
                    stop_epoch = c
                    break
            if stop_epoch is not None:
                reason = "EpochTerminationCondition"
                details = repr(stop_epoch)
                break
            epoch += 1

        best_model = cfg.model_saver.get_best_model()
        if best_model is None:
            best_model = self.net
        result = EarlyStoppingResult(
            termination_reason=reason, termination_details=details,
            score_vs_epoch=score_vs_epoch, best_model_epoch=best_epoch,
            best_model_score=best_score, total_epochs=epoch + 1,
            best_model=best_model)
        if self.listener is not None:
            self.listener.on_completion(result)
        return result

    def _fit_batch(self, batch) -> None:
        feats, labels, fmask, lmask = _unpack_batch(batch)
        self.net.fit(feats, labels,
                     lmask if lmask is not None else fmask)


class EarlyStoppingTrainer(BaseEarlyStoppingTrainer):
    """For MultiLayerNetwork (reference: EarlyStoppingTrainer.java)."""


class EarlyStoppingGraphTrainer(BaseEarlyStoppingTrainer):
    """For ComputationGraph (reference: EarlyStoppingGraphTrainer.java)."""
