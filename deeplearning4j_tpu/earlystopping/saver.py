"""Model savers for early stopping.

Parity with the reference (reference:
deeplearning4j-nn/.../earlystopping/saver/{InMemoryModelSaver,
LocalFileModelSaver,LocalFileGraphSaver}.java).
"""
from __future__ import annotations

import os
from typing import Any, Optional

from deeplearning4j_tpu.util.model_serializer import (
    model_type_of, restore_computation_graph, restore_multi_layer_network,
    write_model)


class EarlyStoppingModelSaver:
    def save_best_model(self, net, score: float) -> None:
        raise NotImplementedError

    def save_latest_model(self, net, score: float) -> None:
        raise NotImplementedError

    def get_best_model(self):
        raise NotImplementedError

    def get_latest_model(self):
        raise NotImplementedError


class InMemoryModelSaver(EarlyStoppingModelSaver):
    def __init__(self):
        self._best: Optional[Any] = None
        self._latest: Optional[Any] = None

    def save_best_model(self, net, score: float) -> None:
        self._best = net.clone()

    def save_latest_model(self, net, score: float) -> None:
        self._latest = net.clone()

    def get_best_model(self):
        return self._best

    def get_latest_model(self):
        return self._latest


class LocalFileModelSaver(EarlyStoppingModelSaver):
    """Persist best/latest model zips under a directory (reference:
    LocalFileModelSaver: bestModel.bin / latestModel.bin)."""

    BEST = "bestModel.zip"
    LATEST = "latestModel.zip"

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _restore(self, path: str):
        if not os.path.exists(path):
            return None
        if model_type_of(path) == "ComputationGraph":
            return restore_computation_graph(path)
        return restore_multi_layer_network(path)

    def save_best_model(self, net, score: float) -> None:
        write_model(net, os.path.join(self.directory, self.BEST))

    def save_latest_model(self, net, score: float) -> None:
        write_model(net, os.path.join(self.directory, self.LATEST))

    def get_best_model(self):
        return self._restore(os.path.join(self.directory, self.BEST))

    def get_latest_model(self):
        return self._restore(os.path.join(self.directory, self.LATEST))
