"""Early-stopping termination conditions.

Parity with the reference (reference:
deeplearning4j-nn/.../earlystopping/termination/ — MaxEpochsTermination-
Condition, MaxTimeIterationTerminationCondition, ScoreImprovementEpoch-
TerminationCondition, BestScoreEpochTerminationCondition, MaxScoreIteration-
TerminationCondition, InvalidScoreIterationTerminationCondition).

Epoch conditions are consulted after each epoch's score calculation;
iteration conditions after every minibatch.
"""
from __future__ import annotations

import math
import time


class EpochTerminationCondition:
    # conditions that only consult the epoch counter / wall clock set this
    # False so the trainer runs them even on epochs where no score was
    # computed (evaluate_every_n_epochs > 1)
    requires_score: bool = True

    def initialize(self) -> None:
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self) -> None:
        pass

    def terminate(self, last_score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    requires_score = False

    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch: int, score: float) -> bool:
        return epoch + 1 >= self.max_epochs

    def __repr__(self):
        return f"MaxEpochsTerminationCondition({self.max_epochs})"


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs with no (sufficient) score improvement."""

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self.best_score = math.inf
        self.epochs_without = 0

    def initialize(self) -> None:
        self.best_score = math.inf
        self.epochs_without = 0

    def terminate(self, epoch: int, score: float) -> bool:
        if self.best_score - score > self.min_improvement:
            self.best_score = score
            self.epochs_without = 0
            return False
        self.epochs_without += 1
        return self.epochs_without >= self.patience

    def __repr__(self):
        return (f"ScoreImprovementEpochTerminationCondition("
                f"{self.patience}, {self.min_improvement})")


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once the score reaches a target value."""

    def __init__(self, best_expected_score: float):
        self.best_expected_score = best_expected_score

    def terminate(self, epoch: int, score: float) -> bool:
        return score <= self.best_expected_score

    def __repr__(self):
        return (f"BestScoreEpochTerminationCondition("
                f"{self.best_expected_score})")


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_time_seconds: float):
        self.max_time_seconds = max_time_seconds
        self._start = None

    def initialize(self) -> None:
        self._start = time.monotonic()

    def terminate(self, last_score: float) -> bool:
        if self._start is None:
            self.initialize()
        return time.monotonic() - self._start >= self.max_time_seconds

    def __repr__(self):
        return (f"MaxTimeIterationTerminationCondition("
                f"{self.max_time_seconds}s)")


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Terminate if the score explodes above a ceiling."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, last_score: float) -> bool:
        return last_score > self.max_score

    def __repr__(self):
        return f"MaxScoreIterationTerminationCondition({self.max_score})"


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    """Terminate on NaN/Inf score."""

    def terminate(self, last_score: float) -> bool:
        return math.isnan(last_score) or math.isinf(last_score)

    def __repr__(self):
        return "InvalidScoreIterationTerminationCondition()"
