from deeplearning4j_tpu.earlystopping.config import (  # noqa: F401
    EarlyStoppingConfiguration,
    EarlyStoppingResult,
)
from deeplearning4j_tpu.earlystopping.saver import (  # noqa: F401
    InMemoryModelSaver,
    LocalFileModelSaver,
)
from deeplearning4j_tpu.earlystopping.scorecalc import (  # noqa: F401
    DataSetLossCalculator,
)
from deeplearning4j_tpu.earlystopping.termination import (  # noqa: F401
    BestScoreEpochTerminationCondition,
    InvalidScoreIterationTerminationCondition,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.earlystopping.trainer import (  # noqa: F401
    EarlyStoppingGraphTrainer,
    EarlyStoppingTrainer,
)
