"""Early-stopping configuration + result.

Parity with the reference (reference:
deeplearning4j-nn/.../earlystopping/EarlyStoppingConfiguration.java,
EarlyStoppingResult.java).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.earlystopping.saver import (EarlyStoppingModelSaver,
                                                    InMemoryModelSaver)


@dataclass
class EarlyStoppingConfiguration:
    epoch_termination_conditions: List[Any] = field(default_factory=list)
    iteration_termination_conditions: List[Any] = field(default_factory=list)
    score_calculator: Optional[Any] = None
    model_saver: EarlyStoppingModelSaver = field(
        default_factory=InMemoryModelSaver)
    save_last_model: bool = False
    evaluate_every_n_epochs: int = 1


@dataclass
class EarlyStoppingResult:
    termination_reason: str  # 'EpochTerminationCondition' |
    #                          'IterationTerminationCondition' | 'Error'
    termination_details: str
    score_vs_epoch: Dict[int, float]
    best_model_epoch: int
    best_model_score: float
    total_epochs: int
    best_model: Any
