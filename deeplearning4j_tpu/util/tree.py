"""Parse tree for recursive autoencoder / recursive neural tensor nets.

Capability parity with the reference's recursive-autoencoder tree
(reference: deeplearning4j-nn/.../nn/layers/feedforward/autoencoder/
recursive/Tree.java): labeled n-ary tree over token spans with per-node
vectors/predictions/error, leaf/preterminal queries, depth, ancestor
lookup, yield, and deep clone. Vectors are jax/numpy arrays instead of
INDArrays; the structure itself is host-side (tree recursion is not an
XLA-friendly shape, so batching over trees happens at a higher level).
"""
from __future__ import annotations

from typing import Any, List, Optional


class Tree:
    def __init__(self, tokens: Optional[List[str]] = None,
                 parent: Optional["Tree"] = None):
        self.parent = parent
        self.tokens: List[str] = list(tokens or [])
        self.children_: List["Tree"] = []
        self.vector: Any = None
        self.prediction: Any = None
        self.error_value: float = 0.0
        self.head_word: Optional[str] = None
        self.value: Optional[str] = None
        self.label_: Optional[str] = None
        self.type_: Optional[str] = None
        self.gold_label: int = 0
        self.tags: List[str] = []
        self.parse: Optional[str] = None
        self.begin: int = 0
        self.end: int = 0

    # -- structure ---------------------------------------------------------
    def children(self) -> List["Tree"]:
        return self.children_

    def add_child(self, child: "Tree") -> "Tree":
        child.parent = self
        self.children_.append(child)
        return child

    def is_leaf(self) -> bool:
        return not self.children_

    def is_pre_terminal(self) -> bool:
        """One level above the leaves (POS-tag level in a parse tree)."""
        return bool(self.children_) and all(c.is_leaf()
                                            for c in self.children_)

    def first_child(self) -> Optional["Tree"]:
        return self.children_[0] if self.children_ else None

    def last_child(self) -> Optional["Tree"]:
        return self.children_[-1] if self.children_ else None

    def depth(self) -> int:
        """Height of the subtree below this node (leaf = 0)."""
        if self.is_leaf():
            return 0
        return 1 + max(c.depth() for c in self.children_)

    def distance_to(self, node: "Tree") -> int:
        """Depth of ``node`` below this subtree root (-1 if absent)."""
        if node is self:
            return 0
        for c in self.children_:
            d = c.distance_to(node)
            if d >= 0:
                return d + 1
        return -1

    def ancestor(self, height: int) -> Optional["Tree"]:
        """The ancestor ``height`` levels up (0 = self)."""
        node: Optional[Tree] = self
        for _ in range(height):
            if node is None:
                return None
            node = node.parent
        return node

    def get_leaves(self) -> List["Tree"]:
        if self.is_leaf():
            return [self]
        out: List[Tree] = []
        for c in self.children_:
            out.extend(c.get_leaves())
        return out

    def yield_(self) -> List[str]:
        """All tokens under this node, left to right."""
        if self.is_leaf():
            return list(self.tokens)
        out: List[str] = []
        for c in self.children_:
            out.extend(c.yield_())
        return out

    # -- labels / error ----------------------------------------------------
    def label(self) -> Optional[str]:
        return self.label_

    def set_label(self, label: str) -> None:
        self.label_ = label

    def error_sum(self) -> float:
        """Total error over this subtree."""
        return self.error_value + sum(c.error_sum()
                                      for c in self.children_)

    def clone(self) -> "Tree":
        t = Tree(self.tokens)
        for name in ("vector", "prediction", "error_value", "head_word",
                     "value", "label_", "type_", "gold_label", "parse",
                     "begin", "end"):
            setattr(t, name, getattr(self, name))
        t.tags = list(self.tags)
        for c in self.children_:
            t.add_child(c.clone())
        return t

    def __repr__(self) -> str:
        if self.is_leaf():
            return f"Tree(leaf {self.tokens or self.value!r})"
        return (f"Tree({self.label_ or self.value!r}, "
                f"{len(self.children_)} children)")
