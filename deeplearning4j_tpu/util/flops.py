"""FLOPs accounting and MFU (model-FLOPs utilization) reporting.

The reference measures throughput only in examples/sec
(reference: optimize/listeners/PerformanceListener.java — examples/sec,
batches/sec); it has no FLOPs accounting because eager per-op dispatch
has no single program to account for. Here every training run IS one XLA
program, so the compiler's own cost model gives an un-gameable FLOP
count for exactly the computation executed: MFU = (program FLOPs /
wall-clock) / chip peak. This is the honest cross-round perf metric —
unlike examples/sec it cannot be inflated by shrinking the model, and
unlike vs-an-estimate ratios it needs no reference measurement.

Note XLA counts every executed FLOP, including rematerialized
(jax.checkpoint) recompute — so for remat'd programs this reports
hardware-FLOPs utilization (HFU), an upper bound on the work actually
"in the model". Callers that want textbook MFU for a remat'd model
should pass analytic model FLOPs instead.

CAVEAT (verified on jax 0.9 / TPU v5e): XLA's cost model counts a
`lax.scan` body ONCE, independent of trip count. For scanned multi-step
programs, cost a single-step program and multiply by the step count
(bench.py does exactly this).
"""
from __future__ import annotations

import jax

# Peak dense matmul throughput per chip, FLOP/s, by jax device_kind
# prefix. bf16 MXU numbers from public TPU specs (v5e: 197 TFLOP/s bf16;
# v4: 275; v5p: 459; v6e "Trillium": 918).
_PEAK_BF16 = {
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v5": 459e12,       # v5p reports "TPU v5"; v5e reports "v5 lite"
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# Peak HBM bandwidth per chip, bytes/s, from the same public specs
# (v5e: 819 GB/s; v4: 1228; v5p: 2765; v6e: 1640) — the denominator of
# the roofline ridge point (observability/profiling.py: a program
# whose arithmetic intensity sits left of peak_flops/peak_bw is
# memory-bound on that chip).
_PEAK_HBM_BPS = {
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v4": 1228e9,
    "TPU v5": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
}


def _peak_lookup(table: dict, device) -> float | None:
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    # longest-prefix match so "TPU v5 lite" beats "TPU v5"
    best = None
    for k, v in table.items():
        if kind.startswith(k) and (best is None or len(k) > best[0]):
            best = (len(k), v)
    return best[1] if best else None


def chip_peak_flops(device: "jax.Device | None" = None) -> float | None:
    """Peak bf16 FLOP/s for one chip, or None when unknown (CPU etc.)."""
    return _peak_lookup(_PEAK_BF16, device)


def chip_peak_bytes_per_s(device: "jax.Device | None" = None
                          ) -> float | None:
    """Peak HBM bytes/s for one chip, or None when unknown (CPU
    etc.) — the roofline ridge point's denominator."""
    return _peak_lookup(_PEAK_HBM_BPS, device)


def cost_analysis(jitted_fn, *args, **kwargs) -> dict:
    """XLA cost analysis ({'flops': ..., 'bytes accessed': ...}) for the
    program ``jitted_fn(*args)`` would run. Lower+compile only — nothing
    executes, so donated buffers are untouched."""
    compiled = jitted_fn.lower(*args, **kwargs).compile()
    try:
        ca = compiled.cost_analysis()
    except Exception:  # some PJRT plugins raise UNIMPLEMENTED here
        return {}
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


def program_flops(jitted_fn, *args, **kwargs) -> float | None:
    """Total FLOPs XLA accounts to one execution of the program, or None
    when the backend offers no estimate."""
    flops = cost_analysis(jitted_fn, *args, **kwargs).get("flops")
    return float(flops) if flops and flops > 0 else None


def mfu(flops: float | None, seconds: float,
        device: "jax.Device | None" = None) -> float | None:
    """Fraction of one chip's peak bf16 throughput achieved: (flops /
    seconds) / peak. None when either side is unknown."""
    peak = chip_peak_flops(device)
    if flops is None or peak is None or seconds <= 0:
        return None
    return flops / seconds / peak
