from deeplearning4j_tpu.util.model_serializer import (  # noqa: F401
    ModelSerializer,
    restore_computation_graph,
    restore_multi_layer_network,
    write_model,
)
from deeplearning4j_tpu.util.model_guesser import ModelGuesser  # noqa: F401
