"""Counting / pairing utility classes.

Parity with the reference's vendored Berkeley-NLP utilities (reference:
deeplearning4j-nn/.../berkeley/ — Pair.java, Triple.java, Counter.java,
CounterMap.java, PriorityQueue.java; used throughout the NLP and
clustering code for counting and best-first search). These are thin,
idiomatic-Python equivalents: `Counter` adds the reference's
argmax/normalize/scale operations missing from `collections.Counter`,
and `PriorityQueue` is a max-heap with the reference's
`next`/`peek`/`getPriority` surface.
"""
from __future__ import annotations

import heapq
import itertools
import numpy as np
from collections import defaultdict
from typing import Dict, Generic, Iterator, List, Optional, Tuple, TypeVar

K = TypeVar("K")
V = TypeVar("V")
T = TypeVar("T")


class Pair(Generic[K, V]):
    """Ordered pair (`berkeley/Pair.java`)."""

    __slots__ = ("first", "second")

    def __init__(self, first: K, second: V):
        self.first = first
        self.second = second

    def reverse(self) -> "Pair[V, K]":
        return Pair(self.second, self.first)

    def __iter__(self):
        return iter((self.first, self.second))

    def __eq__(self, other):
        return (isinstance(other, Pair) and self.first == other.first
                and self.second == other.second)

    def __hash__(self):
        return hash((self.first, self.second))

    def __repr__(self):
        return f"({self.first}, {self.second})"


class Triple(Generic[K, V, T]):
    """Ordered triple (`berkeley/Triple.java`)."""

    __slots__ = ("first", "second", "third")

    def __init__(self, first: K, second: V, third: T):
        self.first = first
        self.second = second
        self.third = third

    def __iter__(self):
        return iter((self.first, self.second, self.third))

    def __eq__(self, other):
        return (isinstance(other, Triple) and tuple(self) == tuple(other))

    def __hash__(self):
        return hash(tuple(self))

    def __repr__(self):
        return f"({self.first}, {self.second}, {self.third})"


class Counter(Generic[K]):
    """A map from keys to float counts (`berkeley/Counter.java`)."""

    def __init__(self):
        self._counts: Dict[K, float] = {}

    def increment_count(self, key: K, amount: float = 1.0) -> None:
        self._counts[key] = self._counts.get(key, 0.0) + amount

    def increment_all(self, keys, amount: float = 1.0) -> None:
        for k in keys:
            self.increment_count(k, amount)

    def set_count(self, key: K, count: float) -> None:
        self._counts[key] = count

    def get_count(self, key: K) -> float:
        return self._counts.get(key, 0.0)

    def remove_key(self, key: K) -> float:
        return self._counts.pop(key, 0.0)

    def contains_key(self, key: K) -> bool:
        return key in self._counts

    def key_set(self):
        return self._counts.keys()

    def total_count(self) -> float:
        return sum(self._counts.values())

    def size(self) -> int:
        return len(self._counts)

    def is_empty(self) -> bool:
        return not self._counts

    def argmax(self) -> Optional[K]:
        if not self._counts:
            return None
        return max(self._counts, key=lambda k: self._counts[k])

    def max_count(self) -> float:
        return max(self._counts.values()) if self._counts else 0.0

    def normalize(self) -> None:
        total = self.total_count()
        if total:
            for k in self._counts:
                self._counts[k] /= total

    def scale(self, factor: float) -> "Counter[K]":
        out: Counter[K] = Counter()
        for k, v in self._counts.items():
            out.set_count(k, v * factor)
        return out

    def keys_sorted_by_count(self, descending: bool = True) -> List[K]:
        return sorted(self._counts, key=lambda k: self._counts[k],
                      reverse=descending)

    def items(self):
        return self._counts.items()

    def __iter__(self) -> Iterator[K]:
        return iter(self._counts)

    def __repr__(self):
        top = self.keys_sorted_by_count()[:10]
        inner = ", ".join(f"{k}: {self._counts[k]:g}" for k in top)
        return "{" + inner + ("…" if self.size() > 10 else "") + "}"


class CounterMap(Generic[K, V]):
    """Nested counters: key → (key → count) (`berkeley/CounterMap.java`)."""

    def __init__(self):
        self._maps: Dict[K, Counter[V]] = defaultdict(Counter)

    def increment_count(self, key: K, value: V, amount: float = 1.0) -> None:
        self._maps[key].increment_count(value, amount)

    def set_count(self, key: K, value: V, count: float) -> None:
        self._maps[key].set_count(value, count)

    def get_count(self, key: K, value: V) -> float:
        return self._maps[key].get_count(value) if key in self._maps else 0.0

    def get_counter(self, key: K) -> Counter[V]:
        return self._maps[key]

    def key_set(self):
        return self._maps.keys()

    def total_count(self) -> float:
        return sum(c.total_count() for c in self._maps.values())

    def total_size(self) -> int:
        return sum(c.size() for c in self._maps.values())

    def normalize(self) -> None:
        for c in self._maps.values():
            c.normalize()

    def is_empty(self) -> bool:
        return all(c.is_empty() for c in self._maps.values())


class PriorityQueue(Generic[T]):
    """Max-priority queue with `next`/`peek`/`get_priority`
    (`berkeley/PriorityQueue.java` — a binary max-heap used for
    best-first beam search)."""

    def __init__(self):
        self._heap: List[Tuple[float, int, T]] = []
        self._tie = itertools.count()

    def put(self, item: T, priority: float) -> None:
        # negate: heapq is a min-heap, reference queue is max-first
        heapq.heappush(self._heap, (-priority, next(self._tie), item))

    # reference name
    add = put

    def next(self) -> T:
        return heapq.heappop(self._heap)[2]

    def peek(self) -> T:
        return self._heap[0][2]

    def get_priority(self) -> float:
        return -self._heap[0][0]

    def has_next(self) -> bool:
        return bool(self._heap)

    def size(self) -> int:
        return len(self._heap)

    def is_empty(self) -> bool:
        return not self._heap

    def __iter__(self) -> Iterator[T]:
        while self.has_next():
            yield self.next()

    def __len__(self):
        return len(self._heap)


class SummaryStatistics:
    """Streaming count/mean/min/max/variance (reference:
    util/SummaryStatistics.java + berkeley counters' summary use)."""

    def __init__(self):
        self.n = 0
        self._mean = 0.0
        self._m2 = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def add(self, x) -> None:
        # vectorized Chan et al. parallel-Welford merge of the batch
        xs = np.ravel(np.asarray(x, dtype=float))
        m = xs.size
        if m == 0:
            return
        b_mean = float(xs.mean())
        b_m2 = float(((xs - b_mean) ** 2).sum())
        d = b_mean - self._mean
        n = self.n + m
        self._mean += d * m / n
        self._m2 += b_m2 + d * d * self.n * m / n
        self.n = n
        self.min = min(self.min, float(xs.min()))
        self.max = max(self.max, float(xs.max()))

    @property
    def mean(self) -> float:
        return self._mean

    @property
    def variance(self) -> float:
        return self._m2 / self.n if self.n else 0.0

    @property
    def std(self) -> float:
        return self.variance ** 0.5

    def __repr__(self):
        return (f"SummaryStatistics(n={self.n}, mean={self.mean:.6g}, "
                f"std={self.std:.6g}, min={self.min:.6g}, "
                f"max={self.max:.6g})")
