"""Model serialization: save/restore config + parameters + updater state.

Parity with the reference's ModelSerializer (reference:
deeplearning4j-nn/.../util/ModelSerializer.java:37 — zip container with
entries configuration.json:90, coefficients.bin:95, updaterState.bin:40).
Same container idea, TPU-native payloads: the configuration serializes
through the framework's JSON serde, and every array pytree (params, layer
state such as batch-norm running stats, updater state) is stored as an
``.npz`` member keyed by flattened tree paths — restoring config + params +
updater state resumes training exactly.
"""
from __future__ import annotations

import io
import json
import zipfile
from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

CONFIG_ENTRY = "configuration.json"
COEFFICIENTS_ENTRY = "coefficients.npz"
STATE_ENTRY = "layerState.npz"
UPDATER_ENTRY = "updaterState.npz"  # reference: UPDATER_BIN, ModelSerializer.java:40
META_ENTRY = "metadata.json"

_SEP = "//"


def _flatten(tree: Dict[str, Any], prefix: str = ""
             ) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    for k, v in tree.items():
        path = f"{prefix}{_SEP}{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, path))
        elif v is None:
            continue
        else:
            out[path] = np.asarray(v)
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for path, arr in flat.items():
        parts = path.split(_SEP)
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(arr)
    return tree


def _merge_into(skeleton: Any, loaded: Any, cast: bool = False) -> Any:
    """Overlay loaded leaves onto a freshly-initialized skeleton so empty
    dicts (e.g. SGD's stateless updater slots) survive the npz round-trip.
    With ``cast``, loaded leaves are cast to the skeleton leaf's dtype —
    used for updater state, whose canonical dtype is >=f32 even for bf16
    params (updaters._init_leaf); checkpoints written before that policy
    hold bf16 moments, and an uncast carry would flip dtype across a
    lax.scan step in fit_batched."""
    if isinstance(skeleton, dict):
        if not isinstance(loaded, dict):
            return skeleton
        return {k: (_merge_into(v, loaded[k], cast) if k in loaded else v)
                for k, v in skeleton.items()}
    if loaded is None:
        return skeleton
    if cast and hasattr(skeleton, "dtype"):
        return jnp.asarray(loaded).astype(skeleton.dtype)
    return loaded


def write_model(model, path: str, save_updater: bool = True) -> None:
    """Save a MultiLayerNetwork or ComputationGraph (reference:
    ModelSerializer.writeModel, ModelSerializer.java:79-95)."""
    from deeplearning4j_tpu.nn.graph.computation_graph import ComputationGraph
    model_type = ("ComputationGraph"
                  if isinstance(model, ComputationGraph)
                  else "MultiLayerNetwork")
    meta = {
        "model_type": model_type,
        "framework": "deeplearning4j_tpu",
        "iteration_count": int(model.iteration_count),
        "epoch_count": int(model.epoch_count),
        "dtype": str(model.conf.training.dtype),
        "has_updater_state": bool(save_updater),
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr(CONFIG_ENTRY, model.conf.to_json())
        zf.writestr(META_ENTRY, json.dumps(meta, indent=2))
        _write_npz(zf, COEFFICIENTS_ENTRY, _flatten(model.params))
        state = getattr(model, "state", None)
        if state:
            _write_npz(zf, STATE_ENTRY, _flatten(state))
        if save_updater and model.updater_state:
            _write_npz(zf, UPDATER_ENTRY, _flatten(model.updater_state))


_DTYPES_KEY = "__dtypes__"


def _write_npz(zf: zipfile.ZipFile, entry: str,
               flat: Dict[str, np.ndarray]) -> None:
    # np.savez round-trips ml_dtypes (bfloat16 etc.) as opaque void dtypes;
    # store such arrays as uint16/uint8 bit-views plus a dtype sidecar
    dtypes: Dict[str, str] = {}
    storable: Dict[str, np.ndarray] = {}
    for k, a in flat.items():
        if a.dtype.kind == "V":  # ml_dtypes (bfloat16, fp8, ...)
            dtypes[k] = a.dtype.name
            storable[k] = a.view(np.uint8 if a.dtype.itemsize == 1
                                 else np.uint16)
        else:
            storable[k] = a
    if dtypes:
        storable[_DTYPES_KEY] = np.frombuffer(
            json.dumps(dtypes).encode(), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **storable)
    zf.writestr(entry, buf.getvalue())


def _read_npz(zf: zipfile.ZipFile, entry: str
              ) -> Optional[Dict[str, np.ndarray]]:
    try:
        data = zf.read(entry)
    except KeyError:
        return None
    with np.load(io.BytesIO(data)) as npz:
        out = {k: npz[k] for k in npz.files}
    dtypes = {}
    if _DTYPES_KEY in out:
        dtypes = json.loads(out.pop(_DTYPES_KEY).tobytes().decode())
    for k, dt in dtypes.items():
        import ml_dtypes
        out[k] = out[k].view(np.dtype(getattr(ml_dtypes, dt)))
    return out


def _read_meta(zf: zipfile.ZipFile) -> Dict[str, Any]:
    try:
        return json.loads(zf.read(META_ENTRY))
    except KeyError:
        return {}


def restore_multi_layer_network(path: str, load_updater: bool = True):
    """reference: ModelSerializer.restoreMultiLayerNetwork."""
    from deeplearning4j_tpu.nn.conf.configuration import \
        MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    with zipfile.ZipFile(path) as zf:
        conf = MultiLayerConfiguration.from_json(
            zf.read(CONFIG_ENTRY).decode())
        net = MultiLayerNetwork(conf).init()
        _restore_arrays(zf, net, load_updater)
    return net


def restore_computation_graph(path: str, load_updater: bool = True):
    """reference: ModelSerializer.restoreComputationGraph."""
    from deeplearning4j_tpu.nn.conf.configuration import \
        ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.graph.computation_graph import ComputationGraph
    with zipfile.ZipFile(path) as zf:
        conf = ComputationGraphConfiguration.from_json(
            zf.read(CONFIG_ENTRY).decode())
        net = ComputationGraph(conf).init()
        _restore_arrays(zf, net, load_updater)
    return net


def _restore_arrays(zf: zipfile.ZipFile, net, load_updater: bool) -> None:
    meta = _read_meta(zf)
    coeff = _read_npz(zf, COEFFICIENTS_ENTRY)
    if coeff is not None:
        net.params = _merge_into(net.params, _unflatten(coeff))
    state = _read_npz(zf, STATE_ENTRY)
    if state is not None:
        net.state = _merge_into(net.state, _unflatten(state))
    if load_updater:
        upd = _read_npz(zf, UPDATER_ENTRY)
        if upd is not None:
            net.updater_state = _merge_into(net.updater_state,
                                            _unflatten(upd), cast=True)
    net.iteration_count = int(meta.get("iteration_count", 0))
    net.epoch_count = int(meta.get("epoch_count", 0))


def model_type_of(path: str) -> Optional[str]:
    """Peek at a saved model's type without restoring it."""
    try:
        with zipfile.ZipFile(path) as zf:
            meta = _read_meta(zf)
            if meta.get("model_type"):
                return meta["model_type"]
            cfg = json.loads(zf.read(CONFIG_ENTRY))
            t = cfg.get("@class", "")
            return ("ComputationGraph"
                    if "ComputationGraph" in t else "MultiLayerNetwork")
    except (zipfile.BadZipFile, KeyError, OSError):
        return None


class ModelSerializer:
    """Static facade matching the reference class name."""
    write_model = staticmethod(write_model)
    restore_multi_layer_network = staticmethod(restore_multi_layer_network)
    restore_computation_graph = staticmethod(restore_computation_graph)
