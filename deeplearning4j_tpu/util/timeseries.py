"""Time-series shape/mask utilities + masked reductions.

Parity with the reference's utility trio (reference:
deeplearning4j-nn/.../util/TimeSeriesUtils.java — movingAverage :44,
reshapeTimeSeriesMaskToVector :58, reshapeVectorToTimeSeriesMask :74,
reshape3dTo2d :93, reshape2dTo3d :105;
util/MaskedReductionUtil.java — maskedPoolingTimeSeries :29,
maskedPoolingConvolution :163; nn/api/MaskState.java — Active /
Passthrough).

Shape conventions differ by design: the reference stores RNN
activations as [miniBatch, size, timeSeriesLength] (NCW); here time
series are [batch, time, features] (the XLA/TPU-friendly layout used
by `nn/layers/recurrent.py`), and CNN activations are NHWC rather than
NCHW. All functions are jit-safe (pure jnp, static shapes).
"""
from __future__ import annotations

import enum

import jax.numpy as jnp


class MaskState(enum.Enum):
    """How a mask propagates past a layer (`nn/api/MaskState.java`):
    ACTIVE — mask should be applied to this layer's activations;
    PASSTHROUGH — mask exists but this layer doesn't use it (e.g.
    after a global pooling collapsed the time axis)."""
    ACTIVE = "active"
    PASSTHROUGH = "passthrough"


def moving_average(x, n: int):
    """Trailing moving average over the last axis, first valid window
    onward — output length is `x.shape[-1] - n + 1`
    (`TimeSeriesUtils.movingAverage:44`, cumsum formulation)."""
    x = jnp.asarray(x)
    c = jnp.cumsum(x, axis=-1)
    head = c[..., n - 1:n]
    rest = c[..., n:] - c[..., :-n]
    return jnp.concatenate([head, rest], axis=-1) / n


def reshape_time_series_mask_to_vector(mask):
    """[B, T] mask → [B*T, 1] column vector
    (`TimeSeriesUtils.reshapeTimeSeriesMaskToVector:58`)."""
    mask = jnp.asarray(mask)
    return mask.reshape(-1, 1)


def reshape_vector_to_time_series_mask(vec, minibatch: int):
    """[B*T, 1] (or flat) → [B, T]
    (`TimeSeriesUtils.reshapeVectorToTimeSeriesMask:74`)."""
    vec = jnp.asarray(vec)
    return vec.reshape(minibatch, -1)


def reshape_3d_to_2d(x):
    """[B, T, F] → [B*T, F]: collapse batch and time so per-step ops
    (e.g. an output layer) see a plain 2-D batch
    (`TimeSeriesUtils.reshape3dTo2d:93`)."""
    x = jnp.asarray(x)
    b, t, f = x.shape
    return x.reshape(b * t, f)


def reshape_2d_to_3d(x, minibatch: int):
    """[B*T, F] → [B, T, F] (`TimeSeriesUtils.reshape2dTo3d:105`)."""
    x = jnp.asarray(x)
    return x.reshape(minibatch, -1, x.shape[-1])


def reshape_per_output_time_series_mask_to_2d(mask):
    """Per-output mask [B, T, O] → [B*T, O]
    (`TimeSeriesUtils.reshapePerOutputTimeSeriesMaskTo2d:83`)."""
    mask = jnp.asarray(mask)
    return mask.reshape(-1, mask.shape[-1])


_NEG = -1e30  # large-negative fill for masked max (safe in bf16/f32)


def masked_pooling_time_series(pooling_type: str, x, mask, pnorm: int = 2):
    """Pool [B, T, F] over time with a [B, T] validity mask — masked
    steps contribute nothing (`MaskedReductionUtil.
    maskedPoolingTimeSeries:29`). pooling_type: max|avg|sum|pnorm."""
    x = jnp.asarray(x)
    m = jnp.asarray(mask).astype(x.dtype)[..., None]        # [B, T, 1]
    ptype = pooling_type.lower()
    if ptype == "max":
        return jnp.max(jnp.where(m > 0, x, _NEG), axis=1)
    if ptype == "sum":
        return jnp.sum(x * m, axis=1)
    if ptype in ("avg", "mean"):
        denom = jnp.maximum(jnp.sum(m, axis=1), 1.0)
        return jnp.sum(x * m, axis=1) / denom
    if ptype == "pnorm":
        p = float(pnorm)
        s = jnp.sum(jnp.abs(x * m) ** p, axis=1)
        return s ** (1.0 / p)
    raise ValueError(f"unknown pooling type: {pooling_type}")


def masked_pooling_convolution(pooling_type: str, x, mask, pnorm: int = 2):
    """Pool NHWC [B, H, W, C] over the spatial axes with a [B, H, W]
    (or broadcastable) validity mask
    (`MaskedReductionUtil.maskedPoolingConvolution:163`; reference is
    NCHW — NHWC here, see module docstring)."""
    x = jnp.asarray(x)
    m = jnp.asarray(mask).astype(x.dtype)
    if m.ndim == 3:
        m = m[..., None]                                     # [B, H, W, 1]
    ptype = pooling_type.lower()
    if ptype == "max":
        return jnp.max(jnp.where(m > 0, x, _NEG), axis=(1, 2))
    if ptype == "sum":
        return jnp.sum(x * m, axis=(1, 2))
    if ptype in ("avg", "mean"):
        denom = jnp.maximum(jnp.sum(m, axis=(1, 2)), 1.0)
        return jnp.sum(x * m, axis=(1, 2)) / denom
    if ptype == "pnorm":
        p = float(pnorm)
        return jnp.sum(jnp.abs(x * m) ** p, axis=(1, 2)) ** (1.0 / p)
    raise ValueError(f"unknown pooling type: {pooling_type}")
