"""Checkpoint / resume for long-running (and distributed) training.

Parity with the reference's checkpoint story (reference: SURVEY.md §5.4 —
ModelSerializer zip of configuration.json + coefficients.bin +
updaterState.bin restores training exactly; earlystopping/saver/
LocalFileModelSaver persists best/latest). That covers single-process
saves; the TPU-idiomatic extension (SURVEY §5.3: "checkpoint-based
restart + multi-host health") is orbax: async array checkpointing that
coordinates across hosts, versioned step directories, retention.

`CheckpointManager` wraps orbax when available and falls back to the
npz serializer otherwise; `CheckpointListener` snapshots every N
iterations from inside the normal listener stream.

Durability model of the npz path (the orbax path inherits orbax's own
guarantees):

- **Atomic publication.** Every `step_<N>` is written into a
  `step_<N>.tmp` staging directory, each file fsynced, then published
  with one `os.replace` (+ parent-dir fsync) — `all_steps()` /
  `latest_step()` can never observe a half-written step. Orphaned
  `.tmp` staging dirs from a mid-write kill are swept at startup.
- **Integrity manifest.** `manifest.json` records a CRC32 + shape +
  dtype per stored array and the payload tree structure. Restore
  verifies the checksum of every array it reads; a mismatch raises
  `CheckpointCorruptError`, which the `step=None` restore path treats
  like any unreadable step — it falls through to the next older
  verified step. A template leaf absent from the manifest fails with
  an explicit tree-structure-mismatch message.
- **Async saves.** `async_save=True` snapshots the payload to host
  memory synchronously (the only work on the step loop's critical
  path) and performs CRC + fsync + rename on a single background
  writer thread, bounded to one write in flight. Write errors are
  surfaced on the next `save()` (or `wait()`); atomic publication
  means `latest_step()` never points at the in-flight write.

Metrics (`observability` registry, injectable via ``registry=``):
`checkpoint_write_seconds`, `checkpoint_save_stall_seconds`,
`checkpoint_saves_total{mode}`, `checkpoint_verify_failures_total`,
`checkpoint_async_pending`.
"""
from __future__ import annotations

import json
import logging
import os
import re
import shutil
import zlib
from concurrent.futures import Future, ThreadPoolExecutor
from pathlib import Path
from typing import Any, Dict, List, Optional

import ml_dtypes
import numpy as np
import jax

# same-width integer container for dtypes numpy can't round-trip via npz
_UINT_OF_WIDTH = {1: np.uint8, 2: np.uint16, 4: np.uint32}

from deeplearning4j_tpu.observability.metrics import default_registry
from deeplearning4j_tpu.train.listeners import IterationListener

log = logging.getLogger("deeplearning4j_tpu")

try:
    import orbax.checkpoint as ocp
    HAVE_ORBAX = True
except ImportError:  # pragma: no cover
    HAVE_ORBAX = False

MANIFEST_VERSION = 1
_TMP_SUFFIX = ".tmp"


class CheckpointCorruptError(RuntimeError):
    """A step directory failed checksum/structure verification."""


def _fsync_path(path: Path) -> None:
    """fsync a file or directory; best-effort on platforms/filesystems
    that refuse directory fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)


def _crc(a: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(a).tobytes())


class CheckpointManager:
    """Save/restore (params, state, updater_state, iteration) for a
    network. Orbax path: async multi-host-safe array checkpointing.
    Fallback: npz files with atomic publication + CRC32 manifests.
    Either way, directory layout is `<root>/step_<N>/` with `latest`
    resolution and retention.

    ``async_save=True`` moves the npz write (CRC, fsync, rename) off
    the caller's thread — `save()` only pays the host-snapshot cost.
    With orbax, the same flag defers `wait_until_finished()` to
    `wait()` so orbax's native async pipeline overlaps the step loop.

    ``fault_injector`` (tests) receives `on_checkpoint_write(step,
    staging_dir)` before publication and `on_checkpoint_published(step,
    final_dir)` after — the torn-write / mid-write-crash hooks of
    `parallel.failure.FaultInjector`.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 use_orbax: Optional[bool] = None,
                 async_save: bool = False,
                 fault_injector=None,
                 registry=None):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.use_orbax = HAVE_ORBAX if use_orbax is None else use_orbax
        self.async_save = bool(async_save)
        self.fault_injector = fault_injector
        reg = registry if registry is not None else default_registry()
        self._m_write = reg.histogram(
            "checkpoint_write_seconds",
            "Disk time of one checkpoint write (CRC+fsync+rename)")
        self._m_stall = reg.histogram(
            "checkpoint_save_stall_seconds",
            "Time save() blocked its caller (async: snapshot only)")
        self._m_saves = reg.counter(
            "checkpoint_saves_total", "Completed checkpoint saves",
            labelnames=("mode",))
        self._m_verify_fail = reg.counter(
            "checkpoint_verify_failures_total",
            "Array checksum / structure verification failures on read")
        self._m_pending = reg.gauge(
            "checkpoint_async_pending",
            "Async checkpoint writes currently in flight")
        # single background writer; bounded to ONE in-flight write
        self._executor: Optional[ThreadPoolExecutor] = None
        self._inflight: Optional[Future] = None
        self._async_error: Optional[BaseException] = None
        self._ocp_mgr = None
        if self.use_orbax:
            self._ocp_mgr = ocp.CheckpointManager(
                self.directory.resolve(),
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, create=True))
        else:
            self._sweep_orphans()

    # -- orphan staging dirs ----------------------------------------------
    def _sweep_orphans(self) -> None:
        """Remove `step_<N>.tmp` staging dirs left by a mid-write kill.
        Only ever unpublished garbage: a completed write has already
        been renamed away from the .tmp name."""
        for p in self.directory.glob(f"step_*{_TMP_SUFFIX}"):
            log.warning("sweeping orphaned checkpoint staging dir %s "
                        "(previous writer died mid-write)", p.name)
            shutil.rmtree(p, ignore_errors=True)

    # -- payload plumbing (shared by net- and tree-level APIs) -------------
    def _write_payload(self, payload: Dict, step: int,
                       meta: Optional[Dict] = None) -> None:
        with self._m_stall.time():
            if self.use_orbax:
                self._ocp_mgr.save(step, args=ocp.args.StandardSave(payload))
                if self.async_save:
                    self._m_saves.labels("orbax_async").inc()
                else:
                    self._ocp_mgr.wait_until_finished()
                    self._m_saves.labels("orbax").inc()
                if meta is not None:
                    self._write_meta(meta, step)
                return
            # Host snapshot: the one synchronous cost of an async save.
            # np.asarray materializes device arrays on host; exotic
            # dtypes (bf16/fp8) go to same-width uints + a sidecar so
            # np.load round-trips exactly.
            flat: Dict[str, np.ndarray] = {}
            exotic: Dict[str, str] = {}
            for k, tree in payload.items():
                leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
                for path, leaf in leaves:
                    name = k + "|" + "/".join(
                        str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in path)
                    a = np.asarray(leaf)
                    if not hasattr(np, a.dtype.name):
                        exotic[name] = a.dtype.name
                        a = a.view(_UINT_OF_WIDTH[a.dtype.itemsize])
                    flat[name] = a
            if not self.async_save:
                self._write_npz(flat, exotic, int(step), meta)
                return
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=1,
                    thread_name_prefix="ckpt-writer")
            if self._inflight is not None:     # bound: 1 write in flight
                self._await_inflight()
            self._surface_async_error()
            self._m_pending.set(1)
            self._inflight = self._executor.submit(
                self._write_npz, flat, exotic, int(step), meta)

    def _write_npz(self, flat: Dict[str, np.ndarray],
                   exotic: Dict[str, str], step: int,
                   meta: Optional[Dict]) -> None:
        """CRC + stage + fsync + atomic publish of one step (runs on
        the writer thread in async mode)."""
        with self._m_write.time():
            manifest = {
                "version": MANIFEST_VERSION,
                "step": step,
                "arrays": {
                    name: {"crc32": _crc(a), "shape": list(a.shape),
                           "dtype": str(a.dtype),
                           "stored_dtype": exotic.get(name)}
                    for name, a in flat.items()},
            }
            final = self.directory / f"step_{step}"
            tmp = self.directory / f"step_{step}{_TMP_SUFFIX}"
            if tmp.exists():
                shutil.rmtree(tmp, ignore_errors=True)
            tmp.mkdir(parents=True)
            np.savez(tmp / "arrays.npz", **flat)
            (tmp / "dtypes.json").write_text(json.dumps(exotic))
            (tmp / "manifest.json").write_text(json.dumps(manifest))
            for f in ("arrays.npz", "dtypes.json", "manifest.json"):
                _fsync_path(tmp / f)
            _fsync_path(tmp)
            if self.fault_injector is not None and hasattr(
                    self.fault_injector, "on_checkpoint_write"):
                self.fault_injector.on_checkpoint_write(step, tmp)
            if final.exists():
                shutil.rmtree(final, ignore_errors=True)
            os.replace(tmp, final)
            _fsync_path(self.directory)
            if self.fault_injector is not None and hasattr(
                    self.fault_injector, "on_checkpoint_published"):
                self.fault_injector.on_checkpoint_published(step, final)
            if meta is not None:
                self._write_meta(meta, step)
            self._retain()
        self._m_saves.labels(
            "async" if self.async_save else "sync").inc()

    def _write_meta(self, meta: Dict, step: int) -> None:
        """meta_<N>.json, atomically (tmp + replace) so a torn meta
        can't shadow a good step dir."""
        final = self.directory / f"meta_{step}.json"
        tmp = self.directory / f"meta_{step}.json{_TMP_SUFFIX}"
        tmp.write_text(json.dumps(meta))
        _fsync_path(tmp)
        os.replace(tmp, final)

    # -- async bookkeeping -------------------------------------------------
    def _await_inflight(self) -> None:
        fut, self._inflight = self._inflight, None
        if fut is None:
            return
        try:
            fut.result()
        except BaseException as e:   # surfaced on the NEXT save / wait
            self._async_error = e
        finally:
            self._m_pending.set(0)

    def _surface_async_error(self) -> None:
        if self._async_error is not None:
            e, self._async_error = self._async_error, None
            raise RuntimeError(
                "previous async checkpoint write failed") from e

    def wait(self) -> None:
        """Join any in-flight async write; raises if it (or a previous
        one) failed. Call at step-loop exit / before reading back."""
        if self.use_orbax and self._ocp_mgr is not None:
            self._ocp_mgr.wait_until_finished()
        self._await_inflight()
        self._surface_async_error()

    # -- read-side verification --------------------------------------------
    def _load_manifest(self, step: int) -> Optional[Dict]:
        p = self.directory / f"step_{step}" / "manifest.json"
        if not p.exists():      # pre-manifest checkpoint: legacy-readable
            return None
        return json.loads(p.read_text())

    def verify_step(self, step: int) -> bool:
        """Full-step integrity check: every manifest array present in
        arrays.npz with a matching CRC32 (and nothing extra). Legacy
        steps without a manifest verify by readability alone. Failures
        bump `checkpoint_verify_failures_total`."""
        if self.use_orbax:
            return int(step) in self.all_steps()
        d = self.directory / f"step_{step}"
        try:
            manifest = self._load_manifest(int(step))
            with np.load(d / "arrays.npz") as data:
                if manifest is None:
                    for name in data.files:    # readability probe
                        data[name]
                    return True
                arrays = manifest["arrays"]
                if set(arrays) != set(data.files):
                    raise CheckpointCorruptError(
                        f"step {step}: manifest lists "
                        f"{len(arrays)} arrays, npz holds "
                        f"{len(data.files)}")
                for name, m in arrays.items():
                    a = data[name]
                    if str(a.dtype) != m["dtype"]:
                        # same-width views keep the CRC identical —
                        # only the manifest dtype catches them
                        raise CheckpointCorruptError(
                            f"step {step}: dtype mismatch for "
                            f"{name!r} (manifest {m['dtype']}, "
                            f"stored {a.dtype})")
                    if _crc(a) != m["crc32"]:
                        raise CheckpointCorruptError(
                            f"step {step}: checksum mismatch for "
                            f"{name!r}")
            return True
        except Exception as e:
            self._m_verify_fail.inc()
            log.warning("checkpoint step_%s failed verification: %s",
                        step, e)
            return False

    def _read_payload(self, template: Dict, step: int) -> Dict:
        if self.use_orbax:
            return self._ocp_mgr.restore(
                step, args=ocp.args.StandardRestore(template))
        d = self.directory / f"step_{step}"
        manifest = self._load_manifest(step)
        man_arrays = manifest["arrays"] if manifest else None
        data = np.load(d / "arrays.npz")
        exotic: Dict[str, str] = {}
        if (d / "dtypes.json").exists():
            exotic = json.loads((d / "dtypes.json").read_text())
        restored = {}
        for k, tree in template.items():
            leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
            vals = []
            for path, leaf in leaves:
                name = k + "|" + "/".join(
                    str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in path)
                if man_arrays is not None and name not in man_arrays:
                    raise CheckpointCorruptError(
                        f"checkpoint tree-structure mismatch: template "
                        f"leaf {name!r} is not in step {step}'s "
                        f"manifest ({len(man_arrays)} arrays: "
                        f"{sorted(man_arrays)[:4]}...)")
                try:
                    a = data[name]
                except KeyError:
                    raise CheckpointCorruptError(
                        f"checkpoint tree-structure mismatch: template "
                        f"leaf {name!r} is not stored in step {step}")
                if man_arrays is not None \
                        and str(a.dtype) != man_arrays[name]["dtype"]:
                    # a rewritten npy header reinterprets the SAME
                    # bytes under a different dtype: CRC (over bytes)
                    # still matches, so restore would silently hand
                    # back garbage values — fail loudly instead
                    self._m_verify_fail.inc()
                    raise CheckpointCorruptError(
                        f"dtype mismatch for {name!r} in step {step}: "
                        f"manifest records "
                        f"{man_arrays[name]['dtype']}, stored array "
                        f"reads back as {a.dtype} — refusing to "
                        "silently reinterpret bytes")
                if man_arrays is not None \
                        and _crc(a) != man_arrays[name]["crc32"]:
                    self._m_verify_fail.inc()
                    raise CheckpointCorruptError(
                        f"checksum mismatch for {name!r} in step {step} "
                        "(torn or corrupted write)")
                if name in exotic:
                    a = a.view(getattr(ml_dtypes, exotic[name]))
                vals.append(jax.numpy.asarray(a))
            restored[k] = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(tree), vals)
        return restored

    # -- save --------------------------------------------------------------
    def save(self, net, step: Optional[int] = None) -> int:
        step = int(net.iteration_count if step is None else step)
        payload = {"params": net.params, "state": net.state,
                   "updater_state": net.updater_state}
        meta = {"step": step,
                "iteration_count": int(net.iteration_count),
                "epoch_count": int(net.epoch_count)}
        self._write_payload(payload, step, meta=meta)
        return step

    def _retain(self) -> None:
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep]:
            shutil.rmtree(self.directory / f"step_{s}", ignore_errors=True)
            try:
                (self.directory / f"meta_{s}.json").unlink()
            except FileNotFoundError:
                pass

    # -- restore -----------------------------------------------------------
    def all_steps(self) -> List[int]:
        if self.use_orbax:
            return sorted(self._ocp_mgr.all_steps())
        out = []
        for p in self.directory.glob("step_*"):
            m = re.fullmatch(r"step_(\d+)", p.name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _resolve_readable(self, template: Dict,
                          step: Optional[int]):
        """Read the requested step, or — when ``step`` is None — the
        NEWEST readable AND verified one: a corrupt/partial/checksum-
        failing `step_<N>` directory (killed mid-write, torn copy, bit
        rot) logs a warning and falls back to the previous good step
        instead of failing restore outright. An explicitly requested
        step still fails hard. Returns (payload, step) or (None, None)
        when no checkpoint exists."""
        steps = ([int(step)] if step is not None
                 else list(reversed(self.all_steps())))
        last_err: Optional[BaseException] = None
        for s in steps:
            try:
                return self._read_payload(template, s), s
            except Exception as e:
                if step is not None:
                    raise
                last_err = e
                log.warning("checkpoint step_%d unreadable (%s); "
                            "falling back to previous step", s, e)
        if last_err is not None:
            raise RuntimeError(
                f"no readable checkpoint under {self.directory}"
            ) from last_err
        return None, None

    def restore(self, net, step: Optional[int] = None):
        """Restore in place; returns the step restored from (None if no
        checkpoint exists). With step=None a corrupt newest step falls
        back to the previous good one (_resolve_readable). Joins any
        in-flight async write first so the newest step is findable."""
        self.wait()
        template = {"params": net.params, "state": net.state,
                    "updater_state": net.updater_state}
        restored, step = self._resolve_readable(template, step)
        if restored is None:
            return None
        net.params = restored["params"]
        net.state = restored["state"]
        # Cast to the freshly-initialized skeleton's dtypes: updater state
        # is canonically >=f32 even for bf16 params (updaters._init_leaf),
        # but older checkpoints hold bf16 moments, and an uncast carry
        # would flip dtype across a lax.scan step in fit_batched.
        net.updater_state = jax.tree.map(
            lambda skel, got: (got.astype(skel.dtype)
                               if hasattr(skel, "dtype") else got),
            net.updater_state, restored["updater_state"])
        meta_path = self.directory / f"meta_{step}.json"
        if meta_path.exists():
            meta = json.loads(meta_path.read_text())
            net.iteration_count = meta.get("iteration_count", step)
            net.epoch_count = meta.get("epoch_count", 0)
        return step


    # -- arbitrary-pytree API (distributed/FSDP training states) -----------
    def save_tree(self, tree, step: int,
                  meta: Optional[Dict] = None) -> int:
        """Checkpoint an arbitrary pytree — e.g. FSDP/composite-parallel
        (params, AdamState) from parallel/fsdp.py or parallel/megatron.py.
        With orbax, sharded jax.Arrays are written distributed-safe
        (each host persists its shards; multi-host coordination via the
        PJRT runtime). ``meta`` (JSON dict) is published atomically
        beside the step — the elastic coordinator stores its data
        cursor there (ISSUE-18)."""
        self._write_payload({"tree": tree}, int(step), meta=meta)
        return int(step)

    def read_meta(self, step: int) -> Optional[Dict]:
        """The meta dict published with ``step`` (save/save_tree
        ``meta=``), or None when the step has none."""
        p = self.directory / f"meta_{int(step)}.json"
        if not p.exists():
            return None
        return json.loads(p.read_text())

    def restore_tree(self, template, step: Optional[int] = None,
                     with_step: bool = False):
        """Restore a pytree saved by save_tree. ``template`` supplies
        structure, dtypes, AND shardings: restoring an FSDP state with a
        sharded template re-places each leaf into its shards (orbax), so
        a job can resume on a different mesh layout by passing the new
        mesh's template. Returns None if no checkpoint exists.
        ``with_step=True`` returns ``(tree, step)`` instead — callers
        resuming a data cursor need to know WHICH step they got (the
        newest-verified fallback may skip a torn newest step)."""
        self.wait()
        payload, step = self._resolve_readable({"tree": template}, step)
        if payload is None:
            return (None, None) if with_step else None
        out = payload["tree"]
        if not self.use_orbax:
            # npz fallback loads host arrays; re-place onto the
            # template's shardings. Abstract templates (jax.eval_shape
            # ShapeDtypeStructs carrying .sharding — the orbax path
            # accepts them) are honored the same way as concrete arrays.
            def _replace(t, v):
                sharding = getattr(t, "sharding", None)
                if isinstance(t, jax.Array) or sharding is not None:
                    return jax.device_put(v, sharding)
                return v

            out = jax.tree_util.tree_map(_replace, template, out)
        return (out, step) if with_step else out


class CheckpointListener(IterationListener):
    """Snapshot every `frequency` iterations (the reference's
    CheckpointListener role; rides the standard listener stream)."""

    def __init__(self, manager: CheckpointManager, frequency: int = 100):
        self.manager = manager
        self.frequency = max(1, frequency)

    def iteration_done(self, model, iteration: int, score: float) -> None:
        if iteration > 0 and iteration % self.frequency == 0:
            self.manager.save(model, step=iteration)
            log.info("checkpointed at iteration %d", iteration)
