"""Checkpoint / resume for long-running (and distributed) training.

Parity with the reference's checkpoint story (reference: SURVEY.md §5.4 —
ModelSerializer zip of configuration.json + coefficients.bin +
updaterState.bin restores training exactly; earlystopping/saver/
LocalFileModelSaver persists best/latest). That covers single-process
saves; the TPU-idiomatic extension (SURVEY §5.3: "checkpoint-based
restart + multi-host health") is orbax: async array checkpointing that
coordinates across hosts, versioned step directories, retention.

`CheckpointManager` wraps orbax when available and falls back to the
npz serializer otherwise; `CheckpointListener` snapshots every N
iterations from inside the normal listener stream.
"""
from __future__ import annotations

import json
import logging
import os
import re
import shutil
from pathlib import Path
from typing import Any, Dict, List, Optional

import ml_dtypes
import numpy as np
import jax

# same-width integer container for dtypes numpy can't round-trip via npz
_UINT_OF_WIDTH = {1: np.uint8, 2: np.uint16, 4: np.uint32}

from deeplearning4j_tpu.train.listeners import IterationListener

log = logging.getLogger("deeplearning4j_tpu")

try:
    import orbax.checkpoint as ocp
    HAVE_ORBAX = True
except ImportError:  # pragma: no cover
    HAVE_ORBAX = False


class CheckpointManager:
    """Save/restore (params, state, updater_state, iteration) for a
    network. Orbax path: async multi-host-safe array checkpointing.
    Fallback: npz files. Either way, directory layout is
    `<root>/step_<N>/` with `latest` resolution and retention."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 use_orbax: Optional[bool] = None):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.use_orbax = HAVE_ORBAX if use_orbax is None else use_orbax
        self._ocp_mgr = None
        if self.use_orbax:
            self._ocp_mgr = ocp.CheckpointManager(
                self.directory.resolve(),
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, create=True))

    # -- payload plumbing (shared by net- and tree-level APIs) -------------
    def _write_payload(self, payload: Dict, step: int) -> None:
        if self.use_orbax:
            self._ocp_mgr.save(step, args=ocp.args.StandardSave(payload))
            self._ocp_mgr.wait_until_finished()
            return
        d = self.directory / f"step_{step}"
        d.mkdir(parents=True, exist_ok=True)
        flat = {}
        exotic: Dict[str, str] = {}
        for k, tree in payload.items():
            leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
            for path, leaf in leaves:
                name = k + "|" + "/".join(
                    str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in path)
                a = np.asarray(leaf)
                # np.load returns raw void for ml_dtypes dtypes
                # (bf16/fp8); persist them as same-width uints plus a
                # dtype sidecar so the round-trip is exact.
                if not hasattr(np, a.dtype.name):
                    exotic[name] = a.dtype.name
                    a = a.view(_UINT_OF_WIDTH[a.dtype.itemsize])
                flat[name] = a
        np.savez(d / "arrays.npz", **flat)
        (d / "dtypes.json").write_text(json.dumps(exotic))
        self._retain()

    def _read_payload(self, template: Dict, step: int) -> Dict:
        if self.use_orbax:
            return self._ocp_mgr.restore(
                step, args=ocp.args.StandardRestore(template))
        d = self.directory / f"step_{step}"
        data = np.load(d / "arrays.npz")
        exotic: Dict[str, str] = {}
        if (d / "dtypes.json").exists():
            exotic = json.loads((d / "dtypes.json").read_text())
        restored = {}
        for k, tree in template.items():
            leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
            vals = []
            for path, leaf in leaves:
                name = k + "|" + "/".join(
                    str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in path)
                a = data[name]
                if name in exotic:
                    a = a.view(getattr(ml_dtypes, exotic[name]))
                vals.append(jax.numpy.asarray(a))
            restored[k] = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(tree), vals)
        return restored

    # -- save --------------------------------------------------------------
    def save(self, net, step: Optional[int] = None) -> int:
        step = int(net.iteration_count if step is None else step)
        payload = {"params": net.params, "state": net.state,
                   "updater_state": net.updater_state}
        self._write_payload(payload, step)
        meta = {"step": step,
                "iteration_count": int(net.iteration_count),
                "epoch_count": int(net.epoch_count)}
        (self.directory / f"meta_{step}.json").write_text(json.dumps(meta))
        return step

    def _retain(self) -> None:
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep]:
            shutil.rmtree(self.directory / f"step_{s}", ignore_errors=True)
            try:
                (self.directory / f"meta_{s}.json").unlink()
            except FileNotFoundError:
                pass

    # -- restore -----------------------------------------------------------
    def all_steps(self) -> List[int]:
        if self.use_orbax:
            return sorted(self._ocp_mgr.all_steps())
        out = []
        for p in self.directory.glob("step_*"):
            m = re.fullmatch(r"step_(\d+)", p.name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _resolve_readable(self, template: Dict,
                          step: Optional[int]):
        """Read the requested step, or — when ``step`` is None — the
        NEWEST readable one: a corrupt/partial `step_<N>` directory
        (killed mid-write, torn copy) logs a warning and falls back to
        the previous good step instead of failing restore outright. An
        explicitly requested step still fails hard. Returns
        (payload, step) or (None, None) when no checkpoint exists."""
        steps = ([int(step)] if step is not None
                 else list(reversed(self.all_steps())))
        last_err: Optional[BaseException] = None
        for s in steps:
            try:
                return self._read_payload(template, s), s
            except Exception as e:
                if step is not None:
                    raise
                last_err = e
                log.warning("checkpoint step_%d unreadable (%s); "
                            "falling back to previous step", s, e)
        if last_err is not None:
            raise RuntimeError(
                f"no readable checkpoint under {self.directory}"
            ) from last_err
        return None, None

    def restore(self, net, step: Optional[int] = None):
        """Restore in place; returns the step restored from (None if no
        checkpoint exists). With step=None a corrupt newest step falls
        back to the previous good one (_resolve_readable)."""
        template = {"params": net.params, "state": net.state,
                    "updater_state": net.updater_state}
        restored, step = self._resolve_readable(template, step)
        if restored is None:
            return None
        net.params = restored["params"]
        net.state = restored["state"]
        # Cast to the freshly-initialized skeleton's dtypes: updater state
        # is canonically >=f32 even for bf16 params (updaters._init_leaf),
        # but older checkpoints hold bf16 moments, and an uncast carry
        # would flip dtype across a lax.scan step in fit_batched.
        net.updater_state = jax.tree.map(
            lambda skel, got: (got.astype(skel.dtype)
                               if hasattr(skel, "dtype") else got),
            net.updater_state, restored["updater_state"])
        meta_path = self.directory / f"meta_{step}.json"
        if meta_path.exists():
            meta = json.loads(meta_path.read_text())
            net.iteration_count = meta.get("iteration_count", step)
            net.epoch_count = meta.get("epoch_count", 0)
        return step


    # -- arbitrary-pytree API (distributed/FSDP training states) -----------
    def save_tree(self, tree, step: int) -> int:
        """Checkpoint an arbitrary pytree — e.g. FSDP/composite-parallel
        (params, AdamState) from parallel/fsdp.py or parallel/megatron.py.
        With orbax, sharded jax.Arrays are written distributed-safe
        (each host persists its shards; multi-host coordination via the
        PJRT runtime)."""
        self._write_payload({"tree": tree}, int(step))
        return int(step)

    def restore_tree(self, template, step: Optional[int] = None):
        """Restore a pytree saved by save_tree. ``template`` supplies
        structure, dtypes, AND shardings: restoring an FSDP state with a
        sharded template re-places each leaf into its shards (orbax), so
        a job can resume on a different mesh layout by passing the new
        mesh's template. Returns None if no checkpoint exists."""
        payload, step = self._resolve_readable({"tree": template}, step)
        if payload is None:
            return None
        out = payload["tree"]
        if not self.use_orbax:
            # npz fallback loads host arrays; re-place onto the
            # template's shardings. Abstract templates (jax.eval_shape
            # ShapeDtypeStructs carrying .sharding — the orbax path
            # accepts them) are honored the same way as concrete arrays.
            def _replace(t, v):
                sharding = getattr(t, "sharding", None)
                if isinstance(t, jax.Array) or sharding is not None:
                    return jax.device_put(v, sharding)
                return v

            out = jax.tree_util.tree_map(_replace, template, out)
        return out


class CheckpointListener(IterationListener):
    """Snapshot every `frequency` iterations (the reference's
    CheckpointListener role; rides the standard listener stream)."""

    def __init__(self, manager: CheckpointManager, frequency: int = 100):
        self.manager = manager
        self.frequency = max(1, frequency)

    def iteration_done(self, model, iteration: int, score: float) -> None:
        if iteration > 0 and iteration % self.frequency == 0:
            self.manager.save(model, step=iteration)
            log.info("checkpointed at iteration %d", iteration)
