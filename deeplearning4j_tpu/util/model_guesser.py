"""ModelGuesser — load any saved artifact heuristically.

Parity with the reference (reference:
deeplearning4j-core/.../util/ModelGuesser.java): given a path that may hold
a saved MultiLayerNetwork, a saved ComputationGraph, or a bare configuration
JSON, figure out which and load it.
"""
from __future__ import annotations

import json
import zipfile
from typing import Any

from deeplearning4j_tpu.util.model_serializer import (
    model_type_of, restore_computation_graph, restore_multi_layer_network)


class ModelGuesser:

    @staticmethod
    def load_model_guess(path: str) -> Any:
        """Saved model zip → restored network; raw JSON → configuration."""
        kind = model_type_of(path)
        if kind == "MultiLayerNetwork":
            return restore_multi_layer_network(path)
        if kind == "ComputationGraph":
            return restore_computation_graph(path)
        return ModelGuesser.load_config_guess(path)

    @staticmethod
    def load_config_guess(path: str) -> Any:
        from deeplearning4j_tpu.nn.conf.configuration import (
            ComputationGraphConfiguration, MultiLayerConfiguration)
        if zipfile.is_zipfile(path):
            with zipfile.ZipFile(path) as zf:
                text = zf.read("configuration.json").decode()
        else:
            with open(path) as f:
                text = f.read()
        obj = json.loads(text)
        t = obj.get("@class", "")
        if "ComputationGraph" in t:
            return ComputationGraphConfiguration.from_json(text)
        return MultiLayerConfiguration.from_json(text)
