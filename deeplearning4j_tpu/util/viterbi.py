"""Viterbi decoding.

Parity with the reference's Viterbi utility (reference:
deeplearning4j-core/.../util/Viterbi.java — most-likely state sequence
given emission likelihoods and a possible-state transition prior). The
dynamic program is expressed as a `lax.scan` over time — one compiled
program for any sequence length, batched over independent sequences.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


@jax.jit
def _viterbi_scan(log_emit: Array, log_trans: Array, log_init: Array):
    """log_emit [T, S], log_trans [S, S] (from->to), log_init [S] →
    (best_path [T], best_logp)."""

    def step(delta, emit_t):
        # delta [S]: best log-prob ending in each state at t-1
        scores = delta[:, None] + log_trans          # [S_from, S_to]
        best_prev = jnp.argmax(scores, axis=0)       # [S_to]
        delta_t = jnp.max(scores, axis=0) + emit_t
        return delta_t, best_prev

    delta0 = log_init + log_emit[0]
    delta_f, backptr = jax.lax.scan(step, delta0, log_emit[1:])
    last = jnp.argmax(delta_f)

    def backtrack(state, bp_t):
        prev = bp_t[state]
        return prev, prev

    _, rev_path = jax.lax.scan(backtrack, last, backptr, reverse=True)
    path = jnp.concatenate([rev_path, last[None]])
    return path, jnp.max(delta_f)


class Viterbi:
    """Decode the most likely hidden-state sequence.

    ``transition`` [S, S] row-stochastic (from -> to); ``initial`` [S]
    prior (uniform when omitted). ``decode(emissions)`` takes per-step
    state likelihoods [T, S] (or log-likelihoods with
    ``log_input=True``) and returns (path [T] int, log-probability).
    """

    def __init__(self, transition, initial=None, eps: float = 1e-12):
        self.log_trans = jnp.log(jnp.asarray(transition, jnp.float32)
                                 + eps)
        s = self.log_trans.shape[0]
        if initial is None:
            self.log_init = jnp.full((s,), -np.log(s), jnp.float32)
        else:
            self.log_init = jnp.log(jnp.asarray(initial, jnp.float32)
                                    + eps)
        self.eps = eps

    def decode(self, emissions, log_input: bool = False
               ) -> Tuple[np.ndarray, float]:
        e = jnp.asarray(emissions, jnp.float32)
        log_e = e if log_input else jnp.log(e + self.eps)
        path, logp = _viterbi_scan(log_e, self.log_trans, self.log_init)
        return np.asarray(path), float(logp)
