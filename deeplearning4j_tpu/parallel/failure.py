"""Failure detection + checkpoint-based recovery for training loops.

The reference has none of this in-tree (SURVEY.md §5.3: Spark mode
inherits RDD retry; a lost executor just loses one split). The
TPU-idiomatic equivalent named there — "checkpoint-based restart +
multi-host health via the coordination service" — is what this module
provides: a `FaultTolerantTrainer` that wraps any fit loop with
periodic checkpoints, detects step failures (device OOM, preempted
TPU grant, injected faults), restores the last good checkpoint, and
resumes; plus a `FaultInjector` for deterministic failure testing
(the fault-injection harness the reference also lacks).

Durability extensions (ISSUE-3) — every long-run killer has a
deterministic CPU-testable injection knob:

- **Torn checkpoints**: `FaultInjector(crash_write_at=...)` kills a
  write mid-staging (orphan `.tmp` left behind);
  `torn_write_at=...` corrupts the published arrays AFTER the atomic
  rename (zip-valid bytes, wrong content — exactly what only the
  CRC32 manifest catches).
- **Silent divergence**: `nan_at=...` poisons a batch so the loss goes
  NaN without raising; pair with `train.guard.TrainingGuard` via
  `FaultTolerantTrainer(guard=...)` for skip/rollback + LR backoff.
- **Preemption**: `PreemptionHandler` turns SIGTERM/SIGINT into a
  graceful stop-at-next-step-boundary + resumable checkpoint;
  `preempt_at=...` simulates the signal deterministically.
- **Hung steps**: `StepWatchdog` flags steps exceeding a deadline from
  a monitor thread (the TPU grant that neither completes nor errors).
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from deeplearning4j_tpu.observability.metrics import default_registry
from deeplearning4j_tpu.train.guard import (DivergenceError, StepTimeout,
                                            TrainingGuard)
from deeplearning4j_tpu.util.checkpointing import CheckpointManager

log = logging.getLogger("deeplearning4j_tpu")


class TrainingFailure(RuntimeError):
    """Raised by fault injection; real device errors (XlaRuntimeError
    etc.) are caught by their base RuntimeError."""


class FaultInjector:
    """Deterministically fail chosen iterations (test harness).
    `persistent=True` keeps failing the same iteration on retry —
    models a hard fault (bad host, poisoned input) rather than a
    transient one.

    Durability knobs (all one-shot unless ``persistent``):

    - ``nan_at``: iterations whose BATCH gets poisoned to NaN by the
      trainer — the loss goes non-finite without any exception (the
      silent-divergence failure mode; checked via `check_nan`).
    - ``preempt_at``: iterations at which a simulated SIGTERM requests
      a graceful stop (checked via `check_preempt`).
    - ``crash_write_at``: checkpoint steps whose write dies MID-STAGING
      (before the atomic rename) — leaves an orphaned `.tmp` dir, the
      published layout never sees a partial step.
    - ``torn_write_at``: checkpoint steps whose arrays.npz is replaced
      AFTER publication with zip-valid zeroed arrays — readable
      without the manifest, caught only by checksum verification.
    - ``write_delay_s``: stall every checkpoint write by this many
      seconds (async-ordering tests: latest_step must not surface the
      in-flight write).
    """

    def __init__(self, fail_at: Iterable[int] = (),
                 persistent: bool = False,
                 nan_at: Iterable[int] = (),
                 preempt_at: Iterable[int] = (),
                 crash_write_at: Iterable[int] = (),
                 torn_write_at: Iterable[int] = (),
                 write_delay_s: float = 0.0):
        self.fail_at = set(int(i) for i in fail_at)
        self.persistent = persistent
        self.nan_at = set(int(i) for i in nan_at)
        self.preempt_at = set(int(i) for i in preempt_at)
        self.crash_write_at = set(int(i) for i in crash_write_at)
        self.torn_write_at = set(int(i) for i in torn_write_at)
        self.write_delay_s = float(write_delay_s)
        self.injected = 0
        self.nans_injected = 0
        self.preempts_injected = 0
        self.writes_crashed = 0
        self.writes_torn = 0

    def check(self, iteration: int) -> None:
        if iteration in self.fail_at:
            if not self.persistent:
                self.fail_at.discard(iteration)
            self.injected += 1
            raise TrainingFailure(f"injected fault at iteration "
                                  f"{iteration}")

    def check_nan(self, iteration: int) -> bool:
        """True when this iteration's batch should be NaN-poisoned."""
        if iteration in self.nan_at:
            if not self.persistent:
                self.nan_at.discard(iteration)
            self.nans_injected += 1
            return True
        return False

    def check_preempt(self, iteration: int) -> bool:
        """True when a simulated preemption signal lands here."""
        if iteration in self.preempt_at:
            self.preempt_at.discard(iteration)
            self.preempts_injected += 1
            return True
        return False

    # -- CheckpointManager hooks (util/checkpointing) -------------------
    def on_checkpoint_write(self, step: int, staging_dir) -> None:
        """Runs after staging is fully written, BEFORE the atomic
        rename — a raise here models a kill mid-write (the .tmp dir
        survives for the startup sweep; the step never publishes)."""
        if self.write_delay_s > 0:
            time.sleep(self.write_delay_s)
        if step in self.crash_write_at:
            if not self.persistent:
                self.crash_write_at.discard(step)
            self.writes_crashed += 1
            raise TrainingFailure(
                f"injected crash during checkpoint write of step {step}")

    def on_checkpoint_published(self, step: int, final_dir) -> None:
        """Runs after the atomic rename: torn-write injection replaces
        the published arrays with zip-valid zeroed content (same names,
        shapes, dtypes) — np.load succeeds, only the CRC32 manifest can
        tell the step is garbage."""
        if step not in self.torn_write_at:
            return
        if not self.persistent:
            self.torn_write_at.discard(step)
        import numpy as np
        p = Path(final_dir) / "arrays.npz"
        with np.load(p) as data:
            zeroed = {k: np.zeros_like(data[k]) for k in data.files}
        np.savez(p, **zeroed)
        self.writes_torn += 1
        log.warning("injected torn write: step %d arrays zeroed "
                    "post-publication", step)


class ServingFaultInjector(FaultInjector):
    """Serving-side deterministic fault injection (the engine-hook
    extension of FaultInjector — serving/engine.py calls
    ``on_decode_step`` immediately before every compiled decode
    invocation).

    Knobs:
      - ``fail_at`` / ``persistent``: decode-step indices to fail. Step
        indices count COMPLETED decode steps — a failed attempt is
        retried at the same index, so a non-persistent fault vanishes on
        the first retry (transient) while ``persistent=True`` keeps
        failing the step through every retry (systemic hard fault; the
        engine's circuit breaker is what eventually reacts).
      - ``poison_requests``: request ids that fail EVERY batch
        containing them — the per-request hard fault. The engine
        responds by isolating the batch (solo re-runs) and quarantining
        exactly the poisoned requests.
      - ``delay_at``: ``{step: seconds}`` one-shot host-side stalls
        injected before the step launches — drives deadline-miss
        scheduling deterministically without real overload.
      - ``prefill_fail_at``: step indices at which a PREFILL call
        fails (continuous batching: the engine's admission prefill and
        its decode chunks share one step counter; this knob targets
        only the prefill calls, so tests can poison an admission
        without touching co-resident decoding slots).
      - ``corrupt_page_at``: ``{step: request_id}`` — before the
        compiled call holding that step index, the PAGED engine
        scribbles garbage over the physical KV page the named
        request's next token will be written to. Because the engine's
        copy-on-write guard makes every write target privately owned,
        the poison lands on the WRITER's page only: a reader sharing
        the same prefix must keep producing its clean-run tokens —
        the shared-page-isolation proof (tests/test_serving_paged.py).
      - ``prefill_chunk_fail_at``: step indices at which a CHUNKED
        prefill call (ISSUE-10: the token-budget scheduler's
        mid-prompt prefill advance) fails — targets only the chunked
        calls, so tests can kill a request MID-PREFILL while
        co-resident decoding slots (and even the same engine's one-shot
        scratch re-runs) stay healthy. ``prefill_fail_at`` also fires
        on chunked calls (they ARE prefill calls); this knob is the
        narrower one.
      - ``adopt_fail_requests``: request ids whose cross-tier KV
        ADOPTION fails at seating on the decode-side engine
        (ISSUE-11): the engine must shed the request typed
        ``shed{reason="handoff"}`` AND decref every page it allocated
        for the adoption — the handoff error path's `_free_slot`-style
        refcount audit (tests/test_serving_disagg.py). Request ids are
        the ADOPTING engine's own rids (engine-local, like
        ``poison_requests``).
      - ``draft_poison_at``: ``{step: request_id}`` — the SPECULATIVE
        engine derails the named request's draft proposals for the
        round at that step index ((d+1) mod V on device — guaranteed
        to differ from the drafter's own tokens, so verification must
        reject them all). The contract under test: a poisoned draft
        pass can never corrupt committed KV — the round degrades to
        one committed (target-verified) token, the slot's trace gains
        a ``draft_rejected`` event, and the adaptive-K controller
        falls back to K=1 (tests/test_serving_spec.py).

    Continuous batching: the engine reports the request ids of ALL
    co-resident slots at every call, so ``poison_requests`` models a
    per-slot hard fault that takes down any pool containing it; the
    engine's slot isolation (evict + solo re-run) is what confines the
    blast radius to the poisoned slot's request.
    """

    def __init__(self, fail_at: Iterable[int] = (),
                 persistent: bool = False,
                 poison_requests: Iterable[int] = (),
                 delay_at: Optional[dict] = None,
                 prefill_fail_at: Iterable[int] = (),
                 corrupt_page_at: Optional[dict] = None,
                 draft_poison_at: Optional[dict] = None,
                 prefill_chunk_fail_at: Iterable[int] = (),
                 adopt_fail_requests: Iterable[int] = ()):
        super().__init__(fail_at, persistent=persistent)
        self.adopt_fail_requests = set(int(r)
                                       for r in adopt_fail_requests)
        self.adoptions_failed = 0
        self.poison_requests = set(int(r) for r in poison_requests)
        self.delay_at = {int(k): float(v)
                         for k, v in (delay_at or {}).items()}
        self.delays_injected = 0
        self.prefill_fail_at = set(int(i) for i in prefill_fail_at)
        self.prefills_failed = 0
        self.prefill_chunk_fail_at = set(
            int(i) for i in prefill_chunk_fail_at)
        self.prefill_chunks_failed = 0
        self.corrupt_page_at = {int(k): int(v)
                                for k, v in (corrupt_page_at
                                             or {}).items()}
        self.pages_corrupted = 0
        self.draft_poison_at = {int(k): int(v)
                                for k, v in (draft_poison_at
                                             or {}).items()}
        self.drafts_poisoned = 0

    def check_corrupt_page(self, step: int) -> Optional[int]:
        """One-shot: the request id whose next-write page the paged
        engine should poison before the call at ``step``, else None.
        The counter bumps when the engine confirms the poke landed
        (the request might have left its slot by then)."""
        return self.corrupt_page_at.pop(int(step), None)

    def check_adopt(self, rid: int) -> bool:
        """One-shot: True when request ``rid``'s KV adoption should
        fail at seating (the decode-side handoff error path)."""
        if int(rid) in self.adopt_fail_requests:
            if not self.persistent:
                self.adopt_fail_requests.discard(int(rid))
            self.adoptions_failed += 1
            return True
        return False

    def check_draft_poison(self, step: int) -> Optional[int]:
        """One-shot: the request id whose draft proposals the
        speculative round at ``step`` should derail, else None. The
        counter bumps when the engine confirms the poison landed on a
        seated slot."""
        return self.draft_poison_at.pop(int(step), None)

    def on_decode_step(self, step: int,
                       request_ids: Iterable[int] = ()) -> None:
        d = self.delay_at.pop(int(step), 0.0)
        if d > 0:
            self.delays_injected += 1
            time.sleep(d)
        bad = self.poison_requests.intersection(
            int(r) for r in request_ids)
        if bad:
            self.injected += 1
            raise TrainingFailure(
                f"poisoned request(s) {sorted(bad)} at decode step "
                f"{step}")
        self.check(int(step))

    def on_prefill(self, step: int,
                   request_ids: Iterable[int] = ()) -> None:
        """Prefill-side hook (continuous batching). Same shared step
        counter and poison/fail_at/delay semantics as on_decode_step
        — a fault index fires at whichever call (prefill or chunk)
        holds that step — plus the prefill-only ``prefill_fail_at``
        knob."""
        if int(step) in self.prefill_fail_at:
            if not self.persistent:
                self.prefill_fail_at.discard(int(step))
            self.injected += 1
            self.prefills_failed += 1
            raise TrainingFailure(
                f"injected prefill fault at step {step}")
        self.on_decode_step(step, request_ids)

    def on_prefill_chunk(self, step: int,
                         request_ids: Iterable[int] = ()) -> None:
        """Chunked-prefill hook (ISSUE-10): the narrower
        ``prefill_chunk_fail_at`` knob fires only on the token-budget
        scheduler's mid-prompt prefill advances, then the call falls
        through to the full prefill semantics (prefill_fail_at /
        poison / fail_at / delay all still apply — a chunked call IS
        a prefill call)."""
        if int(step) in self.prefill_chunk_fail_at:
            if not self.persistent:
                self.prefill_chunk_fail_at.discard(int(step))
            self.injected += 1
            self.prefill_chunks_failed += 1
            raise TrainingFailure(
                f"injected prefill-chunk fault at step {step}")
        self.on_prefill(step, request_ids)


class FleetFaultInjector:
    """Fleet-level deterministic fault injection (ISSUE-9) — the
    router-hook analog of `ServingFaultInjector`: `serving/fleet.py`'s
    `Router` consults it at the start of every scheduling tick (and at
    every probe), so replica-loss scenarios that would need a real
    crashed host replay deterministically on the CPU backend
    (tests/test_serving_fleet.py).

    Knobs (router-TICK indexed where time matters):

    - ``kill_at``: ``{tick: replica_id}`` — the replica crashes at the
      start of that router tick. In-process replicas are marked dead
      (their engine, and every in-flight request's device state, is
      abandoned exactly as a crashed process would abandon it);
      subprocess replicas take a real SIGKILL. The router's contract
      under test: every in-flight request fails over to a survivor
      from its committed prefix — at most one retried dispatch, zero
      lost requests.
    - ``hang_at``: ``{tick: replica_id}`` — the replica stops making
      progress while staying alive and (in-process) answering probes:
      the wedged-grant failure mode a liveness probe cannot see.
      Subprocess replicas are SIGSTOPped (probes time out too). The
      router's no-progress detector must declare it hung and fail
      over.
    - ``slow_at``: ``{tick: (replica_id, seconds)}`` — from that tick
      on, every scheduling step of the replica stalls ``seconds``
      (in-process replicas only): the gray-failure mode hedged
      dispatch exists for.
    - ``fail_probe``: ``{replica_id: n}`` — the replica's next ``n``
      probes fail (the router must take it out of rotation WITHOUT
      killing it, and return it when probes recover).
    - ``handoff_fail_at``: handoff sequence indices (0-based, counted
      across the tiered router's lifetime) whose KV EXPORT from the
      prefill-tier replica fails (ISSUE-11). The contract under test:
      the request is never lost — the decode dispatch falls back to
      re-prefilling the committed prefix, token-exactly, and the
      handoff is counted ``outcome="failed"``.
    - ``corrupt_frame_at``: handoff sequence indices whose EXPORTED
      kvwire frame is corrupted in flight (ISSUE-17): the tiered
      router runs the exported handoff through a real encode ->
      flip-one-payload-byte -> decode round trip, so the frame's
      CRC32 check — not a mock — rejects it. Contract under test:
      typed ``WireError(kind="crc")``, a ``kvwire`` trace event, the
      handoff counted ``outcome="failed"``, and the request completes
      token-exactly via re-prefill.
    """

    def __init__(self, kill_at: Optional[dict] = None,
                 hang_at: Optional[dict] = None,
                 slow_at: Optional[dict] = None,
                 fail_probe: Optional[dict] = None,
                 handoff_fail_at: Iterable[int] = (),
                 corrupt_frame_at: Iterable[int] = ()):
        self.kill_at = {int(k): int(v)
                        for k, v in (kill_at or {}).items()}
        self.hang_at = {int(k): int(v)
                        for k, v in (hang_at or {}).items()}
        self.slow_at = {int(k): (int(v[0]), float(v[1]))
                        for k, v in (slow_at or {}).items()}
        self.fail_probe = {int(k): int(v)
                           for k, v in (fail_probe or {}).items()}
        self.handoff_fail_at = set(int(i) for i in handoff_fail_at)
        self.corrupt_frame_at = set(int(i) for i in corrupt_frame_at)
        self.kills_injected = 0
        self.hangs_injected = 0
        self.slows_injected = 0
        self.probe_failures_injected = 0
        self.handoffs_failed = 0
        self.frames_corrupted = 0

    def check_kill(self, tick: int) -> Optional[int]:
        """One-shot: the replica id to crash at ``tick``, else None."""
        rid = self.kill_at.pop(int(tick), None)
        if rid is not None:
            self.kills_injected += 1
        return rid

    def check_hang(self, tick: int) -> Optional[int]:
        """One-shot: the replica id to wedge at ``tick``, else None."""
        rid = self.hang_at.pop(int(tick), None)
        if rid is not None:
            self.hangs_injected += 1
        return rid

    def check_slow(self, tick: int) -> Optional[tuple]:
        """One-shot: ``(replica_id, seconds)`` to slow from ``tick``
        on, else None."""
        v = self.slow_at.pop(int(tick), None)
        if v is not None:
            self.slows_injected += 1
        return v

    def check_handoff(self, seq: int) -> bool:
        """One-shot: True when the ``seq``-th handoff's KV export
        should fail (the tiered router then falls back to
        re-prefilling on the decode tier)."""
        if int(seq) in self.handoff_fail_at:
            self.handoff_fail_at.discard(int(seq))
            self.handoffs_failed += 1
            return True
        return False

    def check_corrupt_frame(self, seq: int) -> bool:
        """One-shot: True when the ``seq``-th handoff's exported
        kvwire frame should be corrupted in flight (the CRC check
        rejects it and the decode tier re-prefills)."""
        if int(seq) in self.corrupt_frame_at:
            self.corrupt_frame_at.discard(int(seq))
            self.frames_corrupted += 1
            return True
        return False

    def check_probe(self, replica_id: int) -> bool:
        """True when this probe of ``replica_id`` should fail
        (decrements that replica's remaining failure budget)."""
        n = self.fail_probe.get(int(replica_id), 0)
        if n > 0:
            self.fail_probe[int(replica_id)] = n - 1
            self.probe_failures_injected += 1
            return True
        return False


class ElasticFaultInjector:
    """Elastic-training deterministic fault injection (ISSUE-18) —
    the training analog of `FleetFaultInjector`: the elastic
    coordinator (`train/elastic.py`) consults it at the start of every
    global step, so membership churn that would need real crashed
    hosts replays deterministically on the CPU backend
    (tests/test_elastic_training.py, ``flagship.py elastic_train``).

    All knobs are keyed by GLOBAL step index and fire one-shot: after
    a lossy resize rewinds the step counter, replayed steps do not
    re-fire an already-consumed injection.

    - ``kill_at``: ``{step: worker_id}`` — the worker takes a real
      SIGKILL at the start of that step. Contract under test: the
      coordinator detects the loss (pipe EOF / barrier miss), resizes
      from the last published checksummed checkpoint, replays the data
      cursor, and the final state is bit-identical to an uninterrupted
      run.
    - ``hang_at``: ``{step: worker_id}`` — the worker is SIGSTOPped:
      alive to the OS, silent on the pipe. The straggler path must
      escalate (loose sync) and eventually evict it.
    - ``slow_at``: ``{step: (worker_id, seconds)}`` — from that step
      on, the worker sleeps ``seconds`` before answering each command
      (worker-side, over the pipe). ``seconds=0`` clears the slowdown
      — the straggler that recovers.
    - ``join_at``: ``{step: worker_id}`` — a new worker (or a killed
      one's replacement, same id) is spawned and adopted at that
      step's resize barrier.
    """

    def __init__(self, kill_at: Optional[dict] = None,
                 hang_at: Optional[dict] = None,
                 slow_at: Optional[dict] = None,
                 join_at: Optional[dict] = None):
        self.kill_at = {int(k): int(v)
                        for k, v in (kill_at or {}).items()}
        self.hang_at = {int(k): int(v)
                        for k, v in (hang_at or {}).items()}
        self.slow_at = {int(k): (int(v[0]), float(v[1]))
                        for k, v in (slow_at or {}).items()}
        self.join_at = {int(k): int(v)
                        for k, v in (join_at or {}).items()}
        self.kills_injected = 0
        self.hangs_injected = 0
        self.slows_injected = 0
        self.joins_injected = 0

    def check_kill(self, step: int) -> Optional[int]:
        """One-shot: the worker id to SIGKILL at ``step``, else None."""
        wid = self.kill_at.pop(int(step), None)
        if wid is not None:
            self.kills_injected += 1
        return wid

    def check_hang(self, step: int) -> Optional[int]:
        """One-shot: the worker id to SIGSTOP at ``step``, else None."""
        wid = self.hang_at.pop(int(step), None)
        if wid is not None:
            self.hangs_injected += 1
        return wid

    def check_slow(self, step: int) -> Optional[tuple]:
        """One-shot: ``(worker_id, seconds)`` per-command slowdown to
        apply from ``step`` on (0 clears), else None."""
        v = self.slow_at.pop(int(step), None)
        if v is not None:
            self.slows_injected += 1
        return v

    def check_join(self, step: int) -> Optional[int]:
        """One-shot: the worker id to spawn+adopt at ``step``, else
        None."""
        wid = self.join_at.pop(int(step), None)
        if wid is not None:
            self.joins_injected += 1
        return wid


@dataclass(frozen=True)
class StormArrival:
    """One scripted submission of a hostile-tenant storm (ISSUE-16):
    at router/engine tick ``tick``, tenant ``tenant`` submits a
    ``prompt_tokens``-long prompt (derived deterministically from
    ``seed`` via `storm_prompt`) asking for ``max_new_tokens`` at
    QoS class ``priority``."""
    tick: int
    tenant: str
    priority: int
    seed: int
    prompt_tokens: int
    max_new_tokens: int


def hostile_tenant_storm(ticks: int = 120, *,
                         victim: str = "victim",
                         victim_every: int = 4,
                         victim_prompt: int = 8,
                         victim_new: int = 8,
                         victim_priority: int = 5,
                         hostiles: int = 3,
                         flood_per_tick: int = 2,
                         hostile_prompt: int = 24,
                         hostile_new: int = 16,
                         start_tick: int = 0,
                         kill_tick: Optional[int] = None,
                         kill_replica: int = 0,
                         slow_tick: Optional[int] = None,
                         slow_replica: int = 0,
                         slow_seconds: float = 0.05,
                         ) -> Tuple[List[StormArrival], Dict]:
    """Deterministic hostile-tenant arrival script (ISSUE-16), shared
    by the QoS fairness tests and ``flagship.py qos_storm``.

    One well-behaved ``victim`` tenant submits a short high-priority
    request every ``victim_every`` ticks while ``hostiles`` flood
    tenants each submit ``flood_per_tick`` long low-priority requests
    EVERY tick — the adversarial mix a fair-share scheduler must not
    let starve the victim. No RNG is consulted: the same kwargs always
    yield the same arrivals, so a bench run and a test assert on the
    same traffic.

    Returns ``(arrivals, injector_kwargs)``: arrivals sorted by
    ``(tick, submission order)``, and kwargs for `FleetFaultInjector`
    wiring the optional ``kill_tick`` (kill-one-replica-mid-storm)
    and ``slow_tick`` (gray-failure straggler) knobs — empty dicts
    stay absent so ``FleetFaultInjector(**injector_kwargs)`` is a
    no-op injector when neither knob is set.
    """
    if ticks <= 0 or victim_every <= 0:
        raise ValueError("ticks and victim_every must be positive")
    arrivals: List[StormArrival] = []
    seed = 0
    for t in range(start_tick, start_tick + int(ticks)):
        if (t - start_tick) % int(victim_every) == 0:
            arrivals.append(StormArrival(
                tick=t, tenant=victim, priority=int(victim_priority),
                seed=seed, prompt_tokens=int(victim_prompt),
                max_new_tokens=int(victim_new)))
            seed += 1
        for h in range(int(hostiles)):
            for _ in range(int(flood_per_tick)):
                arrivals.append(StormArrival(
                    tick=t, tenant=f"hostile{h}", priority=0,
                    seed=seed, prompt_tokens=int(hostile_prompt),
                    max_new_tokens=int(hostile_new)))
                seed += 1
    injector_kwargs: Dict = {}
    if kill_tick is not None:
        injector_kwargs["kill_at"] = {int(kill_tick): int(kill_replica)}
    if slow_tick is not None:
        injector_kwargs["slow_at"] = {
            int(slow_tick): (int(slow_replica), float(slow_seconds))}
    return arrivals, injector_kwargs


def storm_prompt(arrival: StormArrival, vocab_size: int):
    """The deterministic prompt for one `StormArrival` — same recipe
    as the serving tests' ``_prompt`` helpers, keyed on the arrival's
    seed so distinct arrivals exercise distinct prefixes."""
    import numpy as np
    n = int(arrival.prompt_tokens)
    return (np.arange(n, dtype=np.int32) * (int(arrival.seed) * 2 + 3)
            + int(arrival.seed)) % int(vocab_size)


class PreemptionHandler:
    """Graceful-stop coordination for SIGTERM/SIGINT preemptions.

    `install()` hooks the signals (main thread only — elsewhere the
    handler degrades to flag-only mode, driven via `request_stop()`,
    which is also what `FaultInjector.preempt_at` simulation uses).
    The flag is checked by `FaultTolerantTrainer` at every step
    boundary: the current step finishes, a checkpoint is written, and
    `fit` returns resumable instead of dying mid-step with hours of
    work discarded. Publishes `preemption_stop_requested` (gauge) and
    `preemption_signals_total`."""

    def __init__(self, signals: Optional[Iterable[int]] = None,
                 registry=None):
        import signal as _signal
        self._signal_mod = _signal
        if signals is None:
            signals = [s for s in (getattr(_signal, "SIGTERM", None),
                                   getattr(_signal, "SIGINT", None))
                       if s is not None]
        self.signals = tuple(signals)
        self._stop = threading.Event()
        self._prev: dict = {}
        self.installed = False
        self.signals_seen = 0
        reg = registry if registry is not None else default_registry()
        self._m_signals = reg.counter(
            "preemption_signals_total",
            "Preemption signals (or simulations) observed")
        reg.gauge(
            "preemption_stop_requested",
            "1 while a graceful stop is pending"
        ).set_function(lambda: 1.0 if self._stop.is_set() else 0.0)

    def install(self) -> "PreemptionHandler":
        if threading.current_thread() is not threading.main_thread():
            log.warning("PreemptionHandler: not on the main thread; "
                        "signal hooks unavailable (flag-only mode)")
            return self
        for sig in self.signals:
            self._prev[sig] = self._signal_mod.signal(sig,
                                                      self._on_signal)
        self.installed = True
        return self

    def _on_signal(self, signum, frame) -> None:
        self.signals_seen += 1
        self._m_signals.inc()
        log.warning("signal %s received: graceful stop requested at "
                    "next step boundary", signum)
        self.request_stop()

    def request_stop(self) -> None:
        self._stop.set()

    def stop_requested(self) -> bool:
        return self._stop.is_set()

    def clear(self) -> None:
        self._stop.clear()

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            try:
                self._signal_mod.signal(sig, prev)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._prev.clear()
        self.installed = False

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


class StepWatchdog:
    """Monitor thread flagging training steps that exceed a wall-clock
    deadline — the hung-grant failure mode where a step neither
    completes nor raises. `arm()` before the step, `disarm()` after;
    a step still armed past ``deadline_s`` is flagged once (logged,
    `watchdog_hung_steps_total` bumped, ``on_hung(iteration,
    elapsed_s)`` called if given — e.g. a PreemptionHandler's
    request_stop for checkpoint-and-exit policies).

    ISSUE-18 escalation: ``escalate`` receives a typed
    `train.guard.StepTimeout` for the same flagging (the elastic
    coordinator's loose-sync downgrade consumes it; usable standalone).
    ``clock`` is injectable and `check()` is the synchronous detection
    step the monitor thread runs — unit tests drive it directly with a
    fake clock, no thread, fully deterministic."""

    def __init__(self, deadline_s: float,
                 on_hung: Optional[Callable[[int, float], None]] = None,
                 poll_s: Optional[float] = None,
                 escalate: Optional[Callable[..., None]] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 registry=None):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.deadline_s = float(deadline_s)
        self.on_hung = on_hung
        self.escalate = escalate
        self.clock = clock
        self.poll_s = (max(0.005, min(self.deadline_s / 4.0, 0.25))
                       if poll_s is None else float(poll_s))
        self._lock = threading.Lock()
        self._armed_at: Optional[float] = None
        self._iteration = 0
        self._flagged = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.hung_iterations: list = []
        self.timeouts: list = []
        reg = registry if registry is not None else default_registry()
        self._m_hung = reg.counter(
            "watchdog_hung_steps_total",
            "Steps that exceeded the watchdog deadline")
        reg.gauge(
            "watchdog_step_deadline_seconds",
            "Configured per-step watchdog deadline").set(self.deadline_s)

    def start(self) -> "StepWatchdog":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run,
                                            name="step-watchdog",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def arm(self, iteration: int) -> None:
        with self._lock:
            self._armed_at = self.clock()
            self._iteration = int(iteration)
            self._flagged = False

    def disarm(self) -> None:
        with self._lock:
            self._armed_at = None

    def check(self) -> Optional["StepTimeout"]:
        """One synchronous detection pass: flag the armed step if it
        is past deadline (once per arm), run the callbacks, and return
        the typed `StepTimeout` — or None when nothing fired. The
        monitor thread calls this every ``poll_s``; callers with their
        own event loop (or a fake clock in tests) call it directly."""
        cb = esc = None
        with self._lock:
            if self._armed_at is None or self._flagged:
                return None
            elapsed = self.clock() - self._armed_at
            if elapsed <= self.deadline_s:
                return None
            self._flagged = True
            self.hung_iterations.append(self._iteration)
            self._m_hung.inc()
            it, cb, esc = self._iteration, self.on_hung, self.escalate
            log.error("watchdog: step %d exceeded %.3fs "
                      "deadline (%.3fs elapsed and counting)",
                      self._iteration, self.deadline_s, elapsed)
        timeout = StepTimeout(iteration=it, deadline_s=self.deadline_s,
                              elapsed_s=elapsed)
        self.timeouts.append(timeout)
        if cb is not None:
            cb(it, elapsed)
        if esc is not None:
            esc(timeout)
        return timeout

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.check()

    def __enter__(self) -> "StepWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class FaultTolerantTrainer:
    """Run fit over an iterator with checkpoint/restore-based recovery.

    Each minibatch step is guarded; on failure the model is restored
    from the latest checkpoint and the epoch continues from the current
    batch (at-least-once batch semantics — same guarantee as the
    reference's Spark retry, which may also re-process a split).

    ``max_restarts`` bounds CONSECUTIVE failures, not lifetime
    failures: the counter resets on every successful step, so
    max_restarts transient faults spread across a long job no longer
    abort it — only a fault that persists through max_restarts
    back-to-back recovery attempts does. ``restarts`` stays the
    cumulative total for reporting.

    Durability integrations (all optional):

    - ``guard``: a `TrainingGuard` installed on the net — NaN/spike
      steps are skipped; a `DivergenceError` rollback restores the
      last checkpoint AND backs the learning rate off.
    - ``preemption``: a `PreemptionHandler` (or True to create+install
      one) — a pending stop checkpoints at the step boundary and
      `fit` returns False (resumable) instead of True (completed).
    - ``step_deadline_s``: arms a `StepWatchdog` around every step.
    - ``async_save``: checkpoint writes happen off the step loop's
      critical path (see CheckpointManager.async_save).
    """

    def __init__(self, net, checkpoint_dir: str,
                 checkpoint_frequency: int = 50, max_restarts: int = 3,
                 fault_injector: Optional[FaultInjector] = None,
                 use_orbax: Optional[bool] = None,
                 guard: Optional[TrainingGuard] = None,
                 preemption=None,
                 step_deadline_s: Optional[float] = None,
                 async_save: bool = False,
                 registry=None):
        self.net = net
        self.manager = CheckpointManager(checkpoint_dir,
                                         use_orbax=use_orbax,
                                         async_save=async_save,
                                         fault_injector=fault_injector,
                                         registry=registry)
        self.checkpoint_frequency = max(1, checkpoint_frequency)
        self.max_restarts = max_restarts
        self.fault_injector = fault_injector
        self.guard = guard
        if guard is not None and hasattr(net, "set_training_guard"):
            net.set_training_guard(guard)
        if preemption is True:
            preemption = PreemptionHandler(registry=registry).install()
        self.preemption: Optional[PreemptionHandler] = preemption
        self.step_deadline_s = step_deadline_s
        self._registry = registry
        self.restarts = 0              # cumulative (reporting)
        self.consecutive_failures = 0  # gates max_restarts
        self.preempted = False

    def _maybe_checkpoint(self) -> None:
        if self.net.iteration_count % self.checkpoint_frequency == 0:
            self.manager.save(self.net)

    def _stop_requested(self) -> bool:
        return (self.preemption is not None
                and self.preemption.stop_requested())

    def _checkpoint_and_yield(self) -> bool:
        """Preemption exit: persist a resumable checkpoint, flush the
        writer, report not-completed."""
        self.preempted = True
        self.manager.save(self.net)
        self.manager.wait()
        log.warning("preemption: checkpointed at iteration %d and "
                    "stopping (resumable — rerun fit to continue)",
                    self.net.iteration_count)
        return False

    def _recover(self, err: RuntimeError) -> None:
        """One failure: count it, restore the last good checkpoint,
        apply LR backoff on divergence rollbacks, or re-raise when the
        consecutive budget is exhausted."""
        self.restarts += 1
        self.consecutive_failures += 1
        if self.consecutive_failures > self.max_restarts:
            raise err
        log.warning(
            "step failed (%s); restoring last checkpoint "
            "(consecutive failure %d/%d, %d total)", err,
            self.consecutive_failures, self.max_restarts, self.restarts)
        if self.manager.restore(self.net) is None:
            log.warning("no checkpoint yet; retrying from current "
                        "params")
        if isinstance(err, DivergenceError) and self.guard is not None:
            self.guard.apply_lr_backoff(self.net)

    def fit(self, iterator, epochs: int = 1) -> bool:
        """Train; True when all epochs completed, False when a
        preemption stop was honored (checkpoint written; call fit
        again to resume — the iteration count continues)."""
        if not self.net._initialized:
            self.net.init()
        self.preempted = False
        restored = self.manager.restore(self.net)
        if restored is not None:
            log.info("resumed from checkpoint step %d", restored)
        watchdog = None
        if self.step_deadline_s is not None:
            watchdog = StepWatchdog(self.step_deadline_s,
                                    registry=self._registry).start()
        from deeplearning4j_tpu.nn.multilayer import _unpack_batch
        try:
            for _ in range(epochs):
                for batch in iterator:
                    feats, labs, fmask, lmask = _unpack_batch(batch)
                    it = self.net.iteration_count
                    if self.fault_injector is not None \
                            and self.fault_injector.check_preempt(it) \
                            and self.preemption is not None:
                        self.preemption.request_stop()
                    if self._stop_requested():
                        return self._checkpoint_and_yield()
                    while True:
                        try:
                            # per-attempt view: a NaN-poisoned batch
                            # must not stay poisoned across the retry
                            # after a rollback restore
                            step_feats = feats
                            if self.fault_injector is not None:
                                self.fault_injector.check(
                                    self.net.iteration_count)
                                if self.fault_injector.check_nan(
                                        self.net.iteration_count):
                                    import numpy as np
                                    step_feats = (np.asarray(feats)
                                                  * np.float32("nan"))
                            if watchdog is not None:
                                watchdog.arm(self.net.iteration_count)
                            self.net.fit(step_feats, labs,
                                         lmask if lmask is not None
                                         else fmask)
                            self.consecutive_failures = 0
                            break
                        except RuntimeError as e:
                            self._recover(e)
                        finally:
                            if watchdog is not None:
                                watchdog.disarm()
                    self._maybe_checkpoint()
                if hasattr(iterator, "reset"):
                    iterator.reset()
                if self._stop_requested():
                    return self._checkpoint_and_yield()
        finally:
            if watchdog is not None:
                watchdog.stop()
        self.manager.save(self.net)
        self.manager.wait()
        return True
