"""Failure detection + checkpoint-based recovery for training loops.

The reference has none of this in-tree (SURVEY.md §5.3: Spark mode
inherits RDD retry; a lost executor just loses one split). The
TPU-idiomatic equivalent named there — "checkpoint-based restart +
multi-host health via the coordination service" — is what this module
provides: a `FaultTolerantTrainer` that wraps any fit loop with
periodic checkpoints, detects step failures (device OOM, preempted
TPU grant, injected faults), restores the last good checkpoint, and
resumes; plus a `FaultInjector` for deterministic failure testing
(the fault-injection harness the reference also lacks).
"""
from __future__ import annotations

import logging
import time
from typing import Callable, Iterable, Optional

from deeplearning4j_tpu.util.checkpointing import CheckpointManager

log = logging.getLogger("deeplearning4j_tpu")


class TrainingFailure(RuntimeError):
    """Raised by fault injection; real device errors (XlaRuntimeError
    etc.) are caught by their base RuntimeError."""


class FaultInjector:
    """Deterministically fail chosen iterations (test harness).
    `persistent=True` keeps failing the same iteration on retry —
    models a hard fault (bad host, poisoned input) rather than a
    transient one."""

    def __init__(self, fail_at: Iterable[int] = (),
                 persistent: bool = False):
        self.fail_at = set(int(i) for i in fail_at)
        self.persistent = persistent
        self.injected = 0

    def check(self, iteration: int) -> None:
        if iteration in self.fail_at:
            if not self.persistent:
                self.fail_at.discard(iteration)
            self.injected += 1
            raise TrainingFailure(f"injected fault at iteration "
                                  f"{iteration}")


class ServingFaultInjector(FaultInjector):
    """Serving-side deterministic fault injection (the engine-hook
    extension of FaultInjector — serving/engine.py calls
    ``on_decode_step`` immediately before every compiled decode
    invocation).

    Knobs:
      - ``fail_at`` / ``persistent``: decode-step indices to fail. Step
        indices count COMPLETED decode steps — a failed attempt is
        retried at the same index, so a non-persistent fault vanishes on
        the first retry (transient) while ``persistent=True`` keeps
        failing the step through every retry (systemic hard fault; the
        engine's circuit breaker is what eventually reacts).
      - ``poison_requests``: request ids that fail EVERY batch
        containing them — the per-request hard fault. The engine
        responds by isolating the batch (solo re-runs) and quarantining
        exactly the poisoned requests.
      - ``delay_at``: ``{step: seconds}`` one-shot host-side stalls
        injected before the step launches — drives deadline-miss
        scheduling deterministically without real overload.
    """

    def __init__(self, fail_at: Iterable[int] = (),
                 persistent: bool = False,
                 poison_requests: Iterable[int] = (),
                 delay_at: Optional[dict] = None):
        super().__init__(fail_at, persistent=persistent)
        self.poison_requests = set(int(r) for r in poison_requests)
        self.delay_at = {int(k): float(v)
                         for k, v in (delay_at or {}).items()}
        self.delays_injected = 0

    def on_decode_step(self, step: int,
                       request_ids: Iterable[int] = ()) -> None:
        d = self.delay_at.pop(int(step), 0.0)
        if d > 0:
            self.delays_injected += 1
            time.sleep(d)
        bad = self.poison_requests.intersection(
            int(r) for r in request_ids)
        if bad:
            self.injected += 1
            raise TrainingFailure(
                f"poisoned request(s) {sorted(bad)} at decode step "
                f"{step}")
        self.check(int(step))


class FaultTolerantTrainer:
    """Run fit over an iterator with checkpoint/restore-based recovery.

    Each minibatch step is guarded; on failure the model is restored
    from the latest checkpoint and the epoch continues from the current
    batch (at-least-once batch semantics — same guarantee as the
    reference's Spark retry, which may also re-process a split).
    """

    def __init__(self, net, checkpoint_dir: str,
                 checkpoint_frequency: int = 50, max_restarts: int = 3,
                 fault_injector: Optional[FaultInjector] = None,
                 use_orbax: Optional[bool] = None):
        self.net = net
        self.manager = CheckpointManager(checkpoint_dir,
                                         use_orbax=use_orbax)
        self.checkpoint_frequency = max(1, checkpoint_frequency)
        self.max_restarts = max_restarts
        self.fault_injector = fault_injector
        self.restarts = 0

    def _maybe_checkpoint(self) -> None:
        if self.net.iteration_count % self.checkpoint_frequency == 0:
            self.manager.save(self.net)

    def fit(self, iterator, epochs: int = 1) -> None:
        if not self.net._initialized:
            self.net.init()
        restored = self.manager.restore(self.net)
        if restored is not None:
            log.info("resumed from checkpoint step %d", restored)
        from deeplearning4j_tpu.nn.multilayer import _unpack_batch
        for _ in range(epochs):
            for batch in iterator:
                feats, labs, fmask, lmask = _unpack_batch(batch)
                while True:
                    try:
                        if self.fault_injector is not None:
                            self.fault_injector.check(
                                self.net.iteration_count)
                        self.net.fit(feats, labs,
                                     lmask if lmask is not None else fmask)
                        break
                    except RuntimeError as e:
                        self.restarts += 1
                        if self.restarts > self.max_restarts:
                            raise
                        log.warning(
                            "step failed (%s); restoring last checkpoint "
                            "(restart %d/%d)", e, self.restarts,
                            self.max_restarts)
                        if self.manager.restore(self.net) is None:
                            log.warning("no checkpoint yet; retrying from "
                                        "current params")
                self._maybe_checkpoint()
            if hasattr(iterator, "reset"):
                iterator.reset()
        self.manager.save(self.net)
