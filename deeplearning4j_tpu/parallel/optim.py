"""Sharding-agnostic Adam for the scale paths.

One per-leaf update serves both distributed train steps: the
composite-parallel step (parallel/megatron.py — replica-local shards
inside `shard_map`) and the FSDP step (parallel/fsdp.py — GSPMD-sharded
leaves under `jit`). Elementwise math is sharding-transparent, so the
same function is correct in both regimes; keeping it in one place keeps
the two steps' optimizer semantics from drifting.

(The network-API updater semantics — LR policies, grad clipping, L1/L2
ordering mirroring the reference's `LayerUpdater.java:74-186` — live in
train/updaters.py; this module is the minimal optimizer for the
composite/FSDP transformer steps.)
"""
from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


class AdamState(NamedTuple):
    m: Any
    v: Any
    count: Array


def init_adam_state(params) -> AdamState:
    """Zeros shaped (and sharded) like the params: `jnp.zeros_like` on an
    already-placed tree inherits each leaf's sharding, so FSDP optimizer
    state is born sharded."""
    z = lambda: jax.tree_util.tree_map(  # noqa: E731
        lambda p: jnp.zeros_like(p), params)
    return AdamState(m=z(), v=z(), count=jnp.zeros((), jnp.int32))


def adam_update_tree(params, grads, m, v, t: Array, *,
                     learning_rate: float, b1: float, b2: float,
                     eps: float) -> Tuple[Any, Any, Any]:
    """Apply one Adam step leaf-wise; returns (params, m, v) trees.
    ``t`` is the 1-based float32 step count (for bias correction)."""
    def upd(p, g, m_, v_):
        m2 = b1 * m_ + (1 - b1) * g
        v2 = b2 * v_ + (1 - b2) * g * g
        mhat = m2 / (1 - jnp.power(b1, t))
        vhat = v2 / (1 - jnp.power(b2, t))
        return (p - learning_rate * mhat / (jnp.sqrt(vhat) + eps), m2, v2)

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(m)
    flat_v = treedef.flatten_up_to(v)
    new_p, new_m, new_v = [], [], []
    for pp, gg, mm, vv in zip(flat_p, flat_g, flat_m, flat_v):
        a, b, c = upd(pp, gg, mm, vv)
        new_p.append(a)
        new_m.append(b)
        new_v.append(c)
    unflatten = jax.tree_util.tree_unflatten
    return (unflatten(treedef, new_p), unflatten(treedef, new_m),
            unflatten(treedef, new_v))
