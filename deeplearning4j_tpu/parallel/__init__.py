"""Distributed training over a TPU device mesh.

The reference implements data parallelism three ways — ParallelWrapper
threads averaging params every N iterations
(deeplearning4j-scaleout/.../parallelism/ParallelWrapper.java:125,218), an
Aeron parameter server, and Spark parameter averaging
(dl4j-spark/.../ParameterAveragingTrainingMaster.java:858) — all host-staged
(SURVEY.md §2.6, §5.8). On TPU those collapse into ONE idiom: a sharded,
jitted train step whose gradient synchronization is an XLA `psum` riding ICI.
This package also provides the strategies the reference lacks — tensor,
pipeline, sequence/context (ring attention + Ulysses all-to-all), and
expert parallelism — as sharding policies over the same traced step.
"""
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh  # noqa: F401
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper  # noqa: F401

from deeplearning4j_tpu.parallel.fsdp import (  # noqa: F401
    init_fsdp_adam_state, make_fsdp_train_step, shard_params_fsdp)
from deeplearning4j_tpu.parallel.ring import ring_attention  # noqa: F401
from deeplearning4j_tpu.parallel.ulysses import ulysses_attention  # noqa: F401
from deeplearning4j_tpu.parallel.multihost import (initialize_multihost,
                                                   process_info,
                                                   MultiHostLauncher)
from deeplearning4j_tpu.parallel.failure import (FaultTolerantTrainer,
                                                 FaultInjector)
