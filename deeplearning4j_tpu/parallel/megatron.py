"""Composite-parallel transformer training: DP x TP x PP x SP x EP in one
compiled step.

NET-NEW vs the reference, whose only strategy is data parallelism by
host-staged parameter averaging (SURVEY.md §2.6); here every strategy is a
sharding of one traced program over the named mesh (parallel/mesh.py):

- data ('data'): batch sharded; gradient psum.
- tensor ('model'): megatron-style — attention heads and MLP hidden sharded;
  forward psum ("g" op) paired with an identity-forward/psum-backward "f" op
  at each parallel region's entry so residual-stream gradients stay exact.
- pipeline ('pipe'): blocks stacked [L] -> stages [S, L/S]; activations hop
  stages via ppermute; loss is computed on the last stage and psum-masked
  across the axis. Two microbatch schedules (``pipeline_schedule``):
  'gpipe' (default) — all-forward-then-all-backward, autodiff through the
  tick scan, activation memory O(M) microbatches deep; '1f1b' — explicit
  per-microbatch jax.vjp with an O(S)-deep input stash, forward and
  backward slots interleaved in one scanned round loop (see
  _value_and_grad_1f1b for the schedule math and the honest bubble
  accounting of a slot-synchronous SPMD 1F1B).
- sequence ('seq'): tokens sharded over time; cfg.seq_impl picks the
  strategy — 'ring' (parallel/ring.py: K/V blocks rotate via ppermute) or
  'ulysses' (parallel/ulysses.py: all_to_all head resharding).
- expert ('ep' rides the 'data' axis, Switch/GShard-style): experts sharded
  over 'data', tokens routed by all_to_all. n_experts % data-size == 0.

Gradient synchronization rule: a leaf's gradient is psum'd over exactly the
mesh axes it is replicated across among ('pipe','data','seq') — 'model' is
excluded because the f/g pairing already delivers full gradients on every
model rank.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map
except ImportError:          # jax<0.6: pre-promotion location
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig, chunked_cross_entropy)
from deeplearning4j_tpu.nn.layers.attention import layer_norm
from deeplearning4j_tpu.parallel.optim import (AdamState,  # noqa: F401
                                               adam_update_tree,
                                               init_adam_state)
from deeplearning4j_tpu.parallel.ring import ring_attention
from deeplearning4j_tpu.parallel.ulysses import ulysses_attention

Array = jax.Array


# ---------------------------------------------------------------------------
# megatron f op: identity forward, psum backward
# ---------------------------------------------------------------------------

def _f_sync(axis_name: str):
    """Megatron 'f': identity forward, psum backward — placed at a
    tensor-parallel region's ENTRY so the residual stream's cotangent is
    reassembled from the per-rank partial paths."""
    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        return (lax.psum(g, axis_name),)

    f.defvjp(fwd, bwd)
    return f


def _g_sync(axis_name: str):
    """Megatron 'g': psum forward, IDENTITY backward — a raw lax.psum is
    wrong here because its autodiff transpose is another psum, which
    double-counts the already-full cotangent on every rank."""
    @jax.custom_vjp
    def g(x):
        return lax.psum(x, axis_name)

    def fwd(x):
        return lax.psum(x, axis_name), None

    def bwd(_, ct):
        return (ct,)

    g.defvjp(fwd, bwd)
    return g


# ---------------------------------------------------------------------------
# parameter partition specs
# ---------------------------------------------------------------------------

def param_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpec pytree matching models/transformer.init_params."""
    blocks: Dict[str, P] = {
        "Wq": P("pipe", None, "model"), "Wk": P("pipe", None, "model"),
        "Wv": P("pipe", None, "model"), "Wo": P("pipe", "model", None),
        "ln1g": P("pipe", None), "ln1b": P("pipe", None),
        "ln2g": P("pipe", None), "ln2b": P("pipe", None),
    }
    if cfg.n_experts > 0:
        blocks["router"] = P("pipe", None, None)
        blocks["We1"] = P("pipe", "data", None, None)
        blocks["We2"] = P("pipe", "data", None, None)
    else:
        blocks["W1"] = P("pipe", None, "model")
        blocks["b1"] = P("pipe", "model")
        blocks["W2"] = P("pipe", "model", None)
        blocks["b2"] = P("pipe", None)
    return {"embed": P(), "pos": P(), "blocks": blocks,
            "lnfg": P(), "lnfb": P(), "Wout": P()}


def _grad_psum_axes(spec: P, mesh: Mesh) -> Tuple[str, ...]:
    used = {a for part in spec if part is not None
            for a in ((part,) if isinstance(part, str) else part)}
    return tuple(a for a in ("pipe", "data", "seq")
                 if a not in used and mesh.shape[a] > 1)


# ---------------------------------------------------------------------------
# sharded block forward (operates on LOCAL shards inside shard_map)
# ---------------------------------------------------------------------------

def _block_fwd_sharded(h: Array, p: Dict[str, Array],
                       cfg: TransformerConfig, mesh: Mesh) -> Array:
    tp = mesh.shape["model"]
    sp = mesh.shape["seq"]
    dp = mesh.shape["data"]
    d = cfg.d_model
    h_loc = cfg.n_heads // tp
    f_model = _f_sync("model")
    g_model = _g_sync("model")

    x = layer_norm(h, p["ln1g"], p["ln1b"], cfg.eps)
    x = f_model(x)

    def heads(y):
        return y.reshape(y.shape[0], y.shape[1], h_loc, cfg.d_head)

    q = heads(jnp.matmul(x, p["Wq"].astype(x.dtype)))
    k = heads(jnp.matmul(x, p["Wk"].astype(x.dtype)))
    v = heads(jnp.matmul(x, p["Wv"].astype(x.dtype)))
    if sp > 1:
        # seq_impl validated upfront by make_parallel_train_step
        if cfg.seq_impl == "ulysses":
            a = ulysses_attention(q, k, v, "seq", causal=True)
        else:
            a = ring_attention(q, k, v, "seq", causal=True)
    else:
        from deeplearning4j_tpu.nn.layers.attention import \
            dot_product_attention
        a = dot_product_attention(q, k, v, causal=True)
    a = a.reshape(a.shape[0], a.shape[1], h_loc * cfg.d_head)
    attn_out = jnp.matmul(a, p["Wo"].astype(a.dtype))
    attn_out = g_model(attn_out)
    h = h + attn_out

    x = layer_norm(h, p["ln2g"], p["ln2b"], cfg.eps)
    if cfg.n_experts > 0:
        h = h + _moe_sharded(x, p, cfg, dp)
    else:
        x = f_model(x)
        z = jax.nn.gelu(jnp.matmul(x, p["W1"].astype(x.dtype))
                        + p["b1"].astype(x.dtype))
        m = jnp.matmul(z, p["W2"].astype(z.dtype))
        m = g_model(m)
        h = h + m + p["b2"].astype(h.dtype)
    return h


def _moe_sharded(x: Array, p: Dict[str, Array], cfg: TransformerConfig,
                 dp: int) -> Array:
    """Expert-parallel top-1 MoE: experts sharded over 'data', tokens
    exchanged by all_to_all (Switch-style). Local x: [b, t, D]."""
    b, t, d = x.shape
    e = cfg.n_experts
    e_loc = e // dp
    xt = x.reshape(b * t, d)
    n = b * t
    logits = jnp.matmul(xt.astype(jnp.float32), p["router"])
    gates = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(gates, axis=-1)
    prob = jnp.take_along_axis(gates, expert[:, None], 1)[:, 0]
    cap = max(1, int(cfg.capacity_factor * n / e))
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0
    keep = (pos >= 0) & (pos < cap)
    posc = jnp.clip(pos, 0, cap - 1).astype(jnp.int32)
    disp = (jax.nn.one_hot(posc, cap, dtype=jnp.float32)
            * keep[..., None].astype(jnp.float32) * onehot[..., None])
    xin = jnp.einsum("nec,nd->ecd", disp, xt.astype(jnp.float32))  # [E,C,D]
    if dp > 1:
        # [E, C, D] -> [E/dp, dp*C, D]: each data rank keeps its experts'
        # tokens from every peer
        xin = lax.all_to_all(xin, "data", split_axis=0, concat_axis=1,
                             tiled=True)
    z = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xin, p["We1"]))
    out = jnp.einsum("ecf,efd->ecd", z, p["We2"])
    if dp > 1:
        out = lax.all_to_all(out, "data", split_axis=1, concat_axis=0,
                             tiled=True)                            # [E,C,D]
    comb = disp * prob[:, None, None]
    y = jnp.einsum("nec,ecd->nd", comb, out)
    return y.astype(x.dtype).reshape(b, t, d)


# ---------------------------------------------------------------------------
# GPipe pipeline over stacked local blocks
# ---------------------------------------------------------------------------

def _stage_fn(x: Array, blocks_local, cfg, mesh) -> Array:
    def body(h, p):
        return _block_fwd_sharded(h, p, cfg, mesh), None

    if getattr(cfg, "remat", False):
        # blockwise rematerialization under the scan (prevent_cse=False:
        # the loop structure already blocks the CSE the default guards)
        body = jax.checkpoint(body, prevent_cse=False)
    y, _ = lax.scan(body, x, blocks_local)
    return y


def _pipeline_apply(blocks_local, h_mb: Array, cfg, mesh) -> Array:
    """h_mb: [M, mb, tl, D] local microbatches -> outputs [M, mb, tl, D]
    (meaningful on the LAST pipe stage; other stages produce their own
    stage outputs, masked out by the caller)."""
    s = mesh.shape["pipe"]
    if s == 1:
        m_, mb, tl, d = h_mb.shape
        y = _stage_fn(h_mb.reshape(m_ * mb, tl, d), blocks_local, cfg, mesh)
        return y.reshape(m_, mb, tl, d)
    i = lax.axis_index("pipe")
    m_ = h_mb.shape[0]
    perm_fwd = [(j, j + 1) for j in range(s - 1)]
    from deeplearning4j_tpu.parallel.mesh import pcast_varying

    def vary(x):
        return pcast_varying(x, ("pipe", "data", "seq"))
    recv0 = vary(jnp.zeros_like(h_mb[0]))
    out0 = vary(jnp.zeros_like(h_mb))

    def tick_full(carry, t):
        recv, out_buf = carry
        x0 = lax.dynamic_index_in_dim(h_mb, jnp.clip(t, 0, m_ - 1), 0,
                                      keepdims=False)
        x = jnp.where(i == 0, x0, recv)
        y = _stage_fn(x, blocks_local, cfg, mesh)
        recv_new = lax.ppermute(y, "pipe", perm_fwd)
        store = jnp.clip(t - (s - 1), 0, m_ - 1)
        cur = lax.dynamic_index_in_dim(out_buf, store, 0, keepdims=False)
        upd = jnp.where(t >= s - 1, y, cur)
        out_buf = lax.dynamic_update_index_in_dim(out_buf, upd, store, 0)
        return (recv_new, out_buf), None

    (recv, out_buf), _ = lax.scan(tick_full, (recv0, out0),
                                  jnp.arange(m_ + s - 1))
    return out_buf


# ---------------------------------------------------------------------------
# 1F1B pipeline schedule (explicit per-microbatch vjp, O(S) activations)
# ---------------------------------------------------------------------------

def pipeline_bubble_fraction(schedule: str, n_stages: int,
                             n_microbatches: int) -> float:
    """Analytic pipeline-bubble fraction (idle slot share per stage).

    gpipe: the forward tick scan runs M+S-1 ticks for M useful forwards
    per stage (autodiff mirrors it in reverse) -> (S-1)/(M+S-1).
    1f1b (slot-synchronous, see _value_and_grad_1f1b): M+2(S-1) rounds,
    each carrying one F slot and one B slot, M of each useful ->
    2(S-1)/(M+2(S-1)). The 1f1b schedule trades a larger bubble at
    EQUAL M for activation memory independent of M — the point is that
    M can then grow (memory freed ~M/S-fold) until the bubble is
    smaller than any M the gpipe schedule can afford."""
    if n_stages <= 1:
        return 0.0
    s, m = n_stages, n_microbatches
    if schedule == "gpipe":
        return (s - 1) / (m + s - 1)
    if schedule == "1f1b":
        return 2 * (s - 1) / (m + 2 * (s - 1))
    raise ValueError(f"unknown pipeline schedule {schedule!r}")


def _value_and_grad_1f1b(params, tokens_loc, targets_loc,
                         cfg: TransformerConfig, mesh: Mesh, m_: int):
    """Loss + grads under a 1F1B-style pipeline schedule, computed with
    EXPLICIT per-microbatch vjp instead of autodiff through the GPipe
    tick scan.

    Schedule (stage i of S, round r of M+2(S-1); every round holds one
    forward slot and one backward slot, executed by every rank with
    validity masks — SPMD can't give ranks different control flow):

      forward of microbatch j at stage i  -> round i + j
      backward of microbatch j at stage i -> round 2(S-1) - i + j

    so the LAST stage runs F(j) and B(j) in the same round (the 1F1B
    signature move) and cotangents flow upstream one stage per round
    via reverse ppermute. In-flight forwards at stage i never exceed
    2(S-1-i)+1 microbatches, so the input stash is a fixed 2S-slot ring
    buffer — activation memory is O(S) and INDEPENDENT of M, vs the
    GPipe path whose scan residuals are O(M) deep. The backward slot
    re-runs the stage forward inside jax.vjp from the stashed input
    (stage-granular rematerialization — the same fwd+recompute+bwd
    FLOP count the remat'd GPipe path pays).

    Equality contract: loss and every grad leaf match the GPipe path
    (and therefore single-device training) to float tolerance — the
    per-microbatch loss head is scaled 1/global_count so summed
    microbatch cotangents reproduce the global-mean loss exactly
    (tests/test_megatron.py::test_1f1b_*).

    Role analog: net-new (SURVEY §5.7 — the reference has no pipeline
    parallelism); schedule per Narayanan et al.'s PipeDream-flush /
    Megatron-LM 1F1B, re-expressed as a masked SPMD round loop.
    """
    s = mesh.shape["pipe"]
    dp = mesh.shape["data"]
    sp_ = mesh.shape["seq"]
    dt = cfg.activation_dtype()
    b_loc, tl = tokens_loc.shape
    mb = b_loc // m_
    d = cfg.d_model
    i = lax.axis_index("pipe")
    toks_mb = tokens_loc.reshape(m_, mb, tl)
    tgts_mb = targets_loc.reshape(m_, mb, tl)
    count = b_loc * tl * dp * sp_
    seq_idx = lax.axis_index("seq").astype(jnp.int32)
    blocks = params["blocks"]
    ep_params = {"embed": params["embed"], "pos": params["pos"]}
    head_params = {"lnfg": params["lnfg"], "lnfb": params["lnfb"],
                   "Wout": params["Wout"]}

    def embed_one(ep, toks):
        pos = lax.dynamic_slice(ep["pos"], (seq_idx * tl, jnp.int32(0)),
                                (tl, d))
        return ep["embed"].astype(dt)[toks] + pos.astype(dt)[None]

    def head_loss_sum(hp, y, tgt):
        hf = layer_norm(y, hp["lnfg"], hp["lnfb"], cfg.eps)
        if cfg.xent_chunk > 0 and cfg.vocab_size > cfg.xent_chunk:
            return chunked_cross_entropy(hf, hp["Wout"], tgt,
                                         cfg.xent_chunk) * tgt.size
        logits = jnp.matmul(hf, hp["Wout"].astype(hf.dtype))
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return jnp.sum(-jnp.take_along_axis(
            logp, tgt[..., None].astype(jnp.int32), axis=-1)[..., 0])

    n_slots = 2 * s          # 2S-1 live ring slots + 1 trash slot
    perm_fwd = [(j, j + 1) for j in range(s - 1)]
    perm_bwd = [(j + 1, j) for j in range(s - 1)]
    is_last = i == s - 1
    t_total = m_ + 2 * (s - 1)

    g0 = jax.tree_util.tree_map(
        jnp.zeros_like, {"blocks": blocks, "head": head_params,
                         "ep": ep_params})
    carry0 = (jnp.zeros((mb, tl, d), dt),         # recv_f
              jnp.zeros((mb, tl, d), dt),         # recv_b (cotangent)
              jnp.zeros((n_slots, mb, tl, d), dt),
              g0, jnp.zeros((), jnp.float32))

    def round_body(carry, r):
        recv_f, recv_b, stash, gacc, loss_acc = carry
        # ---- forward slot: F(j_f) with j_f = r - i
        j_f = r - i
        vf = (j_f >= 0) & (j_f < m_)
        jf_c = jnp.clip(j_f, 0, m_ - 1)
        x0 = embed_one(ep_params, lax.dynamic_index_in_dim(
            toks_mb, jf_c, 0, keepdims=False))
        x_in = jnp.where(i == 0, x0, recv_f)
        y = _stage_fn(x_in, blocks, cfg, mesh)
        # invalid slots write to the trash slot so drain-phase garbage
        # can't clobber a stash entry whose backward is still pending
        slot = jnp.where(vf, jf_c % (n_slots - 1), n_slots - 1)
        stash = lax.dynamic_update_index_in_dim(stash, x_in, slot, 0)
        recv_f_new = lax.ppermute(y, "pipe", perm_fwd)

        # ---- backward slot: B(j_b) with j_b = r - 2(S-1) + i
        j_b = r - 2 * (s - 1) + i
        vb = (j_b >= 0) & (j_b < m_)
        jb_c = jnp.clip(j_b, 0, m_ - 1)
        x_s = lax.dynamic_index_in_dim(stash, jb_c % (n_slots - 1), 0,
                                       keepdims=False)
        toks_j = lax.dynamic_index_in_dim(toks_mb, jb_c, 0,
                                          keepdims=False)
        tgt_j = lax.dynamic_index_in_dim(tgts_mb, jb_c, 0,
                                         keepdims=False)

        def fb(x, blk, hp):
            yy = _stage_fn(x, blk, cfg, mesh)
            # every rank computes the head (SPMD-uniform, as the GPipe
            # path does); only the last stage's cotangent is nonzero
            return yy, head_loss_sum(hp, yy, tgt_j) / count

        (_, ls), pull = jax.vjp(fb, x_s, blocks, head_params)
        # zero cotangents make every invalid/masked grad exactly zero
        ct_y = jnp.where(vb & ~is_last, recv_b, 0).astype(dt)
        ct_l = jnp.where(vb & is_last, 1.0, 0.0).astype(jnp.float32)
        dx, dblk, dhp = pull((ct_y, ct_l))
        _, pull_e = jax.vjp(lambda ep: embed_one(ep, toks_j), ep_params)
        dep = pull_e(jnp.where(i == 0, dx, 0).astype(dt))[0]
        gacc = jax.tree_util.tree_map(
            lambda a, b: a + b, gacc,
            {"blocks": dblk, "head": dhp, "ep": dep})
        loss_acc = loss_acc + jnp.where(vb & is_last, ls, 0.0)
        recv_b_new = lax.ppermute(dx, "pipe", perm_bwd)
        return (recv_f_new, recv_b_new, stash, gacc, loss_acc), None

    (_, _, _, gacc, loss_acc), _ = lax.scan(
        round_body, carry0, jnp.arange(t_total, dtype=jnp.int32))
    loss = lax.psum(loss_acc, ("pipe", "data", "seq"))
    grads = {"embed": gacc["ep"]["embed"], "pos": gacc["ep"]["pos"],
             "blocks": gacc["blocks"], "lnfg": gacc["head"]["lnfg"],
             "lnfb": gacc["head"]["lnfb"],
             "Wout": gacc["head"]["Wout"]}
    return loss, grads


# ---------------------------------------------------------------------------
# the train step factory
# ---------------------------------------------------------------------------

def make_parallel_train_step(cfg: TransformerConfig, mesh: Mesh, *,
                             learning_rate: float = 1e-3,
                             n_microbatches: Optional[int] = None,
                             b1: float = 0.9, b2: float = 0.999,
                             eps: float = 1e-8,
                             pipeline_schedule: str = "gpipe"):
    """Build the jitted composite-parallel train step.

    Returns ``step(params, opt_state, tokens, targets) ->
    (params, opt_state, loss)``. ``tokens``/``targets`` are GLOBAL [B, T]
    int32 arrays (sharded on entry by the step's in_shardings).
    ``pipeline_schedule``: 'gpipe' (all-F-then-all-B, O(M) activation
    memory) or '1f1b' (interleaved, O(S) activation memory — see
    _value_and_grad_1f1b); identical losses and grads either way.
    """
    s = mesh.shape["pipe"]
    dp = mesh.shape["data"]
    sp = mesh.shape["seq"]
    tp = mesh.shape["model"]
    if mesh.shape.get("expert", 1) != 1:
        raise ValueError("expert parallelism rides the 'data' axis; use "
                         "expert=1 in the mesh (Switch-style EP)")
    if cfg.n_layers % s:
        raise ValueError("n_layers must divide by pipe size")
    if cfg.n_heads % tp or cfg.d_ff % tp:
        raise ValueError("n_heads and d_ff must divide by model size")
    if cfg.n_experts and cfg.n_experts % dp:
        raise ValueError("n_experts must divide by data size")
    if cfg.seq_impl not in ("ring", "ulysses"):
        raise ValueError(f"unknown seq_impl {cfg.seq_impl!r}: expected "
                         "'ring' or 'ulysses'")
    if cfg.seq_impl == "ulysses" and sp > 1 and (cfg.n_heads // tp) % sp:
        raise ValueError(
            f"seq_impl='ulysses' needs local heads (n_heads/tp = "
            f"{cfg.n_heads // tp}) divisible by seq size {sp}; use "
            "seq_impl='ring' (any head count) or change the mesh")
    if pipeline_schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline_schedule "
                         f"{pipeline_schedule!r}: expected 'gpipe' or "
                         "'1f1b'")
    m_ = n_microbatches or s
    specs = param_specs(cfg)
    use_1f1b = pipeline_schedule == "1f1b" and s > 1

    def local_forward_loss(params, tokens_loc, targets_loc):
        """Everything after sharding: local token block -> global mean
        loss (identical scalar on every device)."""
        dt = cfg.activation_dtype()
        b_loc, tl = tokens_loc.shape
        seq_idx = lax.axis_index("seq").astype(jnp.int32)
        pos = lax.dynamic_slice(params["pos"],
                                (seq_idx * tl, jnp.int32(0)),
                                (tl, cfg.d_model))
        h = params["embed"].astype(dt)[tokens_loc] + pos.astype(dt)[None]
        # microbatch split for the pipeline
        if b_loc % m_:
            raise ValueError(f"local batch {b_loc} not divisible by "
                             f"{m_} microbatches")
        mb = b_loc // m_
        h_mb = h.reshape(m_, mb, tl, cfg.d_model)
        out = _pipeline_apply(params["blocks"], h_mb, cfg, mesh)
        hf = out.reshape(b_loc, tl, cfg.d_model)
        hf = layer_norm(hf, params["lnfg"], params["lnfb"], cfg.eps)
        if cfg.xent_chunk > 0 and cfg.vocab_size > cfg.xent_chunk:
            # streaming vocab-panel loss on the LOCAL tokens (Wout is
            # replicated; each shard scans its own panels) — the same
            # real-vocab memory wall the single-chip loss_fn dodges,
            # models/transformer.chunked_cross_entropy
            local_sum = chunked_cross_entropy(
                hf, params["Wout"], targets_loc,
                cfg.xent_chunk) * (b_loc * tl)
        else:
            logits = jnp.matmul(hf, params["Wout"].astype(hf.dtype))
            logits = logits.astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(
                logp, targets_loc[..., None].astype(jnp.int32),
                axis=-1)[..., 0]
            local_sum = jnp.sum(nll)
        if s > 1:
            is_last = (lax.axis_index("pipe") == s - 1)
            local_sum = jnp.where(is_last, local_sum, 0.0)
        total = lax.psum(local_sum, ("pipe", "data", "seq"))
        count = b_loc * tl * dp * sp
        return total / count

    def sharded_step(params, opt_m, opt_v, count, tokens_loc, targets_loc):
        if use_1f1b:
            if tokens_loc.shape[0] % m_:
                raise ValueError(f"local batch {tokens_loc.shape[0]} "
                                 f"not divisible by {m_} microbatches")
            loss, grads = _value_and_grad_1f1b(params, tokens_loc,
                                               targets_loc, cfg, mesh, m_)
        else:
            loss, grads = jax.value_and_grad(
                lambda p: local_forward_loss(p, tokens_loc,
                                             targets_loc))(params)
        # sync gradients over the axes each leaf is replicated across
        grads = jax.tree_util.tree_map(
            lambda g, sp_: lax.psum(g, _grad_psum_axes(sp_, mesh))
            if _grad_psum_axes(sp_, mesh) else g,
            grads, specs)
        # adam on local shards (identical math on every replica)
        cnt = count + 1
        new_p, new_m, new_v = adam_update_tree(
            params, grads, opt_m, opt_v, cnt.astype(jnp.float32),
            learning_rate=learning_rate, b1=b1, b2=b2, eps=eps)
        return new_p, new_m, new_v, cnt, loss

    data_spec = P(("data",), ("seq",))
    # the replication-check kwarg was renamed check_rep -> check_vma
    # when the vma type system landed (jax 0.7)
    import inspect
    _chk = ("check_vma" if "check_vma"
            in inspect.signature(shard_map).parameters else "check_rep")
    smapped = shard_map(
        sharded_step, mesh=mesh,
        in_specs=(specs, specs, specs, P(), data_spec, data_spec),
        out_specs=(specs, specs, specs, P(), P()),
        **{_chk: False})

    def step(params, opt_state: AdamState, tokens, targets):
        p2, m2, v2, cnt, loss = smapped(params, opt_state.m, opt_state.v,
                                        opt_state.count, tokens, targets)
        return p2, AdamState(m2, v2, cnt), loss

    return jax.jit(step, donate_argnums=(0, 1))


def shard_params(params, cfg: TransformerConfig, mesh: Mesh,
                 specs=None):
    """Place a host/replicated param pytree onto the mesh per
    param_specs (or caller-supplied ``specs`` — e.g. the serving
    layout's MoE overrides)."""
    if specs is None:
        specs = param_specs(cfg)
    return jax.tree_util.tree_map(
        lambda p, sp_: jax.device_put(p, NamedSharding(mesh, sp_)),
        params, specs)
