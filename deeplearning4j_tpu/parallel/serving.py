"""Tensor+data-parallel KV-cache generation — sharded serving.

NET-NEW vs the reference (its serving story is single-process
`MultiLayerNetwork.output`/`rnnTimeStep`; SURVEY §5.7-5.8): the flagship
transformer's autoregressive decode runs SPMD over a `('data',
'model')` mesh. Megatron-style tensor parallelism splits the attention
heads and MLP hidden dim over 'model' (reusing parallel/megatron.py's
param_specs/shard_params layout, pipe=1), the batch splits over 'data',
and each device holds only its head-shard of the KV cache —
[L, B/dp, S, D/tp] in the flattened-head layout models/transformer.py
uses (round-3 decode tiling fix). Per decode step the only collective
is the attention/MLP output psum over 'model' (g-sync), after which
every model-rank holds identical full logits and samples the same
token from the same per-step key — no gather of the cache, ever.

Greedy (temperature <= 0) parallel decode equals single-chip
`models/transformer.generate` token-for-token (the equivalence test's
obligation, tests/test_parallel_serving.py).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from deeplearning4j_tpu.models.transformer import TransformerConfig
from deeplearning4j_tpu.nn.layers.attention import (dot_product_attention,
                                                    layer_norm)
from deeplearning4j_tpu.parallel.megatron import (_g_sync, param_specs,
                                                  shard_params)

Array = jax.Array


def _local_block_prefill(h, p, cfg: TransformerConfig, tp: int):
    """TP block forward over the full prompt, returning the block's
    LOCAL k/v rows (flattened local heads) for the cache.

    NOTE: this and _local_block_decode deliberately mirror
    models/transformer.block_forward/_block_decode and
    megatron._block_fwd_sharded with local head counts + the 'model'
    output psum; any change to the block math must land in all of
    them — tests/test_parallel_serving.py's token-for-token greedy
    equivalence is the guard that catches drift."""
    g_model = _g_sync("model")
    h_loc = cfg.n_heads // tp
    x = layer_norm(h, p["ln1g"], p["ln1b"], cfg.eps)

    def heads(y):
        return y.reshape(y.shape[0], y.shape[1], h_loc, cfg.d_head)

    q = heads(jnp.matmul(x, p["Wq"].astype(x.dtype)))
    k = heads(jnp.matmul(x, p["Wk"].astype(x.dtype)))
    v = heads(jnp.matmul(x, p["Wv"].astype(x.dtype)))
    a = dot_product_attention(q, k, v, causal=True)
    a = a.reshape(a.shape[0], a.shape[1], h_loc * cfg.d_head)
    h = h + g_model(jnp.matmul(a, p["Wo"].astype(a.dtype)))
    x = layer_norm(h, p["ln2g"], p["ln2b"], cfg.eps)
    z = jax.nn.gelu(jnp.matmul(x, p["W1"].astype(x.dtype))
                    + p["b1"].astype(x.dtype))
    m = g_model(jnp.matmul(z, p["W2"].astype(z.dtype)))
    h = h + m + p["b2"].astype(h.dtype)
    kf = k.reshape(k.shape[0], k.shape[1], h_loc * cfg.d_head)
    vf = v.reshape(v.shape[0], v.shape[1], h_loc * cfg.d_head)
    return h, (kf, vf)


def _local_block_decode(h, p, ck_all, cv_all, layer: int, pos,
                        cfg: TransformerConfig, tp: int):
    """One TP block, one new position, local-head cache update +
    attention over the local cache shard."""
    g_model = _g_sync("model")
    h_loc = cfg.n_heads // tp
    d_loc = h_loc * cfg.d_head
    x = layer_norm(h, p["ln1g"], p["ln1b"], cfg.eps)
    q = jnp.matmul(x, p["Wq"].astype(x.dtype)) \
        .reshape(x.shape[0], 1, h_loc, cfg.d_head)
    k = jnp.matmul(x, p["Wk"].astype(x.dtype))      # [B, 1, D_loc]
    v = jnp.matmul(x, p["Wv"].astype(x.dtype))
    z = jnp.asarray(0, pos.dtype)
    lz = jnp.asarray(layer, pos.dtype)
    ck_all = lax.dynamic_update_slice(
        ck_all, k[None].astype(ck_all.dtype), (lz, z, pos, z))
    cv_all = lax.dynamic_update_slice(
        cv_all, v[None].astype(cv_all.dtype), (lz, z, pos, z))
    # same split-K decode path as _block_decode (stacked local cache +
    # layer plane selected in the kernel's BlockSpec — prefix-bounded
    # HBM reads; jnp reference semantics off-TPU)
    from deeplearning4j_tpu.ops.flash_decode import decode_attention
    a = decode_attention(q[:, 0], ck_all, cv_all, pos,
                         n_heads=h_loc, layer=layer)    # [B, h_loc, Dh]
    h = h + g_model(jnp.matmul(a.reshape(a.shape[0], 1, d_loc),
                               p["Wo"].astype(h.dtype)))
    x = layer_norm(h, p["ln2g"], p["ln2b"], cfg.eps)
    z2 = jax.nn.gelu(jnp.matmul(x, p["W1"].astype(x.dtype))
                     + p["b1"].astype(x.dtype))
    m = g_model(jnp.matmul(z2, p["W2"].astype(z2.dtype)))
    h = h + m + p["b2"].astype(h.dtype)
    return h, ck_all, cv_all


def make_parallel_generate(cfg: TransformerConfig, mesh: Mesh,
                           max_new_tokens: int,
                           temperature: float = 0.0):
    """Compiled sharded generate: (params, prompt [B, T0], key) ->
    [B, T0 + max_new_tokens]. Params must be placed with
    `shard_serving_params`; batch shards over 'data', heads/MLP over
    'model'. MoE configs are out of scope (serving covers the dense
    flagship)."""
    if cfg.n_experts > 0:
        raise ValueError("parallel serving covers dense configs; "
                         "route MoE through the training mesh")
    tp = mesh.shape["model"]
    if cfg.n_heads % tp:
        raise ValueError(f"n_heads {cfg.n_heads} not divisible by "
                         f"model axis {tp}")
    for ax in ("pipe", "seq", "expert"):
        if mesh.shape.get(ax, 1) > 1:
            raise ValueError(
                f"serving mesh uses only ('data', 'model'); axis "
                f"'{ax}'={mesh.shape[ax]} would silently shard the "
                "stacked layers with no schedule to reassemble them")
    specs = param_specs(cfg)

    def run(params, prompt, key):
        dt = cfg.activation_dtype()
        b, t0 = prompt.shape
        if t0 + max_new_tokens > cfg.max_len:
            raise ValueError(
                f"generation length {t0 + max_new_tokens} exceeds "
                f"max_len={cfg.max_len}")
        # independent sampling noise per data shard (greedy ignores
        # the key; without the fold, equal prompts on different data
        # ranks would sample identical continuations)
        key = jax.random.fold_in(key, lax.axis_index("data"))
        h = (params["embed"].astype(dt)[prompt]
             + params["pos"].astype(dt)[:t0][None])

        def pf_body(h, p):
            return _local_block_prefill(h, p, cfg, tp)

        h, (ks, vs) = lax.scan(pf_body, h, params["blocks"])
        d_loc = (cfg.n_heads // tp) * cfg.d_head
        ck = jnp.zeros((cfg.n_layers, b, cfg.max_len, d_loc), dt)
        cv = jnp.zeros_like(ck)
        ck = ck.at[:, :, :t0].set(ks.astype(dt))
        cv = cv.at[:, :, :t0].set(vs.astype(dt))
        h = layer_norm(h, params["lnfg"], params["lnfb"], cfg.eps)
        logits = jnp.matmul(h[:, -1], params["Wout"].astype(h.dtype))
        pos0 = jnp.asarray(t0, jnp.int32)

        def sample(carry, k_step):
            ck, cv, pos, logits = carry
            if temperature <= 0:
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            else:
                tok = jax.random.categorical(
                    k_step, logits.astype(jnp.float32) / temperature,
                    axis=-1).astype(jnp.int32)
            emb = params["embed"].astype(dt)[tok]
            posv = lax.dynamic_slice_in_dim(params["pos"], pos, 1,
                                            axis=0).astype(dt)
            hh = (emb + posv)[:, None, :]
            for layer in range(cfg.n_layers):
                p_l = {kk: vv[layer]
                       for kk, vv in params["blocks"].items()}
                hh, ck, cv = _local_block_decode(hh, p_l, ck, cv,
                                                 layer, pos, cfg, tp)
            hh = layer_norm(hh, params["lnfg"], params["lnfb"],
                            cfg.eps)
            new_logits = jnp.matmul(hh[:, 0],
                                    params["Wout"].astype(hh.dtype))
            return (ck, cv, pos + 1, new_logits), tok

        keys = jax.random.split(key, max_new_tokens)
        _, toks = lax.scan(sample, (ck, cv, pos0, logits), keys)
        return jnp.concatenate([prompt, jnp.swapaxes(toks, 0, 1)],
                               axis=1)

    sharded = shard_map(run, mesh=mesh,
                        in_specs=(specs, P("data", None), P()),
                        out_specs=P("data", None), check_rep=False)
    return jax.jit(sharded)


def shard_serving_params(params, cfg: TransformerConfig, mesh: Mesh):
    """Place params for serving — same megatron layout (pipe=1 on a
    serving mesh, so the stacked [L, ...] blocks stay whole per
    device while heads/MLP split over 'model')."""
    return shard_params(params, cfg, mesh)
