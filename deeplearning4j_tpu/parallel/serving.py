"""Tensor+data-parallel KV-cache generation — sharded serving.

NET-NEW vs the reference (its serving story is single-process
`MultiLayerNetwork.output`/`rnnTimeStep`; SURVEY §5.7-5.8): the flagship
transformer's autoregressive decode runs SPMD over a `('data',
'model')` mesh. Megatron-style tensor parallelism splits the attention
heads and MLP hidden dim over 'model' (reusing parallel/megatron.py's
param_specs/shard_params layout, pipe=1), the batch splits over 'data',
and each device holds only its head-shard of the KV cache —
[L, B/dp, S, D/tp] in the flattened-head layout models/transformer.py
uses (round-3 decode tiling fix). Per decode step the only collective
is the attention/MLP output psum over 'model' (g-sync), after which
every model-rank holds identical full logits and samples the same
token from the same per-step key — no gather of the cache, ever.

MoE configs (n_experts > 0) serve via EXPERT-TENSOR parallelism
(VERDICT r3 #4): every rank holds all experts, but each expert's FFN
hidden dim is sharded over 'model' exactly like the dense MLP — the
right layout for serving-scale expert counts, where routing all-to-all
over a dedicated expert axis would add a collective per layer per
token for no memory win. Routing is computed per data shard, but the
capacity DROP decision is made against the GLOBAL token order (an
all_gather of per-expert counts over 'data' supplies each rank's
prefix offsets), so a token is dropped on the mesh iff single-chip
moe_mlp would drop it — without that, capacity binds differently at
B/dp tokens per rank and greedy decode diverges from the single-chip
reference.

Sampling carries the full single-chip surface — temperature, top-k,
nucleus (top-p) — via the SAME `_filter_logits` the single-chip scan
uses (r4 gap: serving silently sampled raw logits, VERDICT r4 weak #5).
On a TP-only mesh (dp=1) the per-step key derivation matches
single-chip `generate` exactly, so sampled decode is token-for-token
equivalent too, not just greedy; with dp>1 each data rank folds its
rank index into the key (equal prompts on different ranks must not
sample identical continuations). Equivalence tests:
tests/test_parallel_serving.py — greedy (dense AND MoE) + sampled
top-k/top-p.

CONTINUOUS BATCHING (ISSUE-4): `make_parallel_generate` fuses prefill
and the whole decode budget into one program — right for one batch run
to completion, wrong for mixed, streaming traffic (the engine would
re-run prefill over the grown sequence every chunk). The split surface
below serves the slotted engine instead:

- `init_slot_state(cfg, mesh, num_slots)` — a PERSISTENT pool of
  `num_slots` KV-cache rows ([L, Ns, S, D] sharded batch-over-'data',
  flattened heads over-'model') plus per-slot `pos`/`tok` vectors,
  resident on device across chunk calls.
- `make_continuous_prefill(cfg, mesh, bucket_len, num_slots, ...)` —
  one FIXED-SHAPE program per (bucket_len, num_slots) that prefills
  any subset of slots (`plen > 0` marks admissions) from prompts
  right-padded to the bucket, writes their cache rows, and samples
  each admitted slot's first token. Mixed prompt lengths share the
  program: causal attention means padded positions never influence
  valid ones, the last-token logits are gathered at `plen-1` per row,
  and (for MoE) padded tokens are masked out of expert dispatch.
- `make_continuous_decode(cfg, mesh, chunk, num_slots, ...)` — one
  fixed-shape program per (chunk, num_slots) advancing every active
  slot `chunk` tokens: per-slot cache-row writes at each slot's own
  `pos`, attention masked to each slot's filled prefix, slots
  deactivating themselves when their remaining-token budget hits 0
  (no wasted writes for finished slots). `active`/`rem` are data, not
  shapes — steady-state mixed traffic triggers ZERO recompiles.

Sampling key schedule for the split path: the token generated at
sequence index j uses fold_in(root_key, j) (per-slot vmapped), so a
retried, solo-isolated, or preempted-and-resumed request reproduces
its continuation exactly — the schedule depends on absolute position
only, never on slot placement or chunk boundaries. (This differs from
the fused path's chunk-shaped schedule; greedy decode is identical.)

CHUNKED PREFILL (ISSUE-10): `make_continuous_prefill` runs a whole
admission's prompt as ONE fused pass, so a single long prompt freezes
every co-resident decoding slot for the full prefill — the TPOT-p99
stall the engine's token-budget scheduler exists to bound.
`make_chunked_prefill` (contiguous pool) and
`make_paged_chunked_prefill` (paged pool) instead advance any subset
of MID-PREFILL slots by up to `chunk_len` prompt tokens per call:
ONE fixed-shape program per (chunk_len, num_slots[, page geometry])
whose per-slot resume position (`start`), valid-token count (`clen`,
partial chunks allowed — the scheduler spends its budget to the
token), and final-chunk flag (`last`) are all runtime data. Each call
writes the chunk's K/V rows at absolute positions start+t and attends
two pieces — the already-written cached prefix masked to s < start,
plus causal float self-attention within the chunk — which is exactly
the paged prefix-hit resume path generalized to ARBITRARY chunk
boundaries (start no longer has to be a prefix-cache page boundary).
When `last` is set the call samples the slot's first generated token
at sequence index start+clen through the same position-keyed schedule
one-shot prefill uses, so chunked prefill is TOKEN-EXACT vs one-shot:
chunk 1's causal self-attention reproduces the one-shot math for its
positions, and every later chunk reads back the identical cached rows
chunk k-1 wrote (float KV bit-for-bit; int8 KV re-reads the prefix
through its quantization exactly as decode does — the same envelope
the paged prefix-hit path documents). tests/test_serving_chunked.py
holds the float/int8, fresh/prefix-hit, greedy/sampled proofs.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   _filter_logits,
                                                   sample_at_positions)
from deeplearning4j_tpu.nn.layers.attention import (dot_product_attention,
                                                    layer_norm)
from deeplearning4j_tpu.parallel.megatron import (_g_sync, param_specs,
                                                  shard_params)

Array = jax.Array


def _local_moe_mlp(x2, p, cfg: TransformerConfig, dp: int, valid=None):
    """Top-1 MoE on this data shard's tokens x2 [N_loc, D] with
    model-sharded expert FFNs (We1 [E, D, F/tp], We2 [E, F/tp, D]) —
    returns the PARTIAL output (caller psums over 'model').

    Mirrors models/transformer.moe_mlp token for token: the capacity
    cap uses the GLOBAL token count (dp * N_loc) and the keep decision
    uses each token's GLOBAL dispatch position — local cumsum plus a
    prefix of lower ranks' per-expert counts (all_gather over 'data').
    Local buffer slots then only need to be collision-free, so kept
    tokens re-rank locally; dispatch/combine read the same slots, so
    the combined output is exactly the single-chip one for every kept
    token and 0 for dropped ones.

    ``valid`` ([N_loc] bool, continuous-batching bucket prefill): pad
    tokens are masked out of dispatch so they can never claim expert
    capacity from real tokens. The cap itself stays computed from the
    PADDED token count (it sizes static buffers), so a bucket-padded
    MoE prefill can drop fewer tokens than an exact-length run —
    documented divergence, docs/serving.md."""
    n_loc = x2.shape[0]
    e = cfg.n_experts
    logits = jnp.matmul(x2.astype(jnp.float32), p["router"])
    gates = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(gates, axis=-1)
    prob = jnp.take_along_axis(gates, expert[:, None], 1)[:, 0]
    cap = max(1, int(cfg.capacity_factor * n_loc * dp / e))
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)       # [N, E]
    if valid is not None:
        onehot = onehot * valid.astype(jnp.float32)[:, None]
    counts = jnp.sum(onehot, axis=0)                            # [E]
    all_counts = lax.all_gather(counts, "data")                 # [dp, E]
    r = lax.axis_index("data")
    prefix = jnp.sum(
        jnp.where(jnp.arange(dp)[:, None] < r, all_counts, 0.0),
        axis=0)                                                 # [E]
    pos_g = (jnp.cumsum(onehot, axis=0) + prefix[None, :]) * onehot \
        - 1.0
    keep = (pos_g >= 0) & (pos_g < cap)
    keep_oh = onehot * keep.astype(jnp.float32)
    cap_loc = max(1, min(cap, n_loc))
    pos_l = jnp.cumsum(keep_oh, axis=0) * keep_oh - 1.0
    posc = jnp.clip(pos_l, 0, cap_loc - 1).astype(jnp.int32)
    disp = (jax.nn.one_hot(posc, cap_loc, dtype=jnp.float32)
            * keep_oh[..., None])                               # [N,E,C]
    xin = jnp.einsum("nec,nd->ecd", disp, x2.astype(jnp.float32))
    # .astype(f32): identity on float trees, on-the-fly dequantization
    # on quantized ones (quant/core.QuantizedTensor)
    z = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xin,
                               p["We1"].astype(jnp.float32)))
    out = jnp.einsum("ecf,efd->ecd", z,
                     p["We2"].astype(jnp.float32))  # partial over tp
    comb = disp * prob[:, None, None]
    return jnp.einsum("nec,ecd->nd", comb, out).astype(x2.dtype)


def _local_mlp(h, x, p, cfg: TransformerConfig, dp: int, g_model,
               valid=None):
    """Shared MLP tail for prefill/decode blocks: dense TP or MoE
    expert-tensor-parallel, partial-output psum'd over 'model'.
    ``valid`` ([B, T] bool) masks pad tokens out of MoE dispatch."""
    if cfg.n_experts > 0:
        b, t, d = x.shape
        y = _local_moe_mlp(x.reshape(b * t, d), p, cfg, dp,
                           valid=None if valid is None
                           else valid.reshape(b * t))
        return h + g_model(y.reshape(b, t, d))
    z = jax.nn.gelu(jnp.matmul(x, p["W1"].astype(x.dtype))
                    + p["b1"].astype(x.dtype))
    m = g_model(jnp.matmul(z, p["W2"].astype(z.dtype)))
    return h + m + p["b2"].astype(h.dtype)


def _local_block_prefill(h, p, cfg: TransformerConfig, tp: int,
                         dp: int, valid=None):
    """TP block forward over the full prompt, returning the block's
    LOCAL k/v rows (flattened local heads) for the cache.

    ``valid`` ([B, T] bool) marks real (non-pad) tokens in a bucket-
    padded continuous-batching prefill; causal attention already keeps
    pad positions (always to the RIGHT of valid ones) from influencing
    valid outputs, so the mask is only consumed by MoE dispatch.

    NOTE: this and _local_block_decode deliberately mirror
    models/transformer.block_forward/_block_decode and
    megatron._block_fwd_sharded with local head counts + the 'model'
    output psum; any change to the block math must land in all of
    them — tests/test_parallel_serving.py's token-for-token greedy
    equivalence is the guard that catches drift."""
    g_model = _g_sync("model")
    h_loc = cfg.n_heads // tp
    x = layer_norm(h, p["ln1g"], p["ln1b"], cfg.eps)

    def heads(y):
        return y.reshape(y.shape[0], y.shape[1], h_loc, cfg.d_head)

    q = heads(jnp.matmul(x, p["Wq"].astype(x.dtype)))
    k = heads(jnp.matmul(x, p["Wk"].astype(x.dtype)))
    v = heads(jnp.matmul(x, p["Wv"].astype(x.dtype)))
    a = dot_product_attention(q, k, v, causal=True)
    a = a.reshape(a.shape[0], a.shape[1], h_loc * cfg.d_head)
    h = h + g_model(jnp.matmul(a, p["Wo"].astype(a.dtype)))
    x = layer_norm(h, p["ln2g"], p["ln2b"], cfg.eps)
    h = _local_mlp(h, x, p, cfg, dp, g_model, valid=valid)
    kf = k.reshape(k.shape[0], k.shape[1], h_loc * cfg.d_head)
    vf = v.reshape(v.shape[0], v.shape[1], h_loc * cfg.d_head)
    return h, (kf, vf)


def _local_block_decode(h, p, ck_all, cv_all, layer: int, pos,
                        cfg: TransformerConfig, tp: int, dp: int):
    """One TP block, one new position, local-head cache update +
    attention over the local cache shard."""
    g_model = _g_sync("model")
    h_loc = cfg.n_heads // tp
    d_loc = h_loc * cfg.d_head
    x = layer_norm(h, p["ln1g"], p["ln1b"], cfg.eps)
    q = jnp.matmul(x, p["Wq"].astype(x.dtype)) \
        .reshape(x.shape[0], 1, h_loc, cfg.d_head)
    k = jnp.matmul(x, p["Wk"].astype(x.dtype))      # [B, 1, D_loc]
    v = jnp.matmul(x, p["Wv"].astype(x.dtype))
    z = jnp.asarray(0, pos.dtype)
    lz = jnp.asarray(layer, pos.dtype)
    ck_all = lax.dynamic_update_slice(
        ck_all, k[None].astype(ck_all.dtype), (lz, z, pos, z))
    cv_all = lax.dynamic_update_slice(
        cv_all, v[None].astype(cv_all.dtype), (lz, z, pos, z))
    # same split-K decode path as _block_decode (stacked local cache +
    # layer plane selected in the kernel's BlockSpec — prefix-bounded
    # HBM reads; jnp reference semantics off-TPU)
    from deeplearning4j_tpu.ops.flash_decode import decode_attention
    a = decode_attention(q[:, 0], ck_all, cv_all, pos,
                         n_heads=h_loc, layer=layer)    # [B, h_loc, Dh]
    h = h + g_model(jnp.matmul(a.reshape(a.shape[0], 1, d_loc),
                               p["Wo"].astype(h.dtype)))
    x = layer_norm(h, p["ln2g"], p["ln2b"], cfg.eps)
    h = _local_mlp(h, x, p, cfg, dp, g_model)
    return h, ck_all, cv_all


def make_parallel_generate(cfg: TransformerConfig, mesh: Mesh,
                           max_new_tokens: int,
                           temperature: float = 0.0,
                           top_k: int = 0, top_p: float = 1.0,
                           quantized=None):
    """Compiled sharded generate: (params, prompt [B, T0], key) ->
    [B, T0 + max_new_tokens]. Params must be placed with
    `shard_serving_params`; batch shards over 'data', heads/MLP over
    'model'. MoE configs serve with experts replicated and each
    expert's FFN hidden sharded over 'model' (module docstring).
    temperature<=0 is greedy; top_k/top_p apply the single-chip
    `_filter_logits` semantics (after temperature, before the
    categorical draw) — logits are replicated across 'model' ranks,
    so every rank filters and samples identically.

    ``quantized`` ("int8"/"fp8"): params are a
    `quant.model.quantize_params` tree placed with
    `shard_quantized_serving_params`; the decode math is unchanged —
    every weight use dequantizes on the fly via `.astype`."""
    tp, dp = _check_serving_mesh(cfg, mesh, top_k, top_p)
    quantized, _ = _resolve_quant(quantized, None)
    specs = _serving_specs(cfg, quantized)

    def run(params, prompt, key):
        dt = cfg.activation_dtype()
        b, t0 = prompt.shape
        if t0 + max_new_tokens > cfg.max_len:
            raise ValueError(
                f"generation length {t0 + max_new_tokens} exceeds "
                f"max_len={cfg.max_len}")
        # independent sampling noise per data shard (greedy ignores
        # the key; without the fold, equal prompts on different data
        # ranks would sample identical continuations). dp=1 skips the
        # fold so the key schedule matches single-chip generate
        # bit-for-bit — the sampled-path equivalence test's obligation.
        if dp > 1:
            key = jax.random.fold_in(key, lax.axis_index("data"))
        h = (params["embed"].astype(dt)[prompt]
             + params["pos"].astype(dt)[:t0][None])

        def pf_body(h, p):
            return _local_block_prefill(h, p, cfg, tp, dp)

        h, (ks, vs) = lax.scan(pf_body, h, params["blocks"])
        d_loc = (cfg.n_heads // tp) * cfg.d_head
        cdt = cfg.cache_jnp_dtype()
        ck = jnp.zeros((cfg.n_layers, b, cfg.max_len, d_loc), cdt)
        cv = jnp.zeros_like(ck)
        ck = ck.at[:, :, :t0].set(ks.astype(cdt))
        cv = cv.at[:, :, :t0].set(vs.astype(cdt))
        h = layer_norm(h, params["lnfg"], params["lnfb"], cfg.eps)
        logits = jnp.matmul(h[:, -1], params["Wout"].astype(h.dtype))
        pos0 = jnp.asarray(t0, jnp.int32)

        def sample(carry, i):
            ck, cv, pos, logits = carry
            if temperature <= 0:
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            else:
                # per-step fold, not pre-split xs — same rationale as
                # models/transformer._generate_jit (greedy traces no
                # threefry work); same _filter_logits so `generate` ->
                # `make_parallel_generate` keeps sampling semantics
                filt = _filter_logits(
                    logits.astype(jnp.float32) / temperature,
                    top_k, top_p)
                tok = jax.random.categorical(
                    jax.random.fold_in(key, i), filt,
                    axis=-1).astype(jnp.int32)
            emb = params["embed"].astype(dt)[tok]
            posv = lax.dynamic_slice_in_dim(params["pos"], pos, 1,
                                            axis=0).astype(dt)
            hh = (emb + posv)[:, None, :]
            for layer in range(cfg.n_layers):
                p_l = {kk: vv[layer]
                       for kk, vv in params["blocks"].items()}
                hh, ck, cv = _local_block_decode(hh, p_l, ck, cv,
                                                 layer, pos, cfg, tp,
                                                 dp)
            hh = layer_norm(hh, params["lnfg"], params["lnfb"],
                            cfg.eps)
            new_logits = jnp.matmul(hh[:, 0],
                                    params["Wout"].astype(hh.dtype))
            return (ck, cv, pos + 1, new_logits), tok

        _, toks = lax.scan(sample, (ck, cv, pos0, logits),
                           jnp.arange(max_new_tokens, dtype=jnp.int32))
        return jnp.concatenate([prompt, jnp.swapaxes(toks, 0, 1)],
                               axis=1)

    sharded = shard_map(run, mesh=mesh,
                        in_specs=(specs, P("data", None), P()),
                        out_specs=P("data", None), check_rep=True)
    return jax.jit(sharded)


def _check_serving_mesh(cfg: TransformerConfig, mesh: Mesh,
                        top_k: int, top_p: float):
    """Shared validation for every serving program factory. Returns
    (tp, dp)."""
    tp = mesh.shape["model"]
    dp = mesh.shape["data"]
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    if cfg.n_heads % tp:
        raise ValueError(f"n_heads {cfg.n_heads} not divisible by "
                         f"model axis {tp}")
    if cfg.d_ff % tp:
        raise ValueError(f"d_ff {cfg.d_ff} not divisible by "
                         f"model axis {tp}")
    for ax in ("pipe", "seq", "expert"):
        if mesh.shape.get(ax, 1) > 1:
            raise ValueError(
                f"serving mesh uses only ('data', 'model'); axis "
                f"'{ax}'={mesh.shape[ax]} would silently shard the "
                "stacked layers with no schedule to reassemble them")
    return tp, dp


# ---------------------------------------------------------------------------
# continuous batching: persistent slot pool + prefill/decode split
# ---------------------------------------------------------------------------

_SLOT_CACHE_SPEC = P(None, "data", None, "model")   # [L, Ns, S, D]
_SLOT_VEC_SPEC = P("data")                          # per-slot scalars
# quantized-KV per-row scales [L, Ns, S, tp]: the trailing axis holds
# each model-rank's independent scale for its D_loc head shard (local
# view [L, ns, S, 1]) — see quant/kv.py for the layout rationale
_SLOT_SCALE_SPEC = P(None, "data", None, "model")


def _resolve_quant(quantized, kv_mode):
    """Normalize the two quantization knobs through
    `quant.core.resolve_mode` (fp8 falls back to int8 off-TPU) without
    importing quant at module load."""
    if quantized is None and kv_mode is None:
        return None, None
    from deeplearning4j_tpu.quant.core import resolve_mode
    return resolve_mode(quantized), resolve_mode(kv_mode)


def _serving_specs(cfg: TransformerConfig, quantized):
    """Param in_specs/placement tree: the serving layout, run through
    `quant.model.quantize_specs` when the tree is quantized (values
    keep the float spec, scales drop sharding on their size-1 axis)."""
    specs = serving_param_specs(cfg)
    if quantized:
        from deeplearning4j_tpu.quant.model import quantize_specs
        specs = quantize_specs(specs, mode=quantized)
    return specs


def _sample_slots(logits, posidx, key, dp: int, temperature: float,
                  top_k: int, top_p: float):
    """Per-slot sampling on [Ns, V] logits: the token generated at
    sequence index ``posidx[i]`` draws from fold_in(key, posidx[i]) —
    position-keyed, slot-placement-independent, so retries, solo
    isolation, preempt-resume, AND speculative verification reproduce
    the same continuation (models/transformer.sample_at_positions owns
    the core; this wrapper adds the data-rank key fold). Greedy
    (temperature<=0) ignores the key entirely."""
    if temperature > 0 and dp > 1:
        key = jax.random.fold_in(key, lax.axis_index("data"))
    return sample_at_positions(logits, posidx, key, temperature,
                               top_k, top_p)


# constrained-decoding runtime operands (ISSUE-20): every masked
# program variant takes five extra operands AFTER its regular runtime
# vectors — callow [C, V] bool + ctrans [C, V] int32 (the engine's
# ConstraintTable, replicated), cstate [Ns] int32 (each slot's global
# DFA state, chained call-to-call), cseed [Ns] bool + cseedval [Ns]
# int32 (host seat-time reseeds) — and returns the advanced cstate as
# one extra LAST output. Mask contents, transitions, and states are
# pure runtime data: the [C, V] table shape is fixed per engine, so
# the compiled-program set stays closed (zero steady-state recompiles).
_CTAB_SPEC = P(None, None)


def _c_start(cstate, cseed, cseedval):
    """Seed-or-carry: slots the host just (re)seated read their seeded
    DFA state (0 = the unconstrained all-allow row); everyone else
    carries the device-chained state."""
    return jnp.where(cseed, cseedval, cstate)


def _mask_allow(logits, allow):
    """Additive grammar fence before sampling: disallowed vocab
    entries drop to NEG_INF, allowed entries add 0.0 — an all-allow
    row (unconstrained slots / terminal states) is numerically inert,
    so co-resident unconstrained slots sample the same tokens a
    maskless program would."""
    from deeplearning4j_tpu.ops.flash_decode import NEG_INF
    return logits + jnp.where(allow, jnp.asarray(0.0, logits.dtype),
                              jnp.asarray(NEG_INF, logits.dtype))


def _local_block_decode_slotted(h, p, ck_all, cv_all, layer: int, pos,
                                act, cfg: TransformerConfig, tp: int,
                                dp: int):
    """One TP block, one new position PER SLOT: h [Ns, 1, D], stacked
    caches [L, Ns, S, D_loc], pos [Ns] (each slot's own filled length),
    act [Ns] (inactive slots neither write their cache row nor advance).
    The K/V row write is a per-slot scatter at (layer, slot, pos[slot]);
    attention masks each slot to its own filled prefix 0..pos[slot] —
    the per-slot generalization of _local_block_decode, sharing
    `ops/flash_decode.decode_attention` (vector-pos form) with the
    fused path so the slotted decode rides the same tuned primitive:
    jnp reference semantics off-TPU (token-identical to the fused
    path), the split-K kernel with per-slot DMA bounds on it."""
    from deeplearning4j_tpu.ops.flash_decode import decode_attention
    g_model = _g_sync("model")
    h_loc = cfg.n_heads // tp
    d_loc = h_loc * cfg.d_head
    ns = h.shape[0]
    s_max = ck_all.shape[2]
    x = layer_norm(h, p["ln1g"], p["ln1b"], cfg.eps)
    q = jnp.matmul(x[:, 0], p["Wq"].astype(x.dtype)) \
        .reshape(ns, h_loc, cfg.d_head)
    k = jnp.matmul(x[:, 0], p["Wk"].astype(x.dtype))      # [Ns, D_loc]
    v = jnp.matmul(x[:, 0], p["Wv"].astype(x.dtype))
    rows = jnp.arange(ns)
    wp = jnp.clip(pos, 0, s_max - 1)
    # masked in-place row write: inactive slots re-write their current
    # row with itself (scatter shape stays static; no branches)
    k_wr = jnp.where(act[:, None], k.astype(ck_all.dtype),
                     ck_all[layer, rows, wp])
    v_wr = jnp.where(act[:, None], v.astype(cv_all.dtype),
                     cv_all[layer, rows, wp])
    ck_all = ck_all.at[layer, rows, wp].set(k_wr)
    cv_all = cv_all.at[layer, rows, wp].set(v_wr)
    a = decode_attention(q, ck_all, cv_all, wp, n_heads=h_loc,
                         layer=layer)                    # [Ns, hl, Dh]
    h = h + g_model(jnp.matmul(a.reshape(ns, 1, d_loc),
                               p["Wo"].astype(h.dtype)))
    x = layer_norm(h, p["ln2g"], p["ln2b"], cfg.eps)
    h = _local_mlp(h, x, p, cfg, dp, g_model)
    return h, ck_all, cv_all


def _local_block_decode_slotted_q(h, p, ck_all, cv_all, ksc, vsc,
                                  layer: int, pos, act,
                                  cfg: TransformerConfig, tp: int,
                                  dp: int, kv_mode: str):
    """Quantized-KV variant of _local_block_decode_slotted: the new
    K/V row is quantized ON WRITE (per-row absmax — quant/kv.py) into
    the int8/fp8 caches, with its float32 scale written to the
    parallel [L, Ns, S, 1]-local scale planes. The attention consumer
    never rebuilds a dequantized cache: the K scale folds into the
    score row (``(q·k_int)·kscale_s``) and the V scale into the
    probability row (``(p·vscale_s)·v_int``) — algebraically the
    dequantized attention, touching [Ns, S] scale vectors instead of
    [Ns, S, D] panels. The fold now lives in
    `ops/flash_decode.decode_attention(k_scale=, v_scale=)` — one
    primitive for float, quantized, slotted, paged, and speculative-
    verify decode — with identical numerics (same NEG_INF mask, f32
    softmax, scale-before-1/sqrt(d) multiplication order)."""
    from deeplearning4j_tpu.ops.flash_decode import decode_attention
    from deeplearning4j_tpu.quant.kv import quantize_rows
    g_model = _g_sync("model")
    h_loc = cfg.n_heads // tp
    d_loc = h_loc * cfg.d_head
    ns = h.shape[0]
    s_max = ck_all.shape[2]
    x = layer_norm(h, p["ln1g"], p["ln1b"], cfg.eps)
    q = jnp.matmul(x[:, 0], p["Wq"].astype(x.dtype)) \
        .reshape(ns, h_loc, cfg.d_head)
    k = jnp.matmul(x[:, 0], p["Wk"].astype(x.dtype))      # [Ns, D_loc]
    v = jnp.matmul(x[:, 0], p["Wv"].astype(x.dtype))
    rows = jnp.arange(ns)
    wp = jnp.clip(pos, 0, s_max - 1)
    kq, ksr = quantize_rows(k, kv_mode)
    vq, vsr = quantize_rows(v, kv_mode)
    # masked in-place row+scale writes (same static-scatter trick as
    # the float path: inactive slots rewrite their current row/scale)
    k_wr = jnp.where(act[:, None], kq, ck_all[layer, rows, wp])
    v_wr = jnp.where(act[:, None], vq, cv_all[layer, rows, wp])
    ks_wr = jnp.where(act, ksr, ksc[layer, rows, wp, 0])
    vs_wr = jnp.where(act, vsr, vsc[layer, rows, wp, 0])
    ck_all = ck_all.at[layer, rows, wp].set(k_wr)
    cv_all = cv_all.at[layer, rows, wp].set(v_wr)
    ksc = ksc.at[layer, rows, wp, 0].set(ks_wr)
    vsc = vsc.at[layer, rows, wp, 0].set(vs_wr)
    a = decode_attention(q, ck_all, cv_all, wp, n_heads=h_loc,
                         layer=layer, k_scale=ksc[layer, :, :, 0],
                         v_scale=vsc[layer, :, :, 0])
    h = h + g_model(jnp.matmul(a.reshape(ns, 1, d_loc),
                               p["Wo"].astype(h.dtype)))
    x = layer_norm(h, p["ln2g"], p["ln2b"], cfg.eps)
    h = _local_mlp(h, x, p, cfg, dp, g_model)
    return h, ck_all, cv_all, ksc, vsc


def init_slot_state(cfg: TransformerConfig, mesh: Mesh, num_slots: int,
                    kv_mode=None, cache_dtype=None):
    """Allocate the persistent slot-pool state (ck, cv, pos, tok) on
    the serving mesh: KV caches [L, Ns, S, D] (slot axis over 'data',
    flattened heads over 'model' — models/transformer.slot_cache_shape)
    plus per-slot position and last-token vectors. These arrays live
    on device for the engine's lifetime; every prefill/decode program
    consumes and returns them functionally, so a failed call leaves
    the pool bit-identical (retry/isolation need no repair).

    ``kv_mode`` ("int8"/"fp8") switches to the QUANTIZED pool —
    `quant.kv.init_quant_slot_state`'s 6-tuple (ck, cv, kscale,
    vscale, pos, tok) consumed by the ``kv_mode=...`` program
    variants. ``cache_dtype`` (jnp dtype) overrides `cfg.cache_dtype`
    for the float pool (bf16 caches under f32 activations)."""
    from jax.sharding import NamedSharding

    from deeplearning4j_tpu.models.transformer import slot_cache_shape
    _, kv_mode = _resolve_quant(None, kv_mode)
    if kv_mode is not None:
        from deeplearning4j_tpu.quant.kv import init_quant_slot_state
        return init_quant_slot_state(cfg, mesh, num_slots, kv_mode)
    dp = mesh.shape["data"]
    if num_slots % dp:
        raise ValueError(f"num_slots {num_slots} not divisible by "
                         f"data axis {dp}")
    dt = (cache_dtype if cache_dtype is not None
          else cfg.cache_jnp_dtype())
    shape = slot_cache_shape(cfg, num_slots)
    kv_sh = NamedSharding(mesh, _SLOT_CACHE_SPEC)
    vec_sh = NamedSharding(mesh, _SLOT_VEC_SPEC)
    ck = jax.device_put(jnp.zeros(shape, dt), kv_sh)
    cv = jax.device_put(jnp.zeros(shape, dt), kv_sh)
    pos = jax.device_put(jnp.zeros((num_slots,), jnp.int32), vec_sh)
    tok = jax.device_put(jnp.zeros((num_slots,), jnp.int32), vec_sh)
    return ck, cv, pos, tok


def make_continuous_prefill(cfg: TransformerConfig, mesh: Mesh,
                            bucket_len: int, num_slots: int,
                            temperature: float = 0.0,
                            top_k: int = 0, top_p: float = 1.0,
                            quantized=None, kv_mode=None,
                            constrain: bool = False):
    """Compiled slot-pool prefill: (params, ck, cv, pos, tok,
    prompts [Ns, Tb], plen [Ns], key) -> (ck, cv, pos, tok,
    first [Ns]).

    ``constrain=True`` (ISSUE-20) inserts the five constraint operands
    before ``key`` and appends the advanced DFA-state vector as the
    last output: the admitted slot's first token samples under its
    seeded state's allow row and advances the state through it.

    Slots with plen[i] > 0 are ADMISSIONS: their prompt (right-padded
    to the Tb bucket) is prefilled in one batched pass, their cache
    rows [0, plen) are written (pad rows land too but sit beyond pos
    and are overwritten before ever being attended), pos[i] <- plen[i],
    and the slot's first generated token is sampled from the logits at
    row plen[i]-1 (returned in ``first``; -1 for non-admitted slots).
    Slots with plen[i] == 0 pass through untouched — so one fixed
    (bucket_len, num_slots) geometry serves every admission pattern
    with zero recompiles.

    ``quantized`` ("int8"/"fp8") marks the params as a quantized tree
    (specs adapt; math is unchanged via on-the-fly dequant).
    ``kv_mode`` switches to the QUANTIZED slot pool: the state grows
    per-row scale planes — (params, ck, cv, kscale, vscale, pos, tok,
    prompts, plen, key) -> (ck, cv, kscale, vscale, pos, tok, first)
    — and prefilled K/V rows are quantized on write (quant/kv.py)."""
    tp, dp = _check_serving_mesh(cfg, mesh, top_k, top_p)
    quantized, kv_mode = _resolve_quant(quantized, kv_mode)
    if num_slots % dp:
        raise ValueError(f"num_slots {num_slots} not divisible by "
                         f"data axis {dp}")
    if not 0 < bucket_len <= cfg.max_len:
        raise ValueError(f"bucket_len {bucket_len} out of "
                         f"(0, {cfg.max_len}]")
    specs = _serving_specs(cfg, quantized)

    def compute(params, prompts, plen, key, allow=None):
        """Shared prefill math: block scan + first-token sampling.
        Returns (admit, ks, vs, first, pos_new-ready pieces)."""
        dt = cfg.activation_dtype()
        ns, tb = prompts.shape
        admit = plen > 0
        h = (params["embed"].astype(dt)[prompts]
             + params["pos"].astype(dt)[:tb][None])
        valid = (jnp.arange(tb)[None, :] < plen[:, None]) \
            if cfg.n_experts > 0 else None

        def pf_body(hh, p):
            return _local_block_prefill(hh, p, cfg, tp, dp, valid=valid)

        h, (ks, vs) = lax.scan(pf_body, h, params["blocks"])
        h = layer_norm(h, params["lnfg"], params["lnfb"], cfg.eps)
        last = h[jnp.arange(ns), jnp.clip(plen - 1, 0, tb - 1)]
        logits = jnp.matmul(last, params["Wout"].astype(last.dtype))
        if allow is not None:
            logits = _mask_allow(logits, allow)
        first = _sample_slots(logits, plen, key, dp, temperature,
                              top_k, top_p)
        return admit, tb, ks, vs, first

    def finish(admit, first, plen, pos, tok):
        pos = jnp.where(admit, plen.astype(pos.dtype), pos)
        tok = jnp.where(admit, first, tok)
        return pos, tok, jnp.where(admit, first,
                                   jnp.asarray(-1, jnp.int32))

    if kv_mode is None:
        def base(params, ck, cv, pos, tok, prompts, plen, key,
                 callow=None, ctrans=None, ds0=None):
            admit, tb, ks, vs, first = compute(
                params, prompts, plen, key,
                allow=None if callow is None else callow[ds0])
            keep = admit[None, :, None, None]
            ck = ck.at[:, :, :tb, :].set(
                jnp.where(keep, ks.astype(ck.dtype), ck[:, :, :tb, :]))
            cv = cv.at[:, :, :tb, :].set(
                jnp.where(keep, vs.astype(cv.dtype), cv[:, :, :tb, :]))
            pos, tok, first = finish(admit, first, plen, pos, tok)
            if callow is None:
                return ck, cv, pos, tok, first
            ds = jnp.where(admit,
                           ctrans[ds0, jnp.maximum(first, 0)], ds0)
            return ck, cv, pos, tok, first, ds

        if constrain:
            def run(params, ck, cv, pos, tok, prompts, plen, callow,
                    ctrans, cstate, cseed, cseedval, key):
                return base(params, ck, cv, pos, tok, prompts, plen,
                            key, callow, ctrans,
                            _c_start(cstate, cseed, cseedval))

            in_specs = (specs, _SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        P("data", None), _SLOT_VEC_SPEC, _CTAB_SPEC,
                        _CTAB_SPEC, _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, P())
            out_specs = (_SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC)
        else:
            run = base
            in_specs = (specs, _SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        P("data", None), _SLOT_VEC_SPEC, P())
            out_specs = (_SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         _SLOT_VEC_SPEC)
    else:
        def base(params, ck, cv, ksc, vsc, pos, tok, prompts, plen,
                 key, callow=None, ctrans=None, ds0=None):
            from deeplearning4j_tpu.quant.kv import quantize_rows
            admit, tb, ks, vs, first = compute(
                params, prompts, plen, key,
                allow=None if callow is None else callow[ds0])
            kq, ksr = quantize_rows(ks, kv_mode)   # [L, Ns, Tb, D_loc]
            vq, vsr = quantize_rows(vs, kv_mode)
            keep = admit[None, :, None, None]
            keep3 = admit[None, :, None]
            ck = ck.at[:, :, :tb, :].set(
                jnp.where(keep, kq, ck[:, :, :tb, :]))
            cv = cv.at[:, :, :tb, :].set(
                jnp.where(keep, vq, cv[:, :, :tb, :]))
            ksc = ksc.at[:, :, :tb, 0].set(
                jnp.where(keep3, ksr, ksc[:, :, :tb, 0]))
            vsc = vsc.at[:, :, :tb, 0].set(
                jnp.where(keep3, vsr, vsc[:, :, :tb, 0]))
            pos, tok, first = finish(admit, first, plen, pos, tok)
            if callow is None:
                return ck, cv, ksc, vsc, pos, tok, first
            ds = jnp.where(admit,
                           ctrans[ds0, jnp.maximum(first, 0)], ds0)
            return ck, cv, ksc, vsc, pos, tok, first, ds

        if constrain:
            def run(params, ck, cv, ksc, vsc, pos, tok, prompts, plen,
                    callow, ctrans, cstate, cseed, cseedval, key):
                return base(params, ck, cv, ksc, vsc, pos, tok,
                            prompts, plen, key, callow, ctrans,
                            _c_start(cstate, cseed, cseedval))

            in_specs = (specs, _SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                        _SLOT_SCALE_SPEC, _SLOT_SCALE_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        P("data", None), _SLOT_VEC_SPEC, _CTAB_SPEC,
                        _CTAB_SPEC, _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, P())
            out_specs = (_SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                         _SLOT_SCALE_SPEC, _SLOT_SCALE_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC)
        else:
            run = base
            in_specs = (specs, _SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                        _SLOT_SCALE_SPEC, _SLOT_SCALE_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        P("data", None), _SLOT_VEC_SPEC, P())
            out_specs = (_SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                         _SLOT_SCALE_SPEC, _SLOT_SCALE_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         _SLOT_VEC_SPEC)

    sharded = shard_map(run, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=True)
    return jax.jit(sharded)


def make_continuous_decode(cfg: TransformerConfig, mesh: Mesh,
                           chunk: int, num_slots: int,
                           temperature: float = 0.0,
                           top_k: int = 0, top_p: float = 1.0,
                           quantized=None, kv_mode=None,
                           constrain: bool = False):
    """Compiled slot-pool decode chunk: (params, ck, cv, pos, tok,
    active [Ns] bool, rem [Ns] int32, key) -> (ck, cv, pos, tok,
    toks [Ns, chunk]).

    ``constrain=True`` (ISSUE-20): five constraint operands before
    ``key``, the chained DFA-state vector appended as the last output;
    each scanned step gathers its slot's allow row, masks the logits
    before sampling, and advances the state through the sampled
    token — mask and transitions are runtime data, the program is one
    more fixed geometry.

    Advances every active slot up to ``chunk`` tokens from its own
    position: each scanned step embeds the slot's pending token at its
    own pos, writes its K/V cache row in place, attends only the
    slot's filled prefix, and samples the next token. A slot whose
    remaining budget (``rem``) hits 0 deactivates itself mid-chunk —
    no further writes, pos frozen, emitted tokens -1 — so per-slot
    budgets never overrun the cache and finished slots stop burning
    writes. active/rem/pos are runtime DATA: one compiled program per
    (chunk, num_slots) geometry covers all traffic.

    ``quantized`` ("int8"/"fp8") marks the params as a quantized tree;
    ``kv_mode`` switches to the quantized slot pool — the state grows
    per-row scale planes ((params, ck, cv, kscale, vscale, pos, tok,
    active, rem, key) -> (..., toks)) and the per-step K/V row is
    quantized on write (_local_block_decode_slotted_q)."""
    tp, dp = _check_serving_mesh(cfg, mesh, top_k, top_p)
    quantized, kv_mode = _resolve_quant(quantized, kv_mode)
    if num_slots % dp:
        raise ValueError(f"num_slots {num_slots} not divisible by "
                         f"data axis {dp}")
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    specs = _serving_specs(cfg, quantized)

    def sample_and_advance(params, h, act, pos, tok, rem, key,
                           ds=None, callow=None, ctrans=None):
        h = layer_norm(h, params["lnfg"], params["lnfb"], cfg.eps)
        logits = jnp.matmul(h[:, 0], params["Wout"].astype(h.dtype))
        if callow is not None:
            logits = _mask_allow(logits, callow[ds])
        nxt = _sample_slots(logits, pos + 1, key, dp, temperature,
                            top_k, top_p)
        if callow is not None:
            ds = jnp.where(act, ctrans[ds, nxt], ds)
        tok = jnp.where(act, nxt, tok)
        emit = jnp.where(act, nxt, jnp.asarray(-1, jnp.int32))
        pos = jnp.where(act, pos + 1, pos)
        rem = jnp.where(act, rem - 1, rem)
        return pos, tok, rem, emit, ds

    def embed_step(params, pos, tok):
        dt = cfg.activation_dtype()
        emb = params["embed"].astype(dt)[tok]
        pv = params["pos"].astype(dt)[
            jnp.clip(pos, 0, cfg.max_len - 1)]
        return (emb + pv)[:, None, :]

    if kv_mode is None:
        if constrain:
            def run(params, ck, cv, pos, tok, active, rem, callow,
                    ctrans, cstate, cseed, cseedval, key):
                def step(carry, _):
                    ck, cv, pos, tok, rem, ds = carry
                    act = active & (rem > 0)
                    h = embed_step(params, pos, tok)
                    for layer in range(cfg.n_layers):
                        p_l = {kk: vv[layer]
                               for kk, vv in params["blocks"].items()}
                        h, ck, cv = _local_block_decode_slotted(
                            h, p_l, ck, cv, layer, pos, act, cfg, tp,
                            dp)
                    pos, tok, rem, emit, ds = sample_and_advance(
                        params, h, act, pos, tok, rem, key, ds,
                        callow, ctrans)
                    return (ck, cv, pos, tok, rem, ds), emit

                ds0 = _c_start(cstate, cseed, cseedval)
                (ck, cv, pos, tok, _, ds), toks = lax.scan(
                    step, (ck, cv, pos, tok, rem, ds0), None,
                    length=chunk)
                return (ck, cv, pos, tok, jnp.swapaxes(toks, 0, 1),
                        ds)

            in_specs = (specs, _SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC, _CTAB_SPEC,
                        _CTAB_SPEC, _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, P())
            out_specs = (_SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         P("data", None), _SLOT_VEC_SPEC)
        else:
            def run(params, ck, cv, pos, tok, active, rem, key):
                def step(carry, _):
                    ck, cv, pos, tok, rem = carry
                    act = active & (rem > 0)
                    h = embed_step(params, pos, tok)
                    for layer in range(cfg.n_layers):
                        p_l = {kk: vv[layer]
                               for kk, vv in params["blocks"].items()}
                        h, ck, cv = _local_block_decode_slotted(
                            h, p_l, ck, cv, layer, pos, act, cfg, tp,
                            dp)
                    pos, tok, rem, emit, _ = sample_and_advance(
                        params, h, act, pos, tok, rem, key)
                    return (ck, cv, pos, tok, rem), emit

                (ck, cv, pos, tok, _), toks = lax.scan(
                    step, (ck, cv, pos, tok, rem), None, length=chunk)
                return ck, cv, pos, tok, jnp.swapaxes(toks, 0, 1)

            in_specs = (specs, _SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC, P())
            out_specs = (_SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         P("data", None))
    else:
        if constrain:
            def run(params, ck, cv, ksc, vsc, pos, tok, active, rem,
                    callow, ctrans, cstate, cseed, cseedval, key):
                def step(carry, _):
                    ck, cv, ksc, vsc, pos, tok, rem, ds = carry
                    act = active & (rem > 0)
                    h = embed_step(params, pos, tok)
                    for layer in range(cfg.n_layers):
                        p_l = {kk: vv[layer]
                               for kk, vv in params["blocks"].items()}
                        h, ck, cv, ksc, vsc = \
                            _local_block_decode_slotted_q(
                                h, p_l, ck, cv, ksc, vsc, layer, pos,
                                act, cfg, tp, dp, kv_mode)
                    pos, tok, rem, emit, ds = sample_and_advance(
                        params, h, act, pos, tok, rem, key, ds,
                        callow, ctrans)
                    return (ck, cv, ksc, vsc, pos, tok, rem, ds), emit

                ds0 = _c_start(cstate, cseed, cseedval)
                (ck, cv, ksc, vsc, pos, tok, _, ds), toks = lax.scan(
                    step, (ck, cv, ksc, vsc, pos, tok, rem, ds0),
                    None, length=chunk)
                return (ck, cv, ksc, vsc, pos, tok,
                        jnp.swapaxes(toks, 0, 1), ds)

            in_specs = (specs, _SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                        _SLOT_SCALE_SPEC, _SLOT_SCALE_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC, _CTAB_SPEC,
                        _CTAB_SPEC, _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, P())
            out_specs = (_SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                         _SLOT_SCALE_SPEC, _SLOT_SCALE_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         P("data", None), _SLOT_VEC_SPEC)
        else:
            def run(params, ck, cv, ksc, vsc, pos, tok, active, rem,
                    key):
                def step(carry, _):
                    ck, cv, ksc, vsc, pos, tok, rem = carry
                    act = active & (rem > 0)
                    h = embed_step(params, pos, tok)
                    for layer in range(cfg.n_layers):
                        p_l = {kk: vv[layer]
                               for kk, vv in params["blocks"].items()}
                        h, ck, cv, ksc, vsc = \
                            _local_block_decode_slotted_q(
                                h, p_l, ck, cv, ksc, vsc, layer, pos,
                                act, cfg, tp, dp, kv_mode)
                    pos, tok, rem, emit, _ = sample_and_advance(
                        params, h, act, pos, tok, rem, key)
                    return (ck, cv, ksc, vsc, pos, tok, rem), emit

                (ck, cv, ksc, vsc, pos, tok, _), toks = lax.scan(
                    step, (ck, cv, ksc, vsc, pos, tok, rem), None,
                    length=chunk)
                return (ck, cv, ksc, vsc, pos, tok,
                        jnp.swapaxes(toks, 0, 1))

            in_specs = (specs, _SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                        _SLOT_SCALE_SPEC, _SLOT_SCALE_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC, P())
            out_specs = (_SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                         _SLOT_SCALE_SPEC, _SLOT_SCALE_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         P("data", None))

    sharded = shard_map(run, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=True)
    return jax.jit(sharded)


def make_chunked_prefill(cfg: TransformerConfig, mesh: Mesh,
                         chunk_len: int, num_slots: int,
                         temperature: float = 0.0, top_k: int = 0,
                         top_p: float = 1.0, quantized=None,
                         kv_mode=None, constrain: bool = False):
    """Compiled CHUNKED admission prefill over the contiguous slot
    pool: (params, ck, cv, pos, tok, toks [Ns, C], clen [Ns],
    start [Ns], last [Ns] bool, key) -> (ck, cv, pos, tok,
    first [Ns]).

    Advances every slot with clen[i] > 0 by its next clen (<= C)
    prompt tokens: ``toks[i, :clen[i]]`` is the slice
    prompt[start[i] : start[i]+clen[i]] of the slot's committed
    prefix, its K/V rows are written at absolute positions start+t,
    and pos[i] <- start[i]+clen[i]. Attention per chunk query t is
    TWO-PIECE — the slot's already-written cache rows masked to
    s < start (exact zeros on the first chunk) plus causal float
    self-attention within the chunk, one softmax over the
    concatenated scores — the paged prefix-hit resume generalized to
    arbitrary chunk boundaries on the contiguous pool, reproducing
    `_local_block_prefill`'s numerics when the chunks are replayed in
    order. Slots with last[i] set additionally sample their first
    generated token at sequence index start+clen (the same
    position-keyed schedule one-shot prefill uses) into ``tok`` and
    ``first``; mid-prompt chunks leave ``tok`` untouched and report
    first = -1. start/clen/last are runtime DATA: one compiled
    program per (chunk_len, num_slots) geometry serves every resume
    position and partial-chunk budget with zero recompiles.

    ``quantized``/``kv_mode`` follow make_continuous_prefill: the
    quantized pool grows scale planes ((params, ck, cv, kscale,
    vscale, pos, tok, toks, clen, start, last, key) -> (..., first))
    and chunk rows quantize on write while the chunk still attends
    itself in float (the cached prefix re-reads through its
    quantization — the int8 decode envelope).

    ``constrain=True`` (ISSUE-20): five constraint operands before
    ``key``, the DFA-state vector appended last; only a FINAL chunk
    (last[i]) samples, so only final chunks mask and advance —
    mid-prompt chunks carry the seeded state unchanged."""
    from deeplearning4j_tpu.ops.flash_decode import NEG_INF
    tp, dp = _check_serving_mesh(cfg, mesh, top_k, top_p)
    quantized, kv_mode = _resolve_quant(quantized, kv_mode)
    if num_slots % dp:
        raise ValueError(f"num_slots {num_slots} not divisible by "
                         f"data axis {dp}")
    if not 0 < chunk_len <= cfg.max_len:
        raise ValueError(f"chunk_len {chunk_len} out of "
                         f"(0, {cfg.max_len}]")
    specs = _serving_specs(cfg, quantized)
    h_loc = cfg.n_heads // tp
    d_loc = h_loc * cfg.d_head
    scale = cfg.d_head ** -0.5

    def body(params, ck, cv, ksc, vsc, toks, clen, start, key,
             allow=None):
        dt = cfg.activation_dtype()
        acc = jnp.promote_types(dt, jnp.float32)
        ns, c = toks.shape
        s_max = ck.shape[2]
        adv = clen > 0
        absp = start[:, None] + jnp.arange(c)[None, :]     # [ns, C]
        valid = jnp.arange(c)[None, :] < clen[:, None]
        rows = jnp.arange(ns)[:, None]
        wp_g = jnp.clip(absp, 0, s_max - 1)   # in-bounds gather index
        pe = params["pos"].astype(dt)[jnp.clip(absp, 0,
                                               cfg.max_len - 1)]
        h = params["embed"].astype(dt)[toks] + pe
        mvalid = valid if cfg.n_experts > 0 else None
        causal = (jnp.arange(c)[None, :]
                  <= jnp.arange(c)[:, None])               # [C, C]
        pmask = (jnp.arange(s_max)[None, None, None, :]
                 < start[:, None, None, None])             # [ns,1,1,S]
        for layer in range(cfg.n_layers):
            p = {kk: vv[layer] for kk, vv in params["blocks"].items()}
            x = layer_norm(h, p["ln1g"], p["ln1b"], cfg.eps)
            q = jnp.matmul(x, p["Wq"].astype(x.dtype)) \
                .reshape(ns, c, h_loc, cfg.d_head)
            k = jnp.matmul(x, p["Wk"].astype(x.dtype))     # [ns,C,Dl]
            v = jnp.matmul(x, p["Wv"].astype(x.dtype))
            # write the chunk's rows at their absolute positions:
            # invalid (pad) entries rewrite their current row with
            # itself (the static-scatter trick) and positions past the
            # pool drop — per-row indices are distinct, so there is no
            # duplicate-index hazard on live rows
            if kv_mode is None:
                k_wr = jnp.where(valid[..., None], k.astype(ck.dtype),
                                 ck[layer][rows, wp_g])
                v_wr = jnp.where(valid[..., None], v.astype(cv.dtype),
                                 cv[layer][rows, wp_g])
                ck = ck.at[layer, rows, absp].set(k_wr, mode="drop")
                cv = cv.at[layer, rows, absp].set(v_wr, mode="drop")
            else:
                from deeplearning4j_tpu.quant.kv import quantize_rows
                kq, ksr = quantize_rows(k, kv_mode)
                vq, vsr = quantize_rows(v, kv_mode)
                k_wr = jnp.where(valid[..., None], kq,
                                 ck[layer][rows, wp_g])
                v_wr = jnp.where(valid[..., None], vq,
                                 cv[layer][rows, wp_g])
                ks_wr = jnp.where(valid, ksr,
                                  ksc[layer][rows, wp_g, 0])
                vs_wr = jnp.where(valid, vsr,
                                  vsc[layer][rows, wp_g, 0])
                ck = ck.at[layer, rows, absp].set(k_wr, mode="drop")
                cv = cv.at[layer, rows, absp].set(v_wr, mode="drop")
                ksc = ksc.at[layer, rows, absp, 0].set(ks_wr,
                                                       mode="drop")
                vsc = vsc.at[layer, rows, absp, 0].set(vs_wr,
                                                       mode="drop")
            kv4 = k.reshape(ns, c, h_loc, cfg.d_head)
            vv4 = v.reshape(ns, c, h_loc, cfg.d_head)
            # piece 2: float causal self-attention within the chunk —
            # bitwise dot_product_attention(q, k, v, causal=True)
            sc2 = jnp.einsum("bthd,bshd->bhts", q, kv4,
                             preferred_element_type=acc) * scale
            sc2 = jnp.where(causal[None, None], sc2, NEG_INF)
            # piece 1: the slot's cached prefix, masked to s < start
            # (fully masked — exact zeros — on the first chunk)
            if kv_mode is None:
                kh = ck[layer].reshape(ns, s_max, h_loc, cfg.d_head)
                vh = cv[layer].reshape(ns, s_max, h_loc, cfg.d_head)
                sc1 = jnp.einsum("bthd,bshd->bhts", q, kh,
                                 preferred_element_type=acc) * scale
            else:
                kh = ck[layer].astype(jnp.float32) \
                    .reshape(ns, s_max, h_loc, cfg.d_head)
                vh = cv[layer].astype(jnp.float32) \
                    .reshape(ns, s_max, h_loc, cfg.d_head)
                ksg = ksc[layer, :, :, 0]                  # [ns, S]
                vsg = vsc[layer, :, :, 0]
                sc1 = jnp.einsum("bthd,bshd->bhts",
                                 q.astype(jnp.float32), kh) \
                    * ksg[:, None, None, :] * scale
            sc1 = jnp.where(pmask, sc1, NEG_INF)
            # one softmax over [prefix | chunk] keys (logical order
            # preserved), then the two value pieces recombine — the
            # make_paged_prefill recombination on the contiguous pool
            w = jax.nn.softmax(
                jnp.concatenate([sc1.astype(acc), sc2], axis=-1),
                axis=-1)
            w1, w2 = w[..., :s_max], w[..., s_max:]
            if kv_mode is None:
                a1 = jnp.einsum("bhts,bshd->bthd",
                                w1.astype(vh.dtype), vh)
            else:
                a1 = jnp.einsum("bhts,bshd->bthd",
                                w1 * vsg[:, None, None, :], vh) \
                    .astype(v.dtype)
            a2 = jnp.einsum("bhts,bshd->bthd", w2.astype(v.dtype),
                            vv4)
            a = (a1 + a2).reshape(ns, c, d_loc)
            h = h + _g_sync("model")(
                jnp.matmul(a, p["Wo"].astype(a.dtype)))
            x = layer_norm(h, p["ln2g"], p["ln2b"], cfg.eps)
            h = _local_mlp(h, x, p, cfg, dp, _g_sync("model"),
                           valid=mvalid)
        h = layer_norm(h, params["lnfg"], params["lnfb"], cfg.eps)
        lastrow = h[jnp.arange(ns), jnp.clip(clen - 1, 0, c - 1)]
        logits = jnp.matmul(lastrow, params["Wout"].astype(
            lastrow.dtype))
        plen = start + clen
        if allow is not None:
            logits = _mask_allow(logits, allow)
        first = _sample_slots(logits, plen, key, dp, temperature,
                              top_k, top_p)
        return adv, plen, first, ck, cv, ksc, vsc

    def finish(adv, lastf, plen, first, pos, tok):
        take = adv & lastf
        pos = jnp.where(adv, plen.astype(pos.dtype), pos)
        tok = jnp.where(take, first, tok)
        return pos, tok, jnp.where(take, first,
                                   jnp.asarray(-1, jnp.int32))

    def c_advance(take, ds0, ctrans, first):
        """Final-chunk DFA advance: only slots that SAMPLED (take)
        step their state through the first generated token;
        mid-prompt chunks carry the seeded state forward."""
        return jnp.where(take, ctrans[ds0, jnp.maximum(first, 0)],
                         ds0)

    if kv_mode is None:
        if constrain:
            def run(params, ck, cv, pos, tok, toks, clen, start, last,
                    callow, ctrans, cstate, cseed, cseedval, key):
                ds0 = _c_start(cstate, cseed, cseedval)
                adv, plen, first, ck, cv, _, _ = body(
                    params, ck, cv, None, None, toks, clen, start,
                    key, allow=callow[ds0])
                pos, tok, first = finish(adv, last, plen, first, pos,
                                         tok)
                ds = c_advance(adv & last, ds0, ctrans, first)
                return ck, cv, pos, tok, first, ds

            in_specs = (specs, _SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        P("data", None), _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC, _CTAB_SPEC,
                        _CTAB_SPEC, _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, P())
            out_specs = (_SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC)
        else:
            def run(params, ck, cv, pos, tok, toks, clen, start, last,
                    key):
                adv, plen, first, ck, cv, _, _ = body(
                    params, ck, cv, None, None, toks, clen, start,
                    key)
                pos, tok, first = finish(adv, last, plen, first, pos,
                                         tok)
                return ck, cv, pos, tok, first

            in_specs = (specs, _SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        P("data", None), _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC, P())
            out_specs = (_SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         _SLOT_VEC_SPEC)
    else:
        if constrain:
            def run(params, ck, cv, ksc, vsc, pos, tok, toks, clen,
                    start, last, callow, ctrans, cstate, cseed,
                    cseedval, key):
                ds0 = _c_start(cstate, cseed, cseedval)
                adv, plen, first, ck, cv, ksc, vsc = body(
                    params, ck, cv, ksc, vsc, toks, clen, start, key,
                    allow=callow[ds0])
                pos, tok, first = finish(adv, last, plen, first, pos,
                                         tok)
                ds = c_advance(adv & last, ds0, ctrans, first)
                return ck, cv, ksc, vsc, pos, tok, first, ds

            in_specs = (specs, _SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                        _SLOT_SCALE_SPEC, _SLOT_SCALE_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        P("data", None), _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC, _CTAB_SPEC,
                        _CTAB_SPEC, _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, P())
            out_specs = (_SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                         _SLOT_SCALE_SPEC, _SLOT_SCALE_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC)
        else:
            def run(params, ck, cv, ksc, vsc, pos, tok, toks, clen,
                    start, last, key):
                adv, plen, first, ck, cv, ksc, vsc = body(
                    params, ck, cv, ksc, vsc, toks, clen, start, key)
                pos, tok, first = finish(adv, last, plen, first, pos,
                                         tok)
                return ck, cv, ksc, vsc, pos, tok, first

            in_specs = (specs, _SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                        _SLOT_SCALE_SPEC, _SLOT_SCALE_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        P("data", None), _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC, P())
            out_specs = (_SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                         _SLOT_SCALE_SPEC, _SLOT_SCALE_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         _SLOT_VEC_SPEC)

    sharded = shard_map(run, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=True)
    return jax.jit(sharded)


# ---------------------------------------------------------------------------
# paged slot KV cache: fixed page pool + per-slot block tables (ISSUE-7)
# ---------------------------------------------------------------------------
#
# The contiguous pool above reserves every slot's full [S] token budget
# up front. The paged layout instead keeps ONE pool of
# `page_size`-token pages — [L, NP, page_size, D], heads over 'model'
# — addressed through a per-slot block table ([Ns, max_pages] int32 of
# physical page indices, HOST-owned and passed as runtime data, so the
# bucket-keyed compiled-program caches stay warm: remapping a page is
# an index edit, never a recompile). Physical page 0 is a reserved
# SCRATCH page: masked/inactive writes are routed there so the scatter
# shape stays static with no duplicate-index hazard on live pages
# (scratch content is never attended — the position mask covers it).
#
# Sharding: the page pool is the one structure slots SHARE, so the
# slot axis cannot shard over 'data' without cross-rank page
# ownership; paged programs therefore require a data=1 (tensor-
# parallel-only) serving mesh — the multi-host fleet work (ROADMAP)
# is where data-axis scaling of paged serving lands. Heads/MLP shard
# over 'model' exactly as the contiguous path; quantized-KV scale
# planes [L, NP, page_size, tp] keep quant/kv.py's per-model-rank
# layout.
#
# Token-exactness obligations (tests/test_serving_paged.py):
# - decode mirrors _local_block_decode_slotted(_q) with the gathered
#   page view standing in for the contiguous cache plane — same
#   values at the same logical positions, same einsum/softmax
#   numerics, so greedy decode is byte-identical to the contiguous
#   engine.
# - prefill is TWO-PIECE: the suffix (tokens not covered by a prefix-
#   cache hit) attends itself in float exactly as
#   _local_block_prefill's dot_product_attention does, PLUS the
#   gathered cache view masked to the shared prefix. With no hit the
#   cache piece is fully masked (exact zeros), reproducing the
#   contiguous prefill bit for bit — including int8-KV mode, where
#   contiguous prefill also attends float and quantizes on store.
#   With a hit, float-KV mode reads back the identical f32 rows the
#   shared prefill wrote, so outputs still match the contiguous run;
#   int8-KV prefix hits re-read the prefix through its quantization
#   (same error envelope as int8 decode — documented approximation).

_PAGE_POOL_SPEC = P(None, None, None, "model")    # [L, NP, ps, D]
_PAGE_SCALE_SPEC = P(None, None, None, "model")   # [L, NP, ps, tp]
_PAGE_VEC_SPEC = P(None)                          # per-slot scalars
_PAGE_BT_SPEC = P(None, None)                     # [Ns, max_pages]


def _check_paged_mesh(cfg: TransformerConfig, mesh: Mesh, top_k: int,
                      top_p: float, page_size: int, num_pages: int,
                      max_pages: int):
    """Paged-program validation: contiguous checks + data=1 (pages are
    shared across slots; a sharded slot axis would need cross-rank
    page ownership). Returns tp."""
    tp, dp = _check_serving_mesh(cfg, mesh, top_k, top_p)
    if dp != 1:
        raise ValueError(
            f"paged KV serving requires a data=1 mesh (got data={dp}): "
            "pages are shared across slots, which a 'data'-sharded "
            "slot axis cannot address")
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    if num_pages < 2:
        raise ValueError(f"num_pages must be >= 2 (page 0 is the "
                         f"reserved scratch page), got {num_pages}")
    if max_pages * page_size < cfg.max_len:
        raise ValueError(
            f"block table of {max_pages} pages x {page_size} tokens "
            f"cannot address max_len={cfg.max_len}")
    return tp


def init_paged_state(cfg: TransformerConfig, mesh: Mesh,
                     num_slots: int, page_size: int, num_pages: int,
                     kv_mode=None, cache_dtype=None):
    """Allocate the persistent PAGED pool state on the serving mesh:
    (kp, vp, pos, tok) with kp/vp [L, num_pages, page_size, D] (heads
    over 'model'), or the 6-tuple (kp, vp, kscale, vscale, pos, tok)
    when ``kv_mode`` selects the quantized pool (quant/kv.py). The
    block table is NOT part of the device state: it is host-owned
    runtime data (the engine passes it per call), so page remapping —
    prefix sharing, copy-on-write, free-list recycling — never touches
    a compiled program's geometry."""
    from deeplearning4j_tpu.models.transformer import page_pool_shape
    _, kv_mode = _resolve_quant(None, kv_mode)
    if kv_mode is not None:
        from deeplearning4j_tpu.quant.kv import init_paged_quant_state
        return init_paged_quant_state(cfg, mesh, num_slots, page_size,
                                      num_pages, kv_mode)
    dt = (cache_dtype if cache_dtype is not None
          else cfg.cache_jnp_dtype())
    shape = page_pool_shape(cfg, num_pages, page_size)
    kv_sh = NamedSharding(mesh, _PAGE_POOL_SPEC)
    vec_sh = NamedSharding(mesh, _PAGE_VEC_SPEC)
    kp = jax.device_put(jnp.zeros(shape, dt), kv_sh)
    vp = jax.device_put(jnp.zeros(shape, dt), kv_sh)
    pos = jax.device_put(jnp.zeros((num_slots,), jnp.int32), vec_sh)
    tok = jax.device_put(jnp.zeros((num_slots,), jnp.int32), vec_sh)
    return kp, vp, pos, tok


def _gather_pages(plane, bt, ns: int, s_view: int):
    """[NP, ps, D_loc] plane -> the block-table-ordered logical view
    [Ns, s_view, D_loc]: unallocated table entries read the scratch
    page; the caller's position mask keeps them out of attention."""
    g = plane[bt]                       # [Ns, mp, ps, D_loc]
    return g.reshape(ns, s_view, g.shape[-1])


def _local_block_decode_paged(h, p, kp, vp, bt, layer: int, pos, act,
                              cfg: TransformerConfig, tp: int, dp: int,
                              page_size: int):
    """One TP block, one new position per slot, PAGED storage: the K/V
    row lands at (bt[slot, pos//ps], pos%ps) — inactive slots write the
    scratch page — and attention runs over the gathered logical view.
    Deliberately mirrors _local_block_decode_slotted's math (the
    gathered view holds the same values at the same logical positions,
    and attention goes through the same
    `ops/flash_decode.decode_attention` primitive over the gathered
    view), so paged greedy decode is byte-identical to the contiguous
    pool."""
    from deeplearning4j_tpu.ops.flash_decode import decode_attention
    g_model = _g_sync("model")
    h_loc = cfg.n_heads // tp
    d_loc = h_loc * cfg.d_head
    ns = h.shape[0]
    mp = bt.shape[1]
    s_view = mp * page_size
    x = layer_norm(h, p["ln1g"], p["ln1b"], cfg.eps)
    q = jnp.matmul(x[:, 0], p["Wq"].astype(x.dtype)) \
        .reshape(ns, h_loc, cfg.d_head)
    k = jnp.matmul(x[:, 0], p["Wk"].astype(x.dtype))      # [Ns, D_loc]
    v = jnp.matmul(x[:, 0], p["Wv"].astype(x.dtype))
    rows = jnp.arange(ns)
    wp = jnp.clip(pos, 0, s_view - 1)
    lp = jnp.clip(wp // page_size, 0, mp - 1)
    pg = jnp.where(act, bt[rows, lp], 0)     # inactive -> scratch
    off = wp % page_size
    kp = kp.at[layer, pg, off].set(k.astype(kp.dtype))
    vp = vp.at[layer, pg, off].set(v.astype(vp.dtype))
    kh = _gather_pages(kp[layer], bt, ns, s_view)    # [Ns, S_view, Dl]
    vh = _gather_pages(vp[layer], bt, ns, s_view)
    a = decode_attention(q, kh, vh, wp, n_heads=h_loc)
    h = h + g_model(jnp.matmul(a.reshape(ns, 1, d_loc),
                               p["Wo"].astype(h.dtype)))
    x = layer_norm(h, p["ln2g"], p["ln2b"], cfg.eps)
    h = _local_mlp(h, x, p, cfg, dp, g_model)
    return h, kp, vp


def _local_block_decode_paged_q(h, p, kp, vp, ksc, vsc, bt, layer: int,
                                pos, act, cfg: TransformerConfig,
                                tp: int, dp: int, page_size: int,
                                kv_mode: str):
    """Quantized-KV paged decode block: quantize-on-write into the
    int8/fp8 page pool + parallel scale planes, scales folded into
    scores/probabilities through the same
    `decode_attention(k_scale=, v_scale=)` call as
    _local_block_decode_slotted_q."""
    from deeplearning4j_tpu.ops.flash_decode import decode_attention
    from deeplearning4j_tpu.quant.kv import quantize_rows
    g_model = _g_sync("model")
    h_loc = cfg.n_heads // tp
    d_loc = h_loc * cfg.d_head
    ns = h.shape[0]
    mp = bt.shape[1]
    s_view = mp * page_size
    x = layer_norm(h, p["ln1g"], p["ln1b"], cfg.eps)
    q = jnp.matmul(x[:, 0], p["Wq"].astype(x.dtype)) \
        .reshape(ns, h_loc, cfg.d_head)
    k = jnp.matmul(x[:, 0], p["Wk"].astype(x.dtype))      # [Ns, D_loc]
    v = jnp.matmul(x[:, 0], p["Wv"].astype(x.dtype))
    rows = jnp.arange(ns)
    wp = jnp.clip(pos, 0, s_view - 1)
    lp = jnp.clip(wp // page_size, 0, mp - 1)
    pg = jnp.where(act, bt[rows, lp], 0)     # inactive -> scratch
    off = wp % page_size
    kq, ksr = quantize_rows(k, kv_mode)
    vq, vsr = quantize_rows(v, kv_mode)
    kp = kp.at[layer, pg, off].set(kq)
    vp = vp.at[layer, pg, off].set(vq)
    ksc = ksc.at[layer, pg, off, 0].set(ksr)
    vsc = vsc.at[layer, pg, off, 0].set(vsr)
    kh = _gather_pages(kp[layer].astype(jnp.float32), bt, ns, s_view)
    vh = _gather_pages(vp[layer].astype(jnp.float32), bt, ns, s_view)
    ksg = _gather_pages(ksc[layer], bt, ns, s_view)[..., 0]
    vsg = _gather_pages(vsc[layer], bt, ns, s_view)[..., 0]
    a = decode_attention(q, kh, vh, wp, n_heads=h_loc, k_scale=ksg,
                         v_scale=vsg)
    h = h + g_model(jnp.matmul(a.reshape(ns, 1, d_loc),
                               p["Wo"].astype(h.dtype)))
    x = layer_norm(h, p["ln2g"], p["ln2b"], cfg.eps)
    h = _local_mlp(h, x, p, cfg, dp, g_model)
    return h, kp, vp, ksc, vsc


def make_paged_prefill(cfg: TransformerConfig, mesh: Mesh,
                       bucket_len: int, num_slots: int, page_size: int,
                       max_pages: int, num_pages: int,
                       temperature: float = 0.0, top_k: int = 0,
                       top_p: float = 1.0, quantized=None,
                       kv_mode=None, chunked: bool = False,
                       constrain: bool = False):
    """Compiled PAGED admission prefill: (params, kp, vp, pos, tok,
    bt [Ns, max_pages], suffix [Ns, Tb], slen [Ns], start [Ns], key)
    -> (kp, vp, pos, tok, first [Ns]).

    ``suffix`` holds each admitted slot's NOT-YET-CACHED token tail
    (the full prefix when there is no prefix-cache hit), right-padded
    to the suffix bucket Tb; ``start[i]`` is the number of prefix
    tokens whose K/V the host already mapped into the slot's block
    table (a radix-cache hit — prefill RESUMES from that boundary, so
    shared system prompts share the prefill compute, not just the
    bytes). Suffix K/V rows are written to the slot's pages at
    absolute positions start+t; attention per suffix query t is the
    cached prefix (gathered pages, masked to s < start) plus causal
    float self-attention within the suffix — exactly
    _local_block_prefill's numerics when start == 0 (the cache piece
    contributes exact zeros), which is what keeps the paged engine
    token-identical to the contiguous one, int8-KV included. Slots
    with slen == 0 pass through untouched.

    ``kv_mode`` switches to the quantized page pool — the state grows
    scale planes ((params, kp, vp, ksc, vsc, pos, tok, bt, suffix,
    slen, start, key) -> (..., first)) and suffix rows quantize on
    write while the suffix still attends itself in float (mirroring
    the contiguous quant prefill, which also stores quantized but
    attends the float activations).

    ``chunked`` (ISSUE-10, see `make_paged_chunked_prefill`)
    generalizes the prefix-hit resume to ARBITRARY chunk boundaries:
    the signature grows a ``last`` [Ns] bool before the key, ``start``
    may be any mid-prompt position (not just a page-aligned cache-hit
    boundary — the attention math is already position-general), and
    only chunks with ``last`` set sample/commit the first generated
    token; mid-prompt chunks advance pos and report first = -1."""
    from deeplearning4j_tpu.ops.flash_decode import NEG_INF
    tp = _check_paged_mesh(cfg, mesh, top_k, top_p, page_size,
                           num_pages, max_pages)
    dp = 1
    quantized, kv_mode = _resolve_quant(quantized, kv_mode)
    if not 0 < bucket_len <= cfg.max_len:
        raise ValueError(f"bucket_len {bucket_len} out of "
                         f"(0, {cfg.max_len}]")
    specs = _serving_specs(cfg, quantized)
    h_loc = cfg.n_heads // tp
    d_loc = h_loc * cfg.d_head
    s_view = max_pages * page_size
    scale = cfg.d_head ** -0.5

    def body(params, kp, vp, ksc, vsc, bt, suffix, slen, start, key,
             allow=None):
        dt = cfg.activation_dtype()
        acc = jnp.promote_types(dt, jnp.float32)
        ns, tb = suffix.shape
        admit = slen > 0
        absp = start[:, None] + jnp.arange(tb)[None, :]   # [Ns, Tb]
        valid = jnp.arange(tb)[None, :] < slen[:, None]
        pe = params["pos"].astype(dt)[
            jnp.clip(absp, 0, cfg.max_len - 1)]
        h = params["embed"].astype(dt)[suffix] + pe
        # write targets: pad/unadmitted rows -> scratch page 0
        lp = jnp.clip(absp // page_size, 0, max_pages - 1)
        pg = jnp.where(valid, jnp.take_along_axis(bt, lp, axis=1), 0)
        off = absp % page_size
        mvalid = valid if cfg.n_experts > 0 else None
        causal = (jnp.arange(tb)[None, :]
                  <= jnp.arange(tb)[:, None])             # [Tb, Tb]
        pmask = (jnp.arange(s_view)[None, None, None, :]
                 < start[:, None, None, None])            # [Ns,1,1,S]
        for layer in range(cfg.n_layers):
            p = {kk: vv[layer] for kk, vv in params["blocks"].items()}
            x = layer_norm(h, p["ln1g"], p["ln1b"], cfg.eps)
            q = jnp.matmul(x, p["Wq"].astype(x.dtype)) \
                .reshape(ns, tb, h_loc, cfg.d_head)
            k = jnp.matmul(x, p["Wk"].astype(x.dtype))    # [Ns,Tb,Dl]
            v = jnp.matmul(x, p["Wv"].astype(x.dtype))
            # store the suffix rows (quantize-on-write in kv_mode)
            if kv_mode is None:
                kp = kp.at[layer, pg, off].set(k.astype(kp.dtype))
                vp = vp.at[layer, pg, off].set(v.astype(vp.dtype))
            else:
                from deeplearning4j_tpu.quant.kv import quantize_rows
                kq, ksr = quantize_rows(k, kv_mode)
                vq, vsr = quantize_rows(v, kv_mode)
                kp = kp.at[layer, pg, off].set(kq)
                vp = vp.at[layer, pg, off].set(vq)
                ksc = ksc.at[layer, pg, off, 0].set(ksr)
                vsc = vsc.at[layer, pg, off, 0].set(vsr)
            kv4 = k.reshape(ns, tb, h_loc, cfg.d_head)
            vv4 = v.reshape(ns, tb, h_loc, cfg.d_head)
            # piece 2: float causal self-attention within the suffix —
            # bitwise dot_product_attention(q, k, v, causal=True)
            sc2 = jnp.einsum("bthd,bshd->bhts", q, kv4,
                             preferred_element_type=acc) * scale
            sc2 = jnp.where(causal[None, None], sc2, NEG_INF)
            # piece 1: the cached prefix, gathered from the pages and
            # masked to s < start (fully masked when there is no hit)
            if kv_mode is None:
                kh = _gather_pages(kp[layer], bt, ns, s_view) \
                    .reshape(ns, s_view, h_loc, cfg.d_head)
                vh = _gather_pages(vp[layer], bt, ns, s_view) \
                    .reshape(ns, s_view, h_loc, cfg.d_head)
                sc1 = jnp.einsum("bthd,bshd->bhts", q, kh,
                                 preferred_element_type=acc) * scale
            else:
                kh = _gather_pages(kp[layer].astype(jnp.float32), bt,
                                   ns, s_view) \
                    .reshape(ns, s_view, h_loc, cfg.d_head)
                vh = _gather_pages(vp[layer].astype(jnp.float32), bt,
                                   ns, s_view) \
                    .reshape(ns, s_view, h_loc, cfg.d_head)
                ksg = _gather_pages(ksc[layer], bt, ns, s_view)[..., 0]
                vsg = _gather_pages(vsc[layer], bt, ns, s_view)[..., 0]
                sc1 = jnp.einsum("bthd,bshd->bhts",
                                 q.astype(jnp.float32), kh) \
                    * ksg[:, None, None, :] * scale
            sc1 = jnp.where(pmask, sc1, NEG_INF)
            # one softmax over [prefix-view | suffix] keys (logical
            # order preserved: prefix positions first), then the two
            # value pieces recombine — exact zeros where masked
            w = jax.nn.softmax(
                jnp.concatenate([sc1.astype(acc), sc2], axis=-1),
                axis=-1)
            w1, w2 = w[..., :s_view], w[..., s_view:]
            if kv_mode is None:
                a1 = jnp.einsum("bhts,bshd->bthd", w1.astype(vh.dtype),
                                vh)
            else:
                a1 = jnp.einsum("bhts,bshd->bthd",
                                w1 * vsg[:, None, None, :], vh) \
                    .astype(v.dtype)
            a2 = jnp.einsum("bhts,bshd->bthd", w2.astype(v.dtype), vv4)
            a = (a1 + a2).reshape(ns, tb, d_loc)
            h = h + _g_sync("model")(
                jnp.matmul(a, p["Wo"].astype(a.dtype)))
            x = layer_norm(h, p["ln2g"], p["ln2b"], cfg.eps)
            h = _local_mlp(h, x, p, cfg, dp, _g_sync("model"),
                           valid=mvalid)
        h = layer_norm(h, params["lnfg"], params["lnfb"], cfg.eps)
        last = h[jnp.arange(ns), jnp.clip(slen - 1, 0, tb - 1)]
        logits = jnp.matmul(last, params["Wout"].astype(last.dtype))
        if allow is not None:
            logits = _mask_allow(logits, allow)
        plen = start + slen
        first = _sample_slots(logits, plen, key, dp, temperature,
                              top_k, top_p)
        return admit, plen, first, kp, vp, ksc, vsc

    def finish(admit, plen, first, pos, tok, lastf=None):
        # chunked: only the prompt's FINAL chunk commits the sampled
        # first token; mid-prompt chunks advance pos only
        take = admit if lastf is None else (admit & lastf)
        pos = jnp.where(admit, plen.astype(pos.dtype), pos)
        tok = jnp.where(take, first, tok)
        return pos, tok, jnp.where(take, first,
                                   jnp.asarray(-1, jnp.int32))

    def c_advance(take, ds0, ctrans, first):
        # advance the DFA only where a first token was committed; the
        # sample was already masked by callow[ds0], so first is legal
        return jnp.where(take, ctrans[ds0, jnp.maximum(first, 0)], ds0)

    _CEXT = (_CTAB_SPEC, _CTAB_SPEC, _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
             _PAGE_VEC_SPEC)

    if kv_mode is None:
        if chunked:
            if constrain:
                def run(params, kp, vp, pos, tok, bt, suffix, slen,
                        start, last, callow, ctrans, cstate, cseed,
                        cseedval, key):
                    ds0 = _c_start(cstate, cseed, cseedval)
                    admit, plen, first, kp, vp, _, _ = body(
                        params, kp, vp, None, None, bt, suffix, slen,
                        start, key, allow=callow[ds0])
                    pos, tok, first = finish(admit, plen, first, pos,
                                             tok, last)
                    ds = c_advance(admit & last, ds0, ctrans, first)
                    return kp, vp, pos, tok, first, ds

                in_specs = (specs, _PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                            _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                            _PAGE_BT_SPEC, P(None, None),
                            _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                            _PAGE_VEC_SPEC) + _CEXT + (P(),)
            else:
                def run(params, kp, vp, pos, tok, bt, suffix, slen,
                        start, last, key):
                    admit, plen, first, kp, vp, _, _ = body(
                        params, kp, vp, None, None, bt, suffix, slen,
                        start, key)
                    pos, tok, first = finish(admit, plen, first, pos,
                                             tok, last)
                    return kp, vp, pos, tok, first

                in_specs = (specs, _PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                            _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                            _PAGE_BT_SPEC, P(None, None),
                            _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                            _PAGE_VEC_SPEC, P())
        else:
            if constrain:
                def run(params, kp, vp, pos, tok, bt, suffix, slen,
                        start, callow, ctrans, cstate, cseed, cseedval,
                        key):
                    ds0 = _c_start(cstate, cseed, cseedval)
                    admit, plen, first, kp, vp, _, _ = body(
                        params, kp, vp, None, None, bt, suffix, slen,
                        start, key, allow=callow[ds0])
                    pos, tok, first = finish(admit, plen, first, pos,
                                             tok)
                    ds = c_advance(admit, ds0, ctrans, first)
                    return kp, vp, pos, tok, first, ds

                in_specs = (specs, _PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                            _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                            _PAGE_BT_SPEC, P(None, None),
                            _PAGE_VEC_SPEC, _PAGE_VEC_SPEC) \
                    + _CEXT + (P(),)
            else:
                def run(params, kp, vp, pos, tok, bt, suffix, slen,
                        start, key):
                    admit, plen, first, kp, vp, _, _ = body(
                        params, kp, vp, None, None, bt, suffix, slen,
                        start, key)
                    pos, tok, first = finish(admit, plen, first, pos,
                                             tok)
                    return kp, vp, pos, tok, first

                in_specs = (specs, _PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                            _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                            _PAGE_BT_SPEC, P(None, None),
                            _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, P())
        out_specs = (_PAGE_POOL_SPEC, _PAGE_POOL_SPEC, _PAGE_VEC_SPEC,
                     _PAGE_VEC_SPEC, _PAGE_VEC_SPEC)
    else:
        if chunked:
            if constrain:
                def run(params, kp, vp, ksc, vsc, pos, tok, bt,
                        suffix, slen, start, last, callow, ctrans,
                        cstate, cseed, cseedval, key):
                    ds0 = _c_start(cstate, cseed, cseedval)
                    admit, plen, first, kp, vp, ksc, vsc = body(
                        params, kp, vp, ksc, vsc, bt, suffix, slen,
                        start, key, allow=callow[ds0])
                    pos, tok, first = finish(admit, plen, first, pos,
                                             tok, last)
                    ds = c_advance(admit & last, ds0, ctrans, first)
                    return kp, vp, ksc, vsc, pos, tok, first, ds

                in_specs = (specs, _PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                            _PAGE_SCALE_SPEC, _PAGE_SCALE_SPEC,
                            _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                            _PAGE_BT_SPEC, P(None, None),
                            _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                            _PAGE_VEC_SPEC) + _CEXT + (P(),)
            else:
                def run(params, kp, vp, ksc, vsc, pos, tok, bt,
                        suffix, slen, start, last, key):
                    admit, plen, first, kp, vp, ksc, vsc = body(
                        params, kp, vp, ksc, vsc, bt, suffix, slen,
                        start, key)
                    pos, tok, first = finish(admit, plen, first, pos,
                                             tok, last)
                    return kp, vp, ksc, vsc, pos, tok, first

                in_specs = (specs, _PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                            _PAGE_SCALE_SPEC, _PAGE_SCALE_SPEC,
                            _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                            _PAGE_BT_SPEC, P(None, None),
                            _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                            _PAGE_VEC_SPEC, P())
        else:
            if constrain:
                def run(params, kp, vp, ksc, vsc, pos, tok, bt,
                        suffix, slen, start, callow, ctrans, cstate,
                        cseed, cseedval, key):
                    ds0 = _c_start(cstate, cseed, cseedval)
                    admit, plen, first, kp, vp, ksc, vsc = body(
                        params, kp, vp, ksc, vsc, bt, suffix, slen,
                        start, key, allow=callow[ds0])
                    pos, tok, first = finish(admit, plen, first, pos,
                                             tok)
                    ds = c_advance(admit, ds0, ctrans, first)
                    return kp, vp, ksc, vsc, pos, tok, first, ds

                in_specs = (specs, _PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                            _PAGE_SCALE_SPEC, _PAGE_SCALE_SPEC,
                            _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                            _PAGE_BT_SPEC, P(None, None),
                            _PAGE_VEC_SPEC, _PAGE_VEC_SPEC) \
                    + _CEXT + (P(),)
            else:
                def run(params, kp, vp, ksc, vsc, pos, tok, bt,
                        suffix, slen, start, key):
                    admit, plen, first, kp, vp, ksc, vsc = body(
                        params, kp, vp, ksc, vsc, bt, suffix, slen,
                        start, key)
                    pos, tok, first = finish(admit, plen, first, pos,
                                             tok)
                    return kp, vp, ksc, vsc, pos, tok, first

                in_specs = (specs, _PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                            _PAGE_SCALE_SPEC, _PAGE_SCALE_SPEC,
                            _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                            _PAGE_BT_SPEC, P(None, None),
                            _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, P())
        out_specs = (_PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                     _PAGE_SCALE_SPEC, _PAGE_SCALE_SPEC,
                     _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, _PAGE_VEC_SPEC)
    if constrain:
        out_specs = out_specs + (_PAGE_VEC_SPEC,)

    sharded = shard_map(run, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=True)
    return jax.jit(sharded)


def make_paged_chunked_prefill(cfg: TransformerConfig, mesh: Mesh,
                               chunk_len: int, num_slots: int,
                               page_size: int, max_pages: int,
                               num_pages: int, temperature: float = 0.0,
                               top_k: int = 0, top_p: float = 1.0,
                               quantized=None, kv_mode=None,
                               constrain: bool = False):
    """Paged twin of `make_chunked_prefill`: (params, kp, vp[, kscale,
    vscale], pos, tok, bt [Ns, max_pages], toks [Ns, C], clen [Ns],
    start [Ns], last [Ns] bool, key) -> (state', pos, tok, first).

    The paged prefill's two-piece attention already resumes from an
    arbitrary per-slot ``start`` as runtime data — the prefix-cache
    hit boundary was just its only caller — so the chunked variant IS
    `make_paged_prefill` with the chunk as the "suffix" plus the
    ``last`` flag gating first-token commitment. Chunk K/V rows land
    at (bt[slot, (start+t)//ps], (start+t)%ps); invalid rows route to
    the scratch page exactly as the one-shot paged prefill's pad rows
    do."""
    return make_paged_prefill(cfg, mesh, chunk_len, num_slots,
                              page_size, max_pages, num_pages,
                              temperature=temperature, top_k=top_k,
                              top_p=top_p, quantized=quantized,
                              kv_mode=kv_mode, chunked=True,
                              constrain=constrain)


def make_paged_decode(cfg: TransformerConfig, mesh: Mesh, chunk: int,
                      num_slots: int, page_size: int, max_pages: int,
                      num_pages: int, temperature: float = 0.0,
                      top_k: int = 0, top_p: float = 1.0,
                      quantized=None, kv_mode=None,
                      constrain: bool = False):
    """Compiled PAGED decode chunk: (params, kp, vp, pos, tok,
    bt [Ns, max_pages], active [Ns], rem [Ns], key) -> (kp, vp, pos,
    tok, toks [Ns, chunk]). Contract identical to
    make_continuous_decode — active/rem/pos AND the block table are
    runtime data, one compiled program per (chunk, num_slots,
    page geometry) — with K/V rows landing in block-table pages
    instead of contiguous slot rows. ``kv_mode`` adds the scale
    planes to the state exactly as the contiguous quant path."""
    tp = _check_paged_mesh(cfg, mesh, top_k, top_p, page_size,
                           num_pages, max_pages)
    dp = 1
    quantized, kv_mode = _resolve_quant(quantized, kv_mode)
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    specs = _serving_specs(cfg, quantized)

    def sample_and_advance(params, h, act, pos, tok, rem, key,
                           ds=None, callow=None, ctrans=None):
        h = layer_norm(h, params["lnfg"], params["lnfb"], cfg.eps)
        logits = jnp.matmul(h[:, 0], params["Wout"].astype(h.dtype))
        if callow is not None:
            logits = _mask_allow(logits, callow[ds])
        nxt = _sample_slots(logits, pos + 1, key, dp, temperature,
                            top_k, top_p)
        if callow is not None:
            ds = jnp.where(act, ctrans[ds, nxt], ds)
        tok = jnp.where(act, nxt, tok)
        emit = jnp.where(act, nxt, jnp.asarray(-1, jnp.int32))
        pos = jnp.where(act, pos + 1, pos)
        rem = jnp.where(act, rem - 1, rem)
        return pos, tok, rem, emit, ds

    def embed_step(params, pos, tok):
        dt = cfg.activation_dtype()
        emb = params["embed"].astype(dt)[tok]
        pv = params["pos"].astype(dt)[
            jnp.clip(pos, 0, cfg.max_len - 1)]
        return (emb + pv)[:, None, :]

    _CEXT = (_CTAB_SPEC, _CTAB_SPEC, _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
             _PAGE_VEC_SPEC)

    if kv_mode is None:
        if constrain:
            def run(params, kp, vp, pos, tok, bt, active, rem, callow,
                    ctrans, cstate, cseed, cseedval, key):
                def step(carry, _):
                    kp, vp, pos, tok, rem, ds = carry
                    act = active & (rem > 0)
                    h = embed_step(params, pos, tok)
                    for layer in range(cfg.n_layers):
                        p_l = {kk: vv[layer]
                               for kk, vv in params["blocks"].items()}
                        h, kp, vp = _local_block_decode_paged(
                            h, p_l, kp, vp, bt, layer, pos, act, cfg,
                            tp, dp, page_size)
                    pos, tok, rem, emit, ds = sample_and_advance(
                        params, h, act, pos, tok, rem, key, ds=ds,
                        callow=callow, ctrans=ctrans)
                    return (kp, vp, pos, tok, rem, ds), emit

                ds0 = _c_start(cstate, cseed, cseedval)
                (kp, vp, pos, tok, _, ds), toks = lax.scan(
                    step, (kp, vp, pos, tok, rem, ds0), None,
                    length=chunk)
                return kp, vp, pos, tok, jnp.swapaxes(toks, 0, 1), ds

            in_specs = (specs, _PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                        _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, _PAGE_BT_SPEC,
                        _PAGE_VEC_SPEC, _PAGE_VEC_SPEC) \
                + _CEXT + (P(),)
            out_specs = (_PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                         _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, P(None, None),
                         _PAGE_VEC_SPEC)
        else:
            def run(params, kp, vp, pos, tok, bt, active, rem, key):
                def step(carry, _):
                    kp, vp, pos, tok, rem = carry
                    act = active & (rem > 0)
                    h = embed_step(params, pos, tok)
                    for layer in range(cfg.n_layers):
                        p_l = {kk: vv[layer]
                               for kk, vv in params["blocks"].items()}
                        h, kp, vp = _local_block_decode_paged(
                            h, p_l, kp, vp, bt, layer, pos, act, cfg,
                            tp, dp, page_size)
                    pos, tok, rem, emit, _ = sample_and_advance(
                        params, h, act, pos, tok, rem, key)
                    return (kp, vp, pos, tok, rem), emit

                (kp, vp, pos, tok, _), toks = lax.scan(
                    step, (kp, vp, pos, tok, rem), None, length=chunk)
                return kp, vp, pos, tok, jnp.swapaxes(toks, 0, 1)

            in_specs = (specs, _PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                        _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, _PAGE_BT_SPEC,
                        _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, P())
            out_specs = (_PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                         _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, P(None, None))
    else:
        if constrain:
            def run(params, kp, vp, ksc, vsc, pos, tok, bt, active,
                    rem, callow, ctrans, cstate, cseed, cseedval, key):
                def step(carry, _):
                    kp, vp, ksc, vsc, pos, tok, rem, ds = carry
                    act = active & (rem > 0)
                    h = embed_step(params, pos, tok)
                    for layer in range(cfg.n_layers):
                        p_l = {kk: vv[layer]
                               for kk, vv in params["blocks"].items()}
                        h, kp, vp, ksc, vsc = \
                            _local_block_decode_paged_q(
                                h, p_l, kp, vp, ksc, vsc, bt, layer,
                                pos, act, cfg, tp, dp, page_size,
                                kv_mode)
                    pos, tok, rem, emit, ds = sample_and_advance(
                        params, h, act, pos, tok, rem, key, ds=ds,
                        callow=callow, ctrans=ctrans)
                    return (kp, vp, ksc, vsc, pos, tok, rem, ds), emit

                ds0 = _c_start(cstate, cseed, cseedval)
                (kp, vp, ksc, vsc, pos, tok, _, ds), toks = lax.scan(
                    step, (kp, vp, ksc, vsc, pos, tok, rem, ds0), None,
                    length=chunk)
                return (kp, vp, ksc, vsc, pos, tok,
                        jnp.swapaxes(toks, 0, 1), ds)

            in_specs = (specs, _PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                        _PAGE_SCALE_SPEC, _PAGE_SCALE_SPEC,
                        _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, _PAGE_BT_SPEC,
                        _PAGE_VEC_SPEC, _PAGE_VEC_SPEC) \
                + _CEXT + (P(),)
            out_specs = (_PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                         _PAGE_SCALE_SPEC, _PAGE_SCALE_SPEC,
                         _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, P(None, None),
                         _PAGE_VEC_SPEC)
        else:
            def run(params, kp, vp, ksc, vsc, pos, tok, bt, active,
                    rem, key):
                def step(carry, _):
                    kp, vp, ksc, vsc, pos, tok, rem = carry
                    act = active & (rem > 0)
                    h = embed_step(params, pos, tok)
                    for layer in range(cfg.n_layers):
                        p_l = {kk: vv[layer]
                               for kk, vv in params["blocks"].items()}
                        h, kp, vp, ksc, vsc = \
                            _local_block_decode_paged_q(
                                h, p_l, kp, vp, ksc, vsc, bt, layer,
                                pos, act, cfg, tp, dp, page_size,
                                kv_mode)
                    pos, tok, rem, emit, _ = sample_and_advance(
                        params, h, act, pos, tok, rem, key)
                    return (kp, vp, ksc, vsc, pos, tok, rem), emit

                (kp, vp, ksc, vsc, pos, tok, _), toks = lax.scan(
                    step, (kp, vp, ksc, vsc, pos, tok, rem), None,
                    length=chunk)
                return (kp, vp, ksc, vsc, pos, tok,
                        jnp.swapaxes(toks, 0, 1))

            in_specs = (specs, _PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                        _PAGE_SCALE_SPEC, _PAGE_SCALE_SPEC,
                        _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, _PAGE_BT_SPEC,
                        _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, P())
            out_specs = (_PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                         _PAGE_SCALE_SPEC, _PAGE_SCALE_SPEC,
                         _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, P(None, None))

    sharded = shard_map(run, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=True)
    return jax.jit(sharded)


# ---------------------------------------------------------------------------
# speculative decoding: draft K tokens, verify them in ONE target pass
# (ISSUE-8)
# ---------------------------------------------------------------------------
#
# Decode is the engine's memory-bound tail: every sequential step pays
# the full weight + KV-prefix bandwidth to emit ONE token per slot.
# A speculative round instead (1) runs K cheap DRAFT steps — the
# int8-quantized weight tree, the model itself ("self"), or an
# early-exit truncation to the first `draft_layers` blocks — proposing
# d_1..d_K per active slot, then (2) runs ONE target-model VERIFY pass
# scoring all K+1 window positions [pending, d_1..d_K] at once, and
# (3) commits the longest accepted prefix plus the target's own token
# at the first divergence (rejection-resampling degenerates to "take
# the target's token" under position-keyed sampling — see below). The
# target pays one pass of bandwidth for up to K+1 committed tokens.
#
# EXACTNESS — stronger than the classic rejection-sampling guarantee:
# the committed token at sequence index j is ALWAYS
# sample(fold_in(key, j), target logits at j) — the verify pass scores
# every window position with the target model and samples it through
# the SAME position-keyed schedule sequential decode uses
# (models/transformer.sample_at_positions), accepting a draft only
# when it EQUALS that sample. By induction every committed token is
# bit-identical to what the non-speculative engine emits at the same
# position under the same seed — greedy AND temperature/top-k/top-p
# sampled, float AND int8 KV, contiguous AND paged — which trivially
# implies the distributional (rejection-sampling) guarantee, and makes
# rollback free: a slot that accepts 3 of 5 drafts simply IS a
# non-speculative slot at its new position.
#
# CACHE SAFETY: draft steps write draft-weight K/V rows at positions
# pos..pos+K-1 (through the ordinary slotted/paged block fns), but the
# verify pass REWRITES rows pos..pos+K with target-weight K/V before
# attending them, so the cache holds pure target K/V for every
# committed position. Rows past the committed prefix (rejected
# drafts) hold target K/V for tokens that never landed — they sit at
# indices >= the new pending position, are never attended (every
# attention mask here is s <= current position), and are overwritten
# in order as real tokens arrive: the same monotone-overwrite argument
# bucket-pad rows rely on. Paged pools route writes past a slot's
# block table (or inactive slots) to the reserved scratch page, and
# the engine's copy-on-write guard privatizes the whole K+1 write
# span before the call — a speculative write can never land on a page
# another slot or the prefix cache references.
#
# SHAPES: one fixed-shape program per (K, num_slots, kv_mode[, page
# geometry]) riding the engine's bucket-keyed compile caches;
# active/rem/poison and per-slot accept counts are runtime data, so
# acceptance variance never recompiles. ``poison`` [Ns] derails the
# drafts on-device ((d+1) mod V — guaranteed != the model's own
# proposal) for deterministic fault-injection
# (ServingFaultInjector.draft_poison_at): verification rejects every
# poisoned draft and the round degrades to one committed token,
# proving a poisoned draft pass cannot corrupt committed KV.
#
# MoE configs are rejected: the expert-capacity cap is a function of
# the tokens-per-call count, so a K+1-token verify pass would bind
# capacity differently than sequential decode and break the
# token-exactness contract (same reason bucket-padded MoE prefill is a
# documented divergence).


def _embed_pending(params, cfg: TransformerConfig, pos, tok):
    """Embed each slot's pending token at its own position — the
    shared first step of every sequential decode/draft step."""
    dt = cfg.activation_dtype()
    emb = params["embed"].astype(dt)[tok]
    pv = params["pos"].astype(dt)[jnp.clip(pos, 0, cfg.max_len - 1)]
    return (emb + pv)[:, None, :]


def _check_spec(cfg: TransformerConfig, spec_k: int, draft_layers: int):
    if spec_k < 1:
        raise ValueError(f"spec_k must be >= 1, got {spec_k}")
    if cfg.n_experts > 0:
        raise ValueError(
            "speculative decoding does not support MoE configs: the "
            "expert-capacity cap depends on the tokens-per-call count, "
            "so a K+1-token verify pass would drop differently than "
            "sequential decode and break token-exactness")
    nd = draft_layers if draft_layers > 0 else cfg.n_layers
    if not 0 < nd <= cfg.n_layers:
        raise ValueError(f"draft_layers {draft_layers} out of "
                         f"(0, {cfg.n_layers}]")
    return nd


def _spec_accept_commit(spec_k: int, drafts, tgt, pos, tok, rem, act):
    """Accept the longest draft prefix matching the target's
    position-keyed samples, commit it plus the target's token at the
    first divergence (or the bonus token after K accepts), capped by
    the slot's remaining budget. Returns (pos', tok', rem', emit
    [Ns, K+1] with -1 past each slot's commit count, ncommit, drafted,
    accepted)."""
    k1 = spec_k + 1
    ns = tok.shape[0]
    rows = jnp.arange(ns)
    zero = jnp.asarray(0, jnp.int32)
    match = (drafts == tgt[:, :spec_k]) & act[:, None]
    # .astype(int32): jnp.sum promotes int32 to the default int, which
    # under jax_enable_x64 silently flips the slot pos/ncommit dtypes
    # to int64 after the first round — a hidden extra jit signature on
    # the lazy path and a hard aval mismatch for an AOT-compiled
    # executable (ISSUE-12). Pin the accept count instead.
    acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                  axis=1).astype(jnp.int32)                 # [Ns] 0..K
    c = jnp.where(act, jnp.minimum(acc + 1, rem), zero)
    emit = jnp.where(jnp.arange(k1)[None, :] < c[:, None], tgt,
                     jnp.asarray(-1, jnp.int32))
    last = tgt[rows, jnp.clip(c - 1, 0, spec_k)]
    tok = jnp.where(act, last, tok)
    pos = jnp.where(act, pos + c, pos)
    rem = jnp.where(act, rem - c, rem)
    drafted = jnp.where(act, jnp.asarray(spec_k, jnp.int32), zero)
    accepted = jnp.maximum(c - 1, 0)
    return pos, tok, rem, emit, c, drafted, accepted


def _c_spec_window(spec_k: int, ds0, ctrans, drafts):
    """Constraint states for the K+1 verify-window positions: entry j
    is the DFA state after consuming drafts[:, :j] from ds0, so the
    target sample at window position j is masked by the state the
    masked sequential engine would hold there. Walked from the POST-
    poison drafts: on the accepted prefix drafts equal the committed
    tokens (so the states agree with the sequential walk by
    construction), and positions past the first divergence are never
    committed — a poisoned draft merely yields a scratch state whose
    masked sample the acceptance test then rejects."""
    sw = [ds0]
    for j in range(spec_k):
        sw.append(ctrans[sw[-1], drafts[:, j]])
    return jnp.stack(sw, axis=1)                         # [Ns, K+1]


def _c_spec_final(spec_k: int, swin, ctrans, tgt, c, act, ds0):
    """DFA state after a speculative commit: the state at the last
    committed window position (column c-1 of the window walk) advanced
    by the committed token there (tgt at c-1 — _spec_accept_commit's
    ``last``). Inactive slots keep ds0."""
    rows = jnp.arange(tgt.shape[0])
    j = jnp.clip(c - 1, 0, spec_k)
    return jnp.where(act, ctrans[swin[rows, j], tgt[rows, j]], ds0)


def make_speculative_decode(cfg: TransformerConfig, mesh: Mesh,
                            spec_k: int, num_slots: int,
                            temperature: float = 0.0, top_k: int = 0,
                            top_p: float = 1.0, quantized=None,
                            kv_mode=None, draft_quantized=None,
                            draft_layers: int = 0,
                            constrain: bool = False):
    """Compiled speculative decode round over the CONTIGUOUS slot
    pool: (params, draft_params, ck, cv[, kscale, vscale], pos, tok,
    active [Ns], rem [Ns], poison [Ns], key) -> (state', toks
    [Ns, K+1], ncommit [Ns], drafted [Ns], accepted [Ns]).

    One round advances every active slot 1..K+1 tokens: K draft steps
    with ``draft_params`` (optionally truncated to the first
    ``draft_layers`` blocks — early-exit self-drafting reads/writes
    exactly the layers the target shares, so its shallow K/V rows are
    the target's own) propose the window, one target pass verifies all
    K+1 positions, and the longest accepted prefix + the correction
    token commit (section comment above has the exactness and cache-
    safety arguments). ``toks[i, :ncommit[i]]`` are the committed
    tokens (-1 beyond); ``drafted``/``accepted`` feed the engine's
    acceptance metrics and adaptive-K controller as runtime data.
    ``quantized``/``draft_quantized`` mark the respective param trees;
    ``kv_mode`` selects the quantized slot pool exactly as
    make_continuous_decode."""
    from deeplearning4j_tpu.ops.flash_decode import \
        decode_window_attention
    tp, dp = _check_serving_mesh(cfg, mesh, top_k, top_p)
    quantized, kv_mode = _resolve_quant(quantized, kv_mode)
    draft_quantized, _ = _resolve_quant(draft_quantized, None)
    nd = _check_spec(cfg, spec_k, draft_layers)
    if num_slots % dp:
        raise ValueError(f"num_slots {num_slots} not divisible by "
                         f"data axis {dp}")
    specs = _serving_specs(cfg, quantized)
    dspecs = _serving_specs(cfg, draft_quantized)
    h_loc = cfg.n_heads // tp
    d_loc = h_loc * cfg.d_head
    k1 = spec_k + 1
    scale = cfg.d_head ** -0.5

    def draft_phase(dparams, st, pos, tok, act, key, ds0=None,
                    callow=None, ctrans=None):
        """K sequential draft steps through the ordinary slotted block
        fns (draft K/V rows land in the live cache; verify rewrites
        them with target K/V before any of them is attended). With a
        constraint table, each step masks its proposal by the slot's
        DFA state and advances the state per drafted token — the final
        draft state is scratch (verify recomputes the committed one
        from the accepted prefix)."""
        def dstep(carry, _):
            if callow is None:
                st, dpos, dtok = carry
            else:
                st, dpos, dtok, ds = carry
            h = _embed_pending(dparams, cfg, dpos, dtok)
            for layer in range(nd):
                p_l = {kk: vv[layer]
                       for kk, vv in dparams["blocks"].items()}
                if kv_mode is None:
                    h, ck, cv = _local_block_decode_slotted(
                        h, p_l, st[0], st[1], layer, dpos, act, cfg,
                        tp, dp)
                    st = (ck, cv)
                else:
                    h, ck, cv, ksc, vsc = _local_block_decode_slotted_q(
                        h, p_l, *st, layer, dpos, act, cfg, tp, dp,
                        kv_mode)
                    st = (ck, cv, ksc, vsc)
            h = layer_norm(h, dparams["lnfg"], dparams["lnfb"],
                           cfg.eps)
            logits = jnp.matmul(h[:, 0],
                                dparams["Wout"].astype(h.dtype))
            if callow is not None:
                logits = _mask_allow(logits, callow[ds])
            nxt = _sample_slots(logits, dpos + 1, key, dp, temperature,
                                top_k, top_p)
            dtok = jnp.where(act, nxt, dtok)
            dpos = jnp.where(act, dpos + 1, dpos)
            if callow is None:
                return (st, dpos, dtok), nxt
            ds = jnp.where(act, ctrans[ds, nxt], ds)
            return (st, dpos, dtok, ds), nxt

        if callow is None:
            (st, _, _), drafts = lax.scan(dstep, (st, pos, tok), None,
                                          length=spec_k)
        else:
            (st, _, _, _), drafts = lax.scan(
                dstep, (st, pos, tok, ds0), None, length=spec_k)
        return st, jnp.swapaxes(drafts, 0, 1)            # [Ns, K]

    def verify_phase(params, st, pos, tok, act, drafts, key,
                     allow_w=None):
        """ONE target pass over the K+1-token window [pending,
        d_1..d_K]: per-layer it rewrites the window's cache rows with
        target K/V, then attends each window position to s <= pos+j —
        element-for-element the slotted sequential decode's numerics
        (same einsum contraction, NEG_INF mask, f32 softmax, scale
        folds), batched over the window instead of scanned, which is
        the whole bandwidth win. ``allow_w`` [Ns, K+1, V] re-applies
        the constraint mask per window position (the state reached
        after the preceding window tokens), so acceptance compares
        masked target samples against masked drafts — bit-identical to
        the masked sequential engine."""
        g_model = _g_sync("model")
        ns = tok.shape[0]
        rows = jnp.arange(ns)
        dt = cfg.activation_dtype()
        if kv_mode is None:
            ck, cv = st
        else:
            ck, cv, ksc, vsc = st
        s_max = ck.shape[2]
        win = jnp.concatenate([tok[:, None], drafts], axis=1)
        posw = pos[:, None] + jnp.arange(k1, dtype=pos.dtype)[None, :]
        wp = jnp.clip(posw, 0, s_max - 1)
        h = (params["embed"].astype(dt)[win]
             + params["pos"].astype(dt)[
                 jnp.clip(posw, 0, cfg.max_len - 1)])
        for layer in range(cfg.n_layers):
            p = {kk: vv[layer] for kk, vv in params["blocks"].items()}
            x = layer_norm(h, p["ln1g"], p["ln1b"], cfg.eps)
            q = jnp.matmul(x, p["Wq"].astype(x.dtype)) \
                .reshape(ns, k1, h_loc, cfg.d_head)
            kw = jnp.matmul(x, p["Wk"].astype(x.dtype))  # [Ns,K1,Dl]
            vw = jnp.matmul(x, p["Wv"].astype(x.dtype))
            # window-row rewrite: inactive slots rewrite their current
            # rows with themselves (the static-scatter trick);
            # positions past the cache drop (mode="drop" — they can
            # only be beyond the slot's budget, never committed)
            if kv_mode is None:
                k_wr = jnp.where(act[:, None, None],
                                 kw.astype(ck.dtype),
                                 ck[layer][rows[:, None], wp])
                v_wr = jnp.where(act[:, None, None],
                                 vw.astype(cv.dtype),
                                 cv[layer][rows[:, None], wp])
                ck = ck.at[layer, rows[:, None], posw].set(
                    k_wr, mode="drop")
                cv = cv.at[layer, rows[:, None], posw].set(
                    v_wr, mode="drop")
                # fused K+1-window attention: the STACKED caches ride
                # into the primitive (kernel picks the layer plane in
                # its BlockSpec; jnp reference reproduces the old
                # inline masked-softmax bit-for-bit — flash_decode
                # .reference_window_attention holds the algebra)
                a = decode_window_attention(q, ck, cv, pos, h_loc,
                                            scale, layer=layer)
            else:
                from deeplearning4j_tpu.quant.kv import quantize_rows
                kq, ksr = quantize_rows(kw, kv_mode)
                vq, vsr = quantize_rows(vw, kv_mode)
                k_wr = jnp.where(act[:, None, None], kq,
                                 ck[layer][rows[:, None], wp])
                v_wr = jnp.where(act[:, None, None], vq,
                                 cv[layer][rows[:, None], wp])
                ks_wr = jnp.where(act[:, None], ksr,
                                  ksc[layer][rows[:, None], wp, 0])
                vs_wr = jnp.where(act[:, None], vsr,
                                  vsc[layer][rows[:, None], wp, 0])
                ck = ck.at[layer, rows[:, None], posw].set(
                    k_wr, mode="drop")
                cv = cv.at[layer, rows[:, None], posw].set(
                    v_wr, mode="drop")
                ksc = ksc.at[layer, rows[:, None], posw, 0].set(
                    ks_wr, mode="drop")
                vsc = vsc.at[layer, rows[:, None], posw, 0].set(
                    vs_wr, mode="drop")
                # per-row scale folds travel into the fused window
                # primitive (scores * kscale_s, probs * vscale_s —
                # identical multiplication order)
                a = decode_window_attention(
                    q, ck, cv, pos, h_loc, scale, layer=layer,
                    k_scale=ksc[layer, :, :, 0],
                    v_scale=vsc[layer, :, :, 0])
            h = h + g_model(jnp.matmul(a.reshape(ns, k1, d_loc),
                                       p["Wo"].astype(h.dtype)))
            x = layer_norm(h, p["ln2g"], p["ln2b"], cfg.eps)
            h = _local_mlp(h, x, p, cfg, dp, g_model)
        h = layer_norm(h, params["lnfg"], params["lnfb"], cfg.eps)
        logits = jnp.matmul(h, params["Wout"].astype(h.dtype))
        if allow_w is not None:
            logits = _mask_allow(logits, allow_w)
        tgt = _sample_slots(
            logits.reshape(ns * k1, logits.shape[-1]),
            (posw + 1).reshape(-1), key, dp, temperature, top_k,
            top_p).reshape(ns, k1)
        st = (ck, cv) if kv_mode is None else (ck, cv, ksc, vsc)
        return st, tgt

    def body(params, dparams, st, pos, tok, active, rem, poison, key,
             callow=None, ctrans=None, ds0=None):
        act = active & (rem > 0)
        st, drafts = draft_phase(dparams, st, pos, tok, act, key,
                                 ds0=ds0, callow=callow,
                                 ctrans=ctrans)
        # deterministic draft poisoning (runtime data): (d+1) mod V is
        # guaranteed to differ from the model's own proposal, so
        # verification MUST reject — the fault-injection proof that a
        # bad draft pass cannot corrupt committed state
        drafts = jnp.where(poison[:, None],
                           (drafts + 1) % cfg.vocab_size, drafts)
        if callow is None:
            st, tgt = verify_phase(params, st, pos, tok, act, drafts,
                                   key)
            pos, tok, rem, emit, c, drafted, accepted = \
                _spec_accept_commit(spec_k, drafts, tgt, pos, tok,
                                    rem, act)
            return st, pos, tok, emit, c, drafted, accepted
        swin = _c_spec_window(spec_k, ds0, ctrans, drafts)
        st, tgt = verify_phase(params, st, pos, tok, act, drafts, key,
                               allow_w=callow[swin])
        pos, tok, rem, emit, c, drafted, accepted = \
            _spec_accept_commit(spec_k, drafts, tgt, pos, tok, rem,
                                act)
        ds = _c_spec_final(spec_k, swin, ctrans, tgt, c, act, ds0)
        return st, pos, tok, emit, c, drafted, accepted, ds

    if kv_mode is None:
        if constrain:
            def run(params, dparams, ck, cv, pos, tok, active, rem,
                    poison, callow, ctrans, cstate, cseed, cseedval,
                    key):
                ds0 = _c_start(cstate, cseed, cseedval)
                st, pos, tok, emit, c, drafted, accepted, ds = body(
                    params, dparams, (ck, cv), pos, tok, active, rem,
                    poison, key, callow=callow, ctrans=ctrans,
                    ds0=ds0)
                return (*st, pos, tok, emit, c, drafted, accepted, ds)

            in_specs = (specs, dspecs, _SLOT_CACHE_SPEC,
                        _SLOT_CACHE_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC, _CTAB_SPEC,
                        _CTAB_SPEC, _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, P())
            out_specs = (_SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         P("data", None), _SLOT_VEC_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         _SLOT_VEC_SPEC)
        else:
            def run(params, dparams, ck, cv, pos, tok, active, rem,
                    poison, key):
                st, pos, tok, emit, c, drafted, accepted = body(
                    params, dparams, (ck, cv), pos, tok, active, rem,
                    poison, key)
                return (*st, pos, tok, emit, c, drafted, accepted)

            in_specs = (specs, dspecs, _SLOT_CACHE_SPEC,
                        _SLOT_CACHE_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC, P())
            out_specs = (_SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         P("data", None), _SLOT_VEC_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC)
    else:
        if constrain:
            def run(params, dparams, ck, cv, ksc, vsc, pos, tok,
                    active, rem, poison, callow, ctrans, cstate,
                    cseed, cseedval, key):
                ds0 = _c_start(cstate, cseed, cseedval)
                st, pos, tok, emit, c, drafted, accepted, ds = body(
                    params, dparams, (ck, cv, ksc, vsc), pos, tok,
                    active, rem, poison, key, callow=callow,
                    ctrans=ctrans, ds0=ds0)
                return (*st, pos, tok, emit, c, drafted, accepted, ds)

            in_specs = (specs, dspecs, _SLOT_CACHE_SPEC,
                        _SLOT_CACHE_SPEC, _SLOT_SCALE_SPEC,
                        _SLOT_SCALE_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC, _CTAB_SPEC,
                        _CTAB_SPEC, _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, P())
            out_specs = (_SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                         _SLOT_SCALE_SPEC, _SLOT_SCALE_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         P("data", None), _SLOT_VEC_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         _SLOT_VEC_SPEC)
        else:
            def run(params, dparams, ck, cv, ksc, vsc, pos, tok,
                    active, rem, poison, key):
                st, pos, tok, emit, c, drafted, accepted = body(
                    params, dparams, (ck, cv, ksc, vsc), pos, tok,
                    active, rem, poison, key)
                return (*st, pos, tok, emit, c, drafted, accepted)

            in_specs = (specs, dspecs, _SLOT_CACHE_SPEC,
                        _SLOT_CACHE_SPEC, _SLOT_SCALE_SPEC,
                        _SLOT_SCALE_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                        _SLOT_VEC_SPEC, _SLOT_VEC_SPEC, P())
            out_specs = (_SLOT_CACHE_SPEC, _SLOT_CACHE_SPEC,
                         _SLOT_SCALE_SPEC, _SLOT_SCALE_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC,
                         P("data", None), _SLOT_VEC_SPEC,
                         _SLOT_VEC_SPEC, _SLOT_VEC_SPEC)

    sharded = shard_map(run, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=True)
    return jax.jit(sharded)


def make_paged_speculative_decode(cfg: TransformerConfig, mesh: Mesh,
                                  spec_k: int, num_slots: int,
                                  page_size: int, max_pages: int,
                                  num_pages: int,
                                  temperature: float = 0.0,
                                  top_k: int = 0, top_p: float = 1.0,
                                  quantized=None, kv_mode=None,
                                  draft_quantized=None,
                                  draft_layers: int = 0,
                                  constrain: bool = False):
    """Paged-pool speculative round: make_speculative_decode's
    contract with the block table as runtime data — (params,
    draft_params, kp, vp[, kscale, vscale], pos, tok, bt, active, rem,
    poison, key) -> (state', toks, ncommit, drafted, accepted).
    Draft steps go through _local_block_decode_paged(_q); the verify
    window's K/V rows land at (bt[slot, pos_j // ps], pos_j % ps),
    with inactive slots and positions past the slot's mapped pages
    routed to the scratch page (never attended). The engine's
    copy-on-write guard privatizes the whole window's pages before
    the call, so speculative writes are COW-safe by construction."""
    from deeplearning4j_tpu.ops.flash_decode import \
        decode_window_attention
    tp = _check_paged_mesh(cfg, mesh, top_k, top_p, page_size,
                           num_pages, max_pages)
    dp = 1
    quantized, kv_mode = _resolve_quant(quantized, kv_mode)
    draft_quantized, _ = _resolve_quant(draft_quantized, None)
    nd = _check_spec(cfg, spec_k, draft_layers)
    specs = _serving_specs(cfg, quantized)
    dspecs = _serving_specs(cfg, draft_quantized)
    h_loc = cfg.n_heads // tp
    d_loc = h_loc * cfg.d_head
    k1 = spec_k + 1
    s_view = max_pages * page_size
    scale = cfg.d_head ** -0.5

    def draft_phase(dparams, st, bt, pos, tok, act, key, ds0=None,
                    callow=None, ctrans=None):
        def dstep(carry, _):
            if callow is None:
                st, dpos, dtok = carry
            else:
                st, dpos, dtok, ds = carry
            h = _embed_pending(dparams, cfg, dpos, dtok)
            for layer in range(nd):
                p_l = {kk: vv[layer]
                       for kk, vv in dparams["blocks"].items()}
                if kv_mode is None:
                    h, kp, vp = _local_block_decode_paged(
                        h, p_l, st[0], st[1], bt, layer, dpos, act,
                        cfg, tp, dp, page_size)
                    st = (kp, vp)
                else:
                    h, kp, vp, ksc, vsc = _local_block_decode_paged_q(
                        h, p_l, *st, bt, layer, dpos, act, cfg, tp,
                        dp, page_size, kv_mode)
                    st = (kp, vp, ksc, vsc)
            h = layer_norm(h, dparams["lnfg"], dparams["lnfb"],
                           cfg.eps)
            logits = jnp.matmul(h[:, 0],
                                dparams["Wout"].astype(h.dtype))
            if callow is not None:
                logits = _mask_allow(logits, callow[ds])
            nxt = _sample_slots(logits, dpos + 1, key, dp, temperature,
                                top_k, top_p)
            dtok = jnp.where(act, nxt, dtok)
            dpos = jnp.where(act, dpos + 1, dpos)
            if callow is None:
                return (st, dpos, dtok), nxt
            ds = jnp.where(act, ctrans[ds, nxt], ds)
            return (st, dpos, dtok, ds), nxt

        if callow is None:
            (st, _, _), drafts = lax.scan(dstep, (st, pos, tok), None,
                                          length=spec_k)
        else:
            (st, _, _, _), drafts = lax.scan(
                dstep, (st, pos, tok, ds0), None, length=spec_k)
        return st, jnp.swapaxes(drafts, 0, 1)

    def verify_phase(params, st, bt, pos, tok, act, drafts, key,
                     allow_w=None):
        g_model = _g_sync("model")
        ns = tok.shape[0]
        mp = bt.shape[1]
        dt = cfg.activation_dtype()
        if kv_mode is None:
            kp, vp = st
        else:
            kp, vp, ksc, vsc = st
        win = jnp.concatenate([tok[:, None], drafts], axis=1)
        posw = pos[:, None] + jnp.arange(k1, dtype=pos.dtype)[None, :]
        # write routing: inactive slots and positions past the block
        # table land on the scratch page (page 0), like the paged
        # decode/prefill write paths
        lp = jnp.clip(posw // page_size, 0, mp - 1)
        pgw = jnp.where(act[:, None] & (posw < s_view),
                        jnp.take_along_axis(bt, lp, axis=1), 0)
        offw = posw % page_size
        h = (params["embed"].astype(dt)[win]
             + params["pos"].astype(dt)[
                 jnp.clip(posw, 0, cfg.max_len - 1)])
        for layer in range(cfg.n_layers):
            p = {kk: vv[layer] for kk, vv in params["blocks"].items()}
            x = layer_norm(h, p["ln1g"], p["ln1b"], cfg.eps)
            q = jnp.matmul(x, p["Wq"].astype(x.dtype)) \
                .reshape(ns, k1, h_loc, cfg.d_head)
            kw = jnp.matmul(x, p["Wk"].astype(x.dtype))
            vw = jnp.matmul(x, p["Wv"].astype(x.dtype))
            if kv_mode is None:
                kp = kp.at[layer, pgw, offw].set(kw.astype(kp.dtype))
                vp = vp.at[layer, pgw, offw].set(vw.astype(vp.dtype))
                # fused K+1-window attention over the gathered logical
                # view (jnp reference off-TPU reproduces the old
                # inline masked-softmax bit-for-bit; the kernel path
                # DMAs each gathered block once for all window rows)
                kh = _gather_pages(kp[layer], bt, ns, s_view)
                vh = _gather_pages(vp[layer], bt, ns, s_view)
                a = decode_window_attention(q, kh, vh, pos, h_loc,
                                            scale)
            else:
                from deeplearning4j_tpu.quant.kv import quantize_rows
                kq, ksr = quantize_rows(kw, kv_mode)
                vq, vsr = quantize_rows(vw, kv_mode)
                kp = kp.at[layer, pgw, offw].set(kq)
                vp = vp.at[layer, pgw, offw].set(vq)
                ksc = ksc.at[layer, pgw, offw, 0].set(ksr)
                vsc = vsc.at[layer, pgw, offw, 0].set(vsr)
                kh = _gather_pages(kp[layer].astype(jnp.float32), bt,
                                   ns, s_view)
                vh = _gather_pages(vp[layer].astype(jnp.float32), bt,
                                   ns, s_view)
                ksg = _gather_pages(ksc[layer], bt, ns, s_view)[..., 0]
                vsg = _gather_pages(vsc[layer], bt, ns, s_view)[..., 0]
                a = decode_window_attention(q, kh, vh, pos, h_loc,
                                            scale, k_scale=ksg,
                                            v_scale=vsg)
            h = h + g_model(jnp.matmul(a.reshape(ns, k1, d_loc),
                                       p["Wo"].astype(h.dtype)))
            x = layer_norm(h, p["ln2g"], p["ln2b"], cfg.eps)
            h = _local_mlp(h, x, p, cfg, dp, g_model)
        h = layer_norm(h, params["lnfg"], params["lnfb"], cfg.eps)
        logits = jnp.matmul(h, params["Wout"].astype(h.dtype))
        if allow_w is not None:
            logits = _mask_allow(logits, allow_w)
        tgt = _sample_slots(
            logits.reshape(ns * k1, logits.shape[-1]),
            (posw + 1).reshape(-1), key, dp, temperature, top_k,
            top_p).reshape(ns, k1)
        st = (kp, vp) if kv_mode is None else (kp, vp, ksc, vsc)
        return st, tgt

    def body(params, dparams, st, pos, tok, bt, active, rem, poison,
             key, callow=None, ctrans=None, ds0=None):
        act = active & (rem > 0)
        st, drafts = draft_phase(dparams, st, bt, pos, tok, act, key,
                                 ds0=ds0, callow=callow,
                                 ctrans=ctrans)
        drafts = jnp.where(poison[:, None],
                           (drafts + 1) % cfg.vocab_size, drafts)
        if callow is None:
            st, tgt = verify_phase(params, st, bt, pos, tok, act,
                                   drafts, key)
            pos, tok, rem, emit, c, drafted, accepted = \
                _spec_accept_commit(spec_k, drafts, tgt, pos, tok,
                                    rem, act)
            return st, pos, tok, emit, c, drafted, accepted
        swin = _c_spec_window(spec_k, ds0, ctrans, drafts)
        st, tgt = verify_phase(params, st, bt, pos, tok, act, drafts,
                               key, allow_w=callow[swin])
        pos, tok, rem, emit, c, drafted, accepted = \
            _spec_accept_commit(spec_k, drafts, tgt, pos, tok, rem,
                                act)
        ds = _c_spec_final(spec_k, swin, ctrans, tgt, c, act, ds0)
        return st, pos, tok, emit, c, drafted, accepted, ds

    if kv_mode is None:
        if constrain:
            def run(params, dparams, kp, vp, pos, tok, bt, active,
                    rem, poison, callow, ctrans, cstate, cseed,
                    cseedval, key):
                ds0 = _c_start(cstate, cseed, cseedval)
                st, pos, tok, emit, c, drafted, accepted, ds = body(
                    params, dparams, (kp, vp), pos, tok, bt, active,
                    rem, poison, key, callow=callow, ctrans=ctrans,
                    ds0=ds0)
                return (*st, pos, tok, emit, c, drafted, accepted, ds)

            in_specs = (specs, dspecs, _PAGE_POOL_SPEC,
                        _PAGE_POOL_SPEC, _PAGE_VEC_SPEC,
                        _PAGE_VEC_SPEC, _PAGE_BT_SPEC, _PAGE_VEC_SPEC,
                        _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, _CTAB_SPEC,
                        _CTAB_SPEC, _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                        _PAGE_VEC_SPEC, P())
            out_specs = (_PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                         _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, P(None, None),
                         _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                         _PAGE_VEC_SPEC, _PAGE_VEC_SPEC)
        else:
            def run(params, dparams, kp, vp, pos, tok, bt, active,
                    rem, poison, key):
                st, pos, tok, emit, c, drafted, accepted = body(
                    params, dparams, (kp, vp), pos, tok, bt, active,
                    rem, poison, key)
                return (*st, pos, tok, emit, c, drafted, accepted)

            in_specs = (specs, dspecs, _PAGE_POOL_SPEC,
                        _PAGE_POOL_SPEC, _PAGE_VEC_SPEC,
                        _PAGE_VEC_SPEC, _PAGE_BT_SPEC, _PAGE_VEC_SPEC,
                        _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, P())
            out_specs = (_PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                         _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, P(None, None),
                         _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                         _PAGE_VEC_SPEC)
    else:
        if constrain:
            def run(params, dparams, kp, vp, ksc, vsc, pos, tok, bt,
                    active, rem, poison, callow, ctrans, cstate,
                    cseed, cseedval, key):
                ds0 = _c_start(cstate, cseed, cseedval)
                st, pos, tok, emit, c, drafted, accepted, ds = body(
                    params, dparams, (kp, vp, ksc, vsc), pos, tok, bt,
                    active, rem, poison, key, callow=callow,
                    ctrans=ctrans, ds0=ds0)
                return (*st, pos, tok, emit, c, drafted, accepted, ds)

            in_specs = (specs, dspecs, _PAGE_POOL_SPEC,
                        _PAGE_POOL_SPEC, _PAGE_SCALE_SPEC,
                        _PAGE_SCALE_SPEC, _PAGE_VEC_SPEC,
                        _PAGE_VEC_SPEC, _PAGE_BT_SPEC, _PAGE_VEC_SPEC,
                        _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, _CTAB_SPEC,
                        _CTAB_SPEC, _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                        _PAGE_VEC_SPEC, P())
            out_specs = (_PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                         _PAGE_SCALE_SPEC, _PAGE_SCALE_SPEC,
                         _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, P(None, None),
                         _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                         _PAGE_VEC_SPEC, _PAGE_VEC_SPEC)
        else:
            def run(params, dparams, kp, vp, ksc, vsc, pos, tok, bt,
                    active, rem, poison, key):
                st, pos, tok, emit, c, drafted, accepted = body(
                    params, dparams, (kp, vp, ksc, vsc), pos, tok, bt,
                    active, rem, poison, key)
                return (*st, pos, tok, emit, c, drafted, accepted)

            in_specs = (specs, dspecs, _PAGE_POOL_SPEC,
                        _PAGE_POOL_SPEC, _PAGE_SCALE_SPEC,
                        _PAGE_SCALE_SPEC, _PAGE_VEC_SPEC,
                        _PAGE_VEC_SPEC, _PAGE_BT_SPEC, _PAGE_VEC_SPEC,
                        _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, P())
            out_specs = (_PAGE_POOL_SPEC, _PAGE_POOL_SPEC,
                         _PAGE_SCALE_SPEC, _PAGE_SCALE_SPEC,
                         _PAGE_VEC_SPEC, _PAGE_VEC_SPEC, P(None, None),
                         _PAGE_VEC_SPEC, _PAGE_VEC_SPEC,
                         _PAGE_VEC_SPEC)

    sharded = shard_map(run, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=True)
    return jax.jit(sharded)


def serving_param_specs(cfg: TransformerConfig):
    """Megatron layout with serving-specific MoE placement: the
    training specs shard EXPERTS over 'data' (expert parallelism for
    throughput training), but serving shards each expert's FFN hidden
    over 'model' and replicates the expert set — every data rank must
    be able to run whatever experts its tokens route to without an
    all-to-all per decode step."""
    specs = param_specs(cfg)
    if cfg.n_experts > 0:
        specs["blocks"]["router"] = P("pipe", None, None)
        specs["blocks"]["We1"] = P("pipe", None, None, "model")
        specs["blocks"]["We2"] = P("pipe", None, "model", None)
    # serving meshes are validated pipe=1, so the training layout's
    # leading 'pipe' placement is dropped: naming a size-1 manual axis
    # still marks the params VARYING over it, which poisons the scan
    # carry's varying-manual-axes set and is what forced
    # check_rep=False in round 3
    specs["blocks"] = {
        k: P(*(None if a == "pipe" else a for a in sp))
        for k, sp in specs["blocks"].items()}
    return specs


def shard_serving_params(params, cfg: TransformerConfig, mesh: Mesh):
    """Place params for serving — megatron layout (pipe=1 on a
    serving mesh, so the stacked [L, ...] blocks stay whole per
    device while heads/MLP split over 'model'), with the serving MoE
    overrides of serving_param_specs. Quantized trees
    (`quant.model.quantize_params`) are detected and placed with
    their derived specs — one entry point for both."""
    from deeplearning4j_tpu.quant.core import QuantizedTensor
    blocks = params.get("blocks", {}) if isinstance(params, dict) else {}
    q = next((leaf for leaf in list(params.values()) +
              list(blocks.values())
              if isinstance(leaf, QuantizedTensor)), None)
    if q is not None:
        from deeplearning4j_tpu.quant.model import (
            shard_quantized_serving_params)
        return shard_quantized_serving_params(params, cfg, mesh,
                                              mode=q.mode)
    return shard_params(params, cfg, mesh,
                        specs=serving_param_specs(cfg))
