"""Fully-sharded data parallelism (ZeRO-3/FSDP-style).

NET-NEW vs the reference: its three data-parallel modes all keep a FULL
model replica per worker (ParallelWrapper thread replicas,
`ParallelWrapper.java:603`; Spark executors get the whole params
broadcast, `ParameterAveragingTrainingMaster.java`), so model size is
capped by one device's memory. Here parameters, gradients, AND optimizer
state are sharded over the mesh's 'data' axis — per-device memory for
the model + Adam state drops by the axis size — and XLA's SPMD
partitioner (GSPMD) materializes each layer's weights just-in-time with
`all_gather` in forward/backward and reduces gradients straight into the
shards with `reduce_scatter`. This is the scaling-book recipe verbatim:
pick a mesh, annotate shardings, let the compiler place the collectives
on ICI.

No wrapper classes, no gather/scatter hooks: FSDP is a *sharding policy*
over the same traced train step the other strategies use — the whole
module is the leaf-spec chooser plus a jitted Adam step with sharded
in/out shardings.
"""
from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   init_params, loss_fn)
from deeplearning4j_tpu.parallel.optim import (AdamState, adam_update_tree,
                                               init_adam_state)


def fsdp_leaf_spec(shape: Tuple[int, ...], axis_size: int,
                   axis_name: str = "data") -> P:
    """Shard the largest axis divisible by the mesh axis; scalars and
    leaves with no divisible axis stay replicated (their memory is
    negligible — norms/biases)."""
    if not shape or axis_size <= 1:
        return P()
    for i in sorted(range(len(shape)), key=lambda j: -shape[j]):
        if shape[i] >= axis_size and shape[i] % axis_size == 0:
            spec: list = [None] * len(shape)
            spec[i] = axis_name
            return P(*spec)
    return P()


def fsdp_shardings(params, mesh: Mesh, axis_name: str = "data"):
    """NamedSharding pytree for a param (or same-shaped opt-state) tree."""
    size = mesh.shape[axis_name]
    return jax.tree_util.tree_map(
        lambda p: NamedSharding(mesh, fsdp_leaf_spec(jnp.shape(p), size,
                                                     axis_name)), params)


def shard_params_fsdp(params, mesh: Mesh, axis_name: str = "data"):
    """Place a replicated param tree into its FSDP shards."""
    return jax.device_put(params, fsdp_shardings(params, mesh, axis_name))


def init_fsdp_adam_state(params) -> AdamState:
    """Zeros with the params' sharding — `zeros_like` on placed shards
    keeps the sharding, so the optimizer state is born sharded (the
    ZeRO-1 half of the memory win). Same AdamState as the composite
    step (parallel/optim.py)."""
    return init_adam_state(params)


def zero1_partition(n_params: int, n_shards: int) -> List[Tuple[int, int]]:
    """Contiguous ZeRO-1 shard boundaries over a flattened parameter
    vector: ``n_shards`` half-open ``(lo, hi)`` ranges covering
    ``[0, n_params)``, remainder spread over the FIRST shards (the
    np.array_split convention). Deterministic in its inputs — the
    elastic coordinator's resharding contract (ISSUE-18) is that the
    same ``(n_params, n_shards)`` always yields the same cut points,
    so which workers hold which ranges is a pure function of live
    membership SIZE, never of join order or failure history."""
    if n_params < 0:
        raise ValueError(f"n_params must be >= 0, got {n_params}")
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    base, extra = divmod(int(n_params), int(n_shards))
    bounds: List[Tuple[int, int]] = []
    lo = 0
    for i in range(int(n_shards)):
        hi = lo + base + (1 if i < extra else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


def flatten_tree(tree) -> np.ndarray:
    """Flatten a float pytree into ONE contiguous float32 vector in
    canonical (tree_flatten) leaf order — the byte layout the ZeRO-1
    shards slice. Deterministic: dict leaves flatten in sorted-key
    order, so coordinator and every worker agree on offsets."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return np.zeros((0,), dtype=np.float32)
    return np.concatenate(
        [np.asarray(leaf, dtype=np.float32).ravel() for leaf in leaves])


def unflatten_tree(vec: np.ndarray, template):
    """Inverse of `flatten_tree` given a same-structure ``template``
    tree (shapes read from its leaves): split the flat vector back
    into a pytree of float32 numpy arrays."""
    leaves, treedef = jax.tree_util.tree_flatten(template)
    vec = np.asarray(vec, dtype=np.float32)
    total = sum(int(np.prod(jnp.shape(leaf))) for leaf in leaves)
    if vec.size != total:
        raise ValueError(f"flat vector has {vec.size} elements; "
                         f"template needs {total}")
    out, off = [], 0
    for leaf in leaves:
        shape = jnp.shape(leaf)
        n = int(np.prod(shape)) if shape else 1
        out.append(vec[off:off + n].reshape(shape))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def make_fsdp_train_step(cfg: TransformerConfig, mesh: Mesh, *,
                         learning_rate: float = 1e-3,
                         betas: Tuple[float, float] = (0.9, 0.999),
                         eps: float = 1e-8):
    """Jitted Adam train step with params/grads/opt-state sharded over
    'data' and the batch sharded over 'data'. GSPMD inserts the
    all_gathers (weights, just-in-time per layer) and reduce_scatters
    (gradients) — the step body is the plain single-device math."""
    example = jax.eval_shape(lambda k: init_params(cfg, k),
                             jax.random.PRNGKey(0))
    p_shard = fsdp_shardings(example, mesh)
    opt_shard = AdamState(m=p_shard, v=p_shard,
                          count=NamedSharding(mesh, P()))
    batch_shard = NamedSharding(mesh, P("data"))
    b1, b2 = betas

    def step(params, opt: AdamState, tokens, targets):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(cfg, p, tokens, targets))(params)
        count = opt.count + 1
        params, m, v = adam_update_tree(
            params, grads, opt.m, opt.v, count.astype(jnp.float32),
            learning_rate=learning_rate, b1=b1, b2=b2, eps=eps)
        return params, AdamState(m, v, count), loss

    return jax.jit(step,
                   in_shardings=(p_shard, opt_shard, batch_shard,
                                 batch_shard),
                   out_shardings=(p_shard, opt_shard,
                                  NamedSharding(mesh, P())),
                   donate_argnums=(0, 1))
