"""Fully-sharded data parallelism (ZeRO-3/FSDP-style).

NET-NEW vs the reference: its three data-parallel modes all keep a FULL
model replica per worker (ParallelWrapper thread replicas,
`ParallelWrapper.java:603`; Spark executors get the whole params
broadcast, `ParameterAveragingTrainingMaster.java`), so model size is
capped by one device's memory. Here parameters, gradients, AND optimizer
state are sharded over the mesh's 'data' axis — per-device memory for
the model + Adam state drops by the axis size — and XLA's SPMD
partitioner (GSPMD) materializes each layer's weights just-in-time with
`all_gather` in forward/backward and reduces gradients straight into the
shards with `reduce_scatter`. This is the scaling-book recipe verbatim:
pick a mesh, annotate shardings, let the compiler place the collectives
on ICI.

No wrapper classes, no gather/scatter hooks: FSDP is a *sharding policy*
over the same traced train step the other strategies use — the whole
module is the leaf-spec chooser plus a jitted Adam step with sharded
in/out shardings.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   init_params, loss_fn)
from deeplearning4j_tpu.parallel.optim import (AdamState, adam_update_tree,
                                               init_adam_state)


def fsdp_leaf_spec(shape: Tuple[int, ...], axis_size: int,
                   axis_name: str = "data") -> P:
    """Shard the largest axis divisible by the mesh axis; scalars and
    leaves with no divisible axis stay replicated (their memory is
    negligible — norms/biases)."""
    if not shape or axis_size <= 1:
        return P()
    for i in sorted(range(len(shape)), key=lambda j: -shape[j]):
        if shape[i] >= axis_size and shape[i] % axis_size == 0:
            spec: list = [None] * len(shape)
            spec[i] = axis_name
            return P(*spec)
    return P()


def fsdp_shardings(params, mesh: Mesh, axis_name: str = "data"):
    """NamedSharding pytree for a param (or same-shaped opt-state) tree."""
    size = mesh.shape[axis_name]
    return jax.tree_util.tree_map(
        lambda p: NamedSharding(mesh, fsdp_leaf_spec(jnp.shape(p), size,
                                                     axis_name)), params)


def shard_params_fsdp(params, mesh: Mesh, axis_name: str = "data"):
    """Place a replicated param tree into its FSDP shards."""
    return jax.device_put(params, fsdp_shardings(params, mesh, axis_name))


def init_fsdp_adam_state(params) -> AdamState:
    """Zeros with the params' sharding — `zeros_like` on placed shards
    keeps the sharding, so the optimizer state is born sharded (the
    ZeRO-1 half of the memory win). Same AdamState as the composite
    step (parallel/optim.py)."""
    return init_adam_state(params)


def make_fsdp_train_step(cfg: TransformerConfig, mesh: Mesh, *,
                         learning_rate: float = 1e-3,
                         betas: Tuple[float, float] = (0.9, 0.999),
                         eps: float = 1e-8):
    """Jitted Adam train step with params/grads/opt-state sharded over
    'data' and the batch sharded over 'data'. GSPMD inserts the
    all_gathers (weights, just-in-time per layer) and reduce_scatters
    (gradients) — the step body is the plain single-device math."""
    example = jax.eval_shape(lambda k: init_params(cfg, k),
                             jax.random.PRNGKey(0))
    p_shard = fsdp_shardings(example, mesh)
    opt_shard = AdamState(m=p_shard, v=p_shard,
                          count=NamedSharding(mesh, P()))
    batch_shard = NamedSharding(mesh, P("data"))
    b1, b2 = betas

    def step(params, opt: AdamState, tokens, targets):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(cfg, p, tokens, targets))(params)
        count = opt.count + 1
        params, m, v = adam_update_tree(
            params, grads, opt.m, opt.v, count.astype(jnp.float32),
            learning_rate=learning_rate, b1=b1, b2=b2, eps=eps)
        return params, AdamState(m, v, count), loss

    return jax.jit(step,
                   in_shardings=(p_shard, opt_shard, batch_shard,
                                 batch_shard),
                   out_shardings=(p_shard, opt_shard,
                                  NamedSharding(mesh, P())),
                   donate_argnums=(0, 1))
