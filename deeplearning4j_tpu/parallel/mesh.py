"""Device-mesh construction.

The reference pins model replicas to devices by thread affinity
(ParallelWrapper.java:131 via Nd4j AffinityManager). TPU-native: devices form
a logical `jax.sharding.Mesh` with named axes; every parallelism strategy is
a PartitionSpec over those axes, and XLA inserts the collectives that ride
ICI (intra-slice) or DCN (cross-slice).

Axis vocabulary used across the framework:
  data  — data parallelism (batch dim; gradient psum)
  seq   — sequence/context parallelism (time dim; ring attention)
  model — tensor parallelism (hidden/head dims; megatron-style psum)
  pipe  — pipeline parallelism (layer-stage dim; ppermute activations)
  expert— expert parallelism (MoE experts; all_to_all token routing)
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("pipe", "data", "seq", "model", "expert")


def pcast_varying(x, axes):
    """`lax.pcast(x, axes, to="varying")` on jax>=0.7 — marks a constant
    as device-varying over manual mesh axes so it can seed a scan carry
    whose steady state IS varying (the vma type system rejects the
    mismatch otherwise). On older jax the same marking goes through the
    legacy check_rep machinery: adding a zero derived from
    `lax.axis_index(axis)` — unreplicated over that axis by its rep
    rule — drops `axes` from the value's replication set without
    changing its bytes."""
    from jax import lax
    import jax.numpy as jnp
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    marker = None
    for a in axes:
        t = lax.axis_index(a)
        marker = t if marker is None else marker + t
    if marker is None:
        return x
    return x + (marker * 0).astype(x.dtype)


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. Unspecified axes default to 1 (absent)."""
    data: int = 1
    seq: int = 1
    model: int = 1
    pipe: int = 1
    expert: int = 1

    def axis_sizes(self) -> Dict[str, int]:
        return {"pipe": self.pipe, "data": self.data, "seq": self.seq,
                "model": self.model, "expert": self.expert}

    @property
    def n_devices(self) -> int:
        n = 1
        for v in self.axis_sizes().values():
            n *= v
        return n


def make_mesh(spec: Optional[MeshSpec] = None, devices=None, **axes) -> Mesh:
    """Build a Mesh. Axis order is (pipe, data, seq, model, expert) so that
    tensor-parallel collectives (the most latency-sensitive, every-layer ones)
    land on the innermost — physically nearest — devices, and pipeline hops
    (cheapest: one activation ppermute per microbatch) span the outermost.
    Axes of size 1 are kept: PartitionSpecs can always name them, and XLA
    drops the no-op collectives.
    """
    if spec is None:
        spec = MeshSpec(**axes)
    elif axes:
        raise ValueError("pass either a MeshSpec or axis kwargs, not both")
    devices = list(jax.devices()) if devices is None else list(devices)
    n = spec.n_devices
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    sizes = spec.axis_sizes()
    arr = np.array(devices[:n]).reshape([sizes[a] for a in AXES])
    return Mesh(arr, AXES)


def data_parallel_mesh(n: Optional[int] = None, devices=None) -> Mesh:
    """All devices on the 'data' axis — the ParallelWrapper-equivalent
    topology."""
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices) if n is None else n
    return make_mesh(MeshSpec(data=n), devices=devices)
