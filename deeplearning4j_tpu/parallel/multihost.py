"""Multi-host distributed runtime: the DCN half of the communication
backend.

Role parity (SURVEY.md §5.8): the reference's cross-machine transports
are host-side — Aeron UDP parameter server and Spark RPC/shuffle, both
moving parameters as byte arrays between JVMs. The TPU-native backend
has two layers instead: **ICI** collectives inside the compiled program
(psum/all_gather inserted by GSPMD — see parallel/wrapper.py and
parallel/megatron.py), and **DCN** for cross-host process coordination
via the PJRT distributed runtime (jax.distributed): one coordinator,
N processes, each owning its local chips, with `jax.devices()` spanning
the whole job so one Mesh covers every host.

`initialize_multihost` wraps jax.distributed with env-var defaults
(the idiom TPU pod launchers use); `MultiHostLauncher` spawns local
processes for hardware-free testing — the reference's `local[N]` Spark
test trick (BaseSparkTest.java) reborn as real separate processes on a
CPU PJRT backend.
"""
from __future__ import annotations

import inspect
import os
import pickle
import subprocess
import sys
import tempfile
import textwrap
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None,
                         local_device_ids: Optional[Sequence[int]] = None
                         ) -> None:
    """Join the distributed runtime. Arguments default to the standard
    env vars (JAX_COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID) so
    pod launchers can configure by environment alone. On real TPU pods
    jax.distributed.initialize() autodetects everything; explicit args
    are for CPU simulation and bespoke clusters."""
    kwargs: Dict[str, Any] = {}
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    num_processes = num_processes if num_processes is not None else \
        _env_int("JAX_NUM_PROCESSES")
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    process_id = process_id if process_id is not None else \
        _env_int("JAX_PROCESS_ID")
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = list(local_device_ids)
    jax.distributed.initialize(**kwargs)


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v is not None else None


def process_info() -> Dict[str, int]:
    return {"process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "local_device_count": jax.local_device_count(),
            "global_device_count": jax.device_count()}


class MultiHostLauncher:
    """Spawn N local python processes that each join a distributed CPU
    runtime and run `fn()` (pickled), collecting every process's return
    value. Used by tests to prove the DCN path end-to-end without
    hardware."""

    def __init__(self, num_processes: int = 2,
                 devices_per_process: int = 2, port: int = 0):
        self.num_processes = num_processes
        self.devices_per_process = devices_per_process
        if port == 0:
            import socket
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
        self.coordinator = f"127.0.0.1:{port}"

    def run(self, fn: Callable[[], Any], timeout: float = 300.0
            ) -> List[Any]:
        with tempfile.TemporaryDirectory() as td:
            fn_path = Path(td) / "fn.pkl"
            # the fn's defining module (often a test file outside any
            # package) must be importable when the subprocess unpickles
            try:
                fn_dir = str(Path(inspect.getfile(fn)).resolve().parent)
            except (TypeError, OSError):
                fn_dir = ""
            fn_path.write_bytes(pickle.dumps(fn))
            driver = textwrap.dedent(f"""
                import os, pickle, sys
                sys.path.insert(0, {fn_dir!r})
                import jax
                from jax._src import xla_bridge as xb
                xb._backend_factories.pop("axon", None)
                jax.config.update("jax_platforms", "cpu")
                jax.distributed.initialize(
                    coordinator_address="{self.coordinator}",
                    num_processes={self.num_processes},
                    process_id=int(sys.argv[1]))
                fn = pickle.loads(open({str(fn_path)!r}, "rb").read())
                result = fn()
                with open(sys.argv[2], "wb") as f:
                    pickle.dump(result, f)
            """)
            script = Path(td) / "driver.py"
            script.write_text(driver)
            procs = []
            out_paths = []
            # scrub the TPU-tunnel environment: the axon sitecustomize
            # rides PYTHONPATH and claims the single real chip at
            # interpreter startup — subprocesses must be pure CPU
            env = {k: v for k, v in os.environ.items()
                   if k not in ("PYTHONSTARTUP", "JAX_PLATFORMS",
                                "PYTHONPATH")}
            env["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                + " --xla_force_host_platform_device_count"
                                  f"={self.devices_per_process}")
            env["JAX_PLATFORMS"] = "cpu"
            pp = [p for p in os.environ.get("PYTHONPATH", "").split(
                os.pathsep) if p and "axon" not in p]
            pp.insert(0, str(Path(__file__).resolve().parents[2]))
            env["PYTHONPATH"] = os.pathsep.join(pp)
            for pid in range(self.num_processes):
                out = Path(td) / f"out_{pid}.pkl"
                out_paths.append(out)
                procs.append(subprocess.Popen(
                    [sys.executable, str(script), str(pid), str(out)],
                    env=env, stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE))
            results = []
            errors = []
            for pid, p in enumerate(procs):
                try:
                    _, err = p.communicate(timeout=timeout)
                except subprocess.TimeoutExpired:
                    p.kill()
                    _, err = p.communicate()
                    errors.append(f"process {pid}: timeout\n"
                                  f"{err.decode()[-2000:]}")
                    continue
                if p.returncode != 0:
                    errors.append(f"process {pid}: rc={p.returncode}\n"
                                  f"{err.decode()[-2000:]}")
                elif out_paths[pid].exists():
                    results.append(pickle.loads(
                        out_paths[pid].read_bytes()))
            if errors:
                raise RuntimeError("multi-host launch failed:\n"
                                   + "\n".join(errors))
            return results
