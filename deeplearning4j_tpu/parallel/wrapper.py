"""ParallelWrapper — data-parallel training facade.

API parity with the reference's ParallelWrapper
(deeplearning4j-scaleout-parallelwrapper/.../ParallelWrapper.java: builder
:343, fit :125, round-robin dispatch :157-165, averaging :218) and with the
Spark ParameterAveragingTrainingMaster's role (SURVEY.md §3.5), re-designed
TPU-first: instead of N model replicas on N threads with host-staged
`Nd4j.averageAndPropagate` every `averagingFrequency` iterations, the SAME
jitted train step is compiled with the batch sharded over the mesh's 'data'
axis. XLA GSPMD inserts the gradient all-reduce (psum over ICI) inside the
compiled program — synchronous averaging every step at collective speed,
which strictly dominates the reference's periodic averaging (documented
deliberate non-port of the async Aeron mode, SURVEY.md §5.8).
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh


class ParallelWrapper:
    """Data-parallel trainer around a MultiLayerNetwork / ComputationGraph.

    Usage (reference: ParallelWrapper.Builder)::

        pw = ParallelWrapper(net, workers=8)   # or mesh=<Mesh with 'data'>
        pw.fit(iterator)

    ``averaging_frequency`` / ``prefetch_buffer`` are accepted for API parity;
    gradient sync happens every step in-program (see module docstring), and
    prefetch is the iterator's job (AsyncDataSetIterator).
    """

    def __init__(self, model, workers: Optional[int] = None,
                 mesh: Optional[Mesh] = None,
                 averaging_frequency: int = 1,
                 prefetch_buffer: int = 2,
                 report_score_after_averaging: bool = True):
        self.model = model
        self.mesh = mesh if mesh is not None else data_parallel_mesh(workers)
        if "data" not in self.mesh.axis_names:
            raise ValueError("mesh must have a 'data' axis")
        self.workers = int(self.mesh.shape["data"])
        self.averaging_frequency = averaging_frequency  # parity only
        self.prefetch_buffer = prefetch_buffer          # parity only
        self._sharded_step = None

    # ------------------------------------------------------------------
    def _replicated(self):
        return NamedSharding(self.mesh, P())

    def _batch_sharding(self, ndim: int):
        return NamedSharding(self.mesh, P("data", *([None] * (ndim - 1))))

    def _get_step(self, x, y, has_mask: bool):
        # mesh in the key: two wrappers over different meshes must not
        # share compiled shardings through the model's jit cache
        key = ("pw", self.mesh, x.shape, y.shape, has_mask)
        fn = self.model._jit_cache.get(key)
        if fn is None:
            rep = self._replicated()
            fn = self.model._make_train_step(
                in_shardings=(rep, rep, rep, rep,
                              self._batch_sharding(x.ndim),
                              self._batch_sharding(y.ndim),
                              rep,
                              self._batch_sharding(2) if has_mask else None),
                out_shardings=(rep, rep, rep, rep))
            self.model._jit_cache[key] = fn
        return fn

    # ------------------------------------------------------------------
    def fit(self, data, labels=None, mask=None) -> None:
        """Train data-parallel. Accepts the same inputs as model.fit."""
        m = self.model
        if not m._initialized:
            m.init()
        if labels is not None:
            self._fit_batch(jnp.asarray(data), jnp.asarray(labels), mask)
            return
        for l in m.listeners:
            l.on_epoch_start(m)
        for batch in data:
            from deeplearning4j_tpu.nn.multilayer import _unpack_batch
            feats, labs, fmask, lmask = _unpack_batch(batch)
            self._fit_batch(jnp.asarray(feats), jnp.asarray(labs),
                            lmask if lmask is not None else fmask)
        for l in m.listeners:
            l.on_epoch_end(m)
        m.epoch_count += 1
        if hasattr(data, "reset"):
            data.reset()

    def fit_batched(self, xs, ys, epochs: int = 1):
        """Data-parallel scanned training: the staged pool [N, B, ...] is
        sharded over 'data' on the batch dim and the whole multi-epoch
        run is ONE XLA program per call (MultiLayerNetwork.fit_batched
        semantics — same math, the gradient psum rides ICI inside the
        scan). The Spark-equivalent 'epoch wall-clock' fast path."""
        m = self.model
        m._validate_fit_batched(epochs)
        if hasattr(m, "_as_input_dict"):        # ComputationGraph
            xs = m._as_input_dict(xs, m.conf.network_inputs)
            ys = m._as_input_dict(ys, m.conf.network_outputs)
        else:                                   # MultiLayerNetwork
            xs = jnp.asarray(xs)
            ys = jnp.asarray(ys)
        tree = jax.tree_util.tree_map
        for leaf in jax.tree_util.tree_leaves((xs, ys)):
            if leaf.shape[1] % self.workers:
                raise ValueError(
                    f"batch dim {leaf.shape[1]} must divide by workers "
                    f"{self.workers} (GSPMD even sharding)")
        shapes = tuple(l.shape for l in
                       jax.tree_util.tree_leaves((xs, ys)))
        key = ("pw-scanfit", self.mesh, epochs, shapes)
        fn = m._jit_cache.get(key)
        if fn is None:
            rep = self._replicated()

            def pool_shard(a):
                return NamedSharding(
                    self.mesh, P(None, "data", *([None] * (a.ndim - 2))))

            fn = m._make_scan_fit(
                epochs,
                in_shardings=(rep, rep, rep, rep, tree(pool_shard, xs),
                              tree(pool_shard, ys), rep),
                out_shardings=(rep, rep, rep, rep))
            m._jit_cache[key] = fn
        return m._run_scan_fit(fn, xs, ys)

    def output_batched(self, xs):
        """Data-parallel scanned inference: the staged pool [N, B, ...]
        shards over 'data' on the batch dim; one compiled program per
        pool (the inference face of fit_batched). MultiLayerNetwork
        pools only (the DAG runtime has its own output_batched)."""
        m = self.model
        if not hasattr(m, "_make_scan_out"):
            raise ValueError(
                "ParallelWrapper.output_batched supports "
                "MultiLayerNetwork pools; use "
                "ComputationGraph.output_batched for the DAG runtime")
        if not m._initialized:
            m.init()
        xs = jnp.asarray(xs)
        if xs.shape[1] % self.workers:
            raise ValueError(
                f"batch dim {xs.shape[1]} must divide by workers "
                f"{self.workers} (GSPMD even sharding)")
        key = ("pw-output-scan", self.mesh, xs.shape)
        fn = m._jit_cache.get(key)
        if fn is None:
            rep = self._replicated()
            pool = NamedSharding(
                self.mesh, P(None, "data", *([None] * (xs.ndim - 2))))
            fn = m._make_scan_out(in_shardings=(rep, rep, pool))
            m._jit_cache[key] = fn
        return fn(m.params, m.state, xs)

    def _fit_batch(self, x, y, mask=None) -> None:
        m = self.model
        n = x.shape[0]
        if n % self.workers != 0:
            # GSPMD needs an evenly divisible batch; drop the remainder like
            # the reference drops the last partial round-robin minibatch.
            keep = n - (n % self.workers)
            if keep == 0:
                return
            x, y = x[:keep], y[:keep]
            if mask is not None:
                mask = jnp.asarray(mask)[:keep]
        step = self._get_step(x, y, mask is not None)
        key = jax.random.fold_in(
            jax.random.PRNGKey(m.conf.training.seed), m.iteration_count)
        m.params, m.state, m.updater_state, score = step(
            m.params, m.state, m.updater_state, m.iteration_count, x, y, key,
            None if mask is None else jnp.asarray(mask))
        m.score_value = score
        for l in m.listeners:
            if hasattr(l, "record_batch"):
                l.record_batch(int(x.shape[0]))
            l.iteration_done(m, m.iteration_count, m.score_value)
        m.iteration_count += 1

    # reference API aliases -------------------------------------------------
    def shutdown(self) -> None:  # thread-pool teardown has no TPU analog
        pass
