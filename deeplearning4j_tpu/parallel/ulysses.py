"""Ulysses-style all-to-all sequence/context parallelism.

NET-NEW vs the reference (no attention, no sequence parallelism;
SURVEY.md §5.7 — its only long-sequence tool is truncated BPTT,
`MultiLayerNetwork.java:1119`). Complements ring attention
(parallel/ring.py) as the second first-class long-context strategy:

- **ring**: K/V blocks rotate neighbor-to-neighbor (`ppermute`) while
  queries stay put — communication O(T·D) per hop, overlapped with
  compute; heads stay whole, so it works for any head count.
- **ulysses** (this module): two `all_to_all` collectives re-shard the
  activations from sequence-sharded to head-sharded and back, so each
  device runs ordinary (flash) attention over the FULL sequence for a
  subset of heads. Communication is 2 all-to-alls of the qkv/out tensors
  — cheaper than a full all-gather by the axis size, and the inner
  attention kernel is the unmodified single-device one (the Pallas flash
  path on TPU). Requires n_local_heads % axis_size == 0.

Both run inside `shard_map` over the mesh's 'seq' axis and are exact —
bitwise-equivalent math to single-device causal attention up to float
reassociation.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax import lax

Array = jax.Array


def ulysses_attention(q: Array, k: Array, v: Array, axis_name: str, *,
                      causal: bool = True,
                      scale: Optional[float] = None) -> Array:
    """All-to-all sequence-parallel attention inside a `shard_map`.

    q, k, v: LOCAL sequence blocks [B, Tl, H, Dh]; global sequence length
    is Tl * axis_size. Returns the local output block [B, Tl, H, Dh].

    The first all_to_all splits the head axis across the 'seq' ranks and
    concatenates the sequence blocks (rank order == sequence order), so
    each rank holds [B, T, H/s, Dh] with the full sequence; the inverse
    all_to_all restores [B, Tl, H, Dh] afterwards.
    """
    s = lax.psum(1, axis_name)
    if q.shape[2] % s != 0:
        raise ValueError(
            f"ulysses_attention: local head count {q.shape[2]} not "
            f"divisible by '{axis_name}' axis size {s}")

    def seq_to_heads(x):
        # [B, Tl, H, Dh] -> [B, Tl*s, H/s, Dh]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        # [B, T, H/s, Dh] -> [B, T/s, H, Dh]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    from deeplearning4j_tpu.nn.layers.attention import dot_product_attention
    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = dot_product_attention(qg, kg, vg, causal=causal, scale=scale)
    return heads_to_seq(out)
