"""Ring attention — sequence/context parallelism.

NET-NEW vs the reference (no attention, no sequence parallelism; SURVEY.md
§5.7): the sequence axis is sharded over the mesh's 'seq' axis and K/V blocks
rotate around the ring via `lax.ppermute` while each device accumulates its
queries' attention with an online (flash-style) softmax. Communication is
neighbor-to-neighbor — exactly the ICI-friendly pattern — and compute for the
current block overlaps the next block's transfer inside the XLA schedule.

Causality is applied on GLOBAL positions (block offsets from
`lax.axis_index`), so the math matches single-device causal attention
exactly; fully-masked future blocks contribute nothing because the running
max starts from the local (always partially valid) diagonal block.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array

NEG_INF = -1e30


def ring_attention(q: Array, k: Array, v: Array, axis_name: str, *,
                   causal: bool = True,
                   scale: Optional[float] = None) -> Array:
    """Blockwise ring attention inside a `shard_map`.

    q, k, v: LOCAL blocks [B, Tl, H, Dh]; the global sequence length is
    Tl * axis_size. Returns the local output block [B, Tl, H, Dh].
    Accumulation is float32 throughout.
    """
    s = lax.psum(1, axis_name)          # ring size (static under jit)
    idx = lax.axis_index(axis_name)
    b, tl, h, dh = q.shape
    scale = (1.0 / jnp.sqrt(dh)) if scale is None else scale
    q32 = q.astype(jnp.float32)
    q_off = idx * tl
    qpos = q_off + jnp.arange(tl)

    # carry: running max m [B,H,Tl], normalizer l [B,H,Tl],
    # accumulator acc [B,H,Tl,Dh], and the rotating k/v blocks.
    # pcast: the initial accumulators are constants, but the scan carry is
    # device-varying over the ring axis — the vma type system requires the
    # init to be marked varying too.
    from deeplearning4j_tpu.parallel.mesh import pcast_varying

    def vary(x):
        return pcast_varying(x, (axis_name,))

    m0 = vary(jnp.full((b, h, tl), NEG_INF, jnp.float32))
    l0 = vary(jnp.zeros((b, h, tl), jnp.float32))
    acc0 = vary(jnp.zeros((b, h, tl, dh), jnp.float32))
    perm = [(i, (i + 1) % s) for i in range(s)]

    def step(carry, sidx):
        m, l, acc, kb, vb = carry
        kv_idx = (idx - sidx) % s
        kpos = kv_idx * tl + jnp.arange(tl)
        scores = jnp.einsum("bthd,bshd->bhts", q32,
                            kb.astype(jnp.float32)) * scale
        if causal:
            cm = qpos[:, None] >= kpos[None, :]
            scores = jnp.where(cm[None, None], scores, NEG_INF)
        blk_max = jnp.max(scores, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        # guard: rows with no valid key yet keep exp(NEG_INF-NEG_INF)=1 from
        # poisoning l — mask p where scores are NEG_INF
        p = jnp.exp(scores - new_m[..., None])
        p = jnp.where(scores <= NEG_INF / 2, 0.0, p)
        corr = jnp.exp(m - new_m)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhts,bshd->bhtd", p, vb.astype(jnp.float32))
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (new_m, l, acc, kb, vb), None

    (m, l, acc, _, _), _ = lax.scan(step, (m0, l0, acc0, k, v),
                                    jnp.arange(s))
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return jnp.einsum("bhtd->bthd", out).astype(q.dtype)
