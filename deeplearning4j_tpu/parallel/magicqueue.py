"""Device-affinity-aware minibatch queue.

Parity with the reference (reference: deeplearning4j-core/.../
parallelism/MagicQueue.java:26 — a BlockingQueue<DataSet> that
partitions incoming batches into per-device internal queues using the
ND4J AffinityManager, so each multi-GPU Trainer thread polls batches
pinned to its own device; parallelism/AsyncIterator.java — background
iterator thread feeding it).

TPU reshaping: device affinity is by local-device ordinal
(`jax.local_devices()`), and the common consumer is `ParallelWrapper`'s
sharded step, which wants one *global* batch sharded over the mesh
rather than N independent per-device batches — so alongside the
reference-shaped `put`/`poll(device)` API there is `next_global()`,
which takes one batch from every bucket and stacks them for a
batch-sharded step.
"""
from __future__ import annotations

import queue
import threading
from typing import Iterable, List, Optional

import numpy as np


class MagicQueue:
    """Round-robin partitioned blocking queue (`MagicQueue.java:26`)."""

    def __init__(self, num_devices: Optional[int] = None,
                 capacity_per_device: int = 8):
        if num_devices is None:
            try:
                import jax
                num_devices = max(1, jax.local_device_count())
            except Exception:  # jax unavailable (pure host-side use)
                num_devices = 1
        self.num_devices = num_devices
        self._buckets: List[queue.Queue] = [
            queue.Queue(maxsize=capacity_per_device)
            for _ in range(num_devices)]
        self._next = 0
        self._lock = threading.Lock()

    def put(self, item, timeout: Optional[float] = None) -> None:
        """Add a batch; it lands in the next device bucket
        (round-robin interleave, `MagicQueue.java` put/add path)."""
        with self._lock:
            idx = self._next
            self._next = (self._next + 1) % self.num_devices
        self._buckets[idx].put(item, timeout=timeout)

    add = put

    def poll(self, device: int = 0, timeout: Optional[float] = None):
        """Take the next batch for `device`; None on timeout
        (`MagicQueue.java` poll — consumer thread pinned to a device)."""
        try:
            return self._buckets[device].get(
                block=timeout is not None, timeout=timeout)
        except queue.Empty:
            return None

    def poll_nowait(self, device: int = 0):
        try:
            return self._buckets[device].get_nowait()
        except queue.Empty:
            return None

    def size(self, device: Optional[int] = None) -> int:
        """Per-device depth, or (device=None) the min across buckets —
        the number of complete all-device rounds available (matches the
        reference's size() semantics of 'batches per trainer')."""
        if device is not None:
            return self._buckets[device].qsize()
        return min(b.qsize() for b in self._buckets)

    def is_empty(self) -> bool:
        return all(b.empty() for b in self._buckets)

    def next_global(self, timeout: Optional[float] = None):
        """Take one batch from every device bucket and stack features/
        labels along the batch axis — the global batch a sharded-jit
        step consumes (TPU-native composition; no reference analog).

        All-or-nothing: if any bucket can't supply a batch (immediately
        with the default timeout=None, else within `timeout` seconds),
        already-dequeued batches are returned to their buckets and
        queue.Empty is raised — a partial tail-of-epoch round is never
        silently dropped and never deadlocks the training loop."""
        items = []
        try:
            for d in range(self.num_devices):
                items.append(self._buckets[d].get(
                    block=timeout is not None, timeout=timeout))
        except queue.Empty:
            for d, item in enumerate(items):
                self._buckets[d].put_nowait(item)
            raise
        first = items[0]
        if hasattr(first, "features"):
            feats = np.concatenate([np.asarray(i.features) for i in items], 0)
            labels = np.concatenate([np.asarray(i.labels) for i in items], 0)
            return type(first)(feats, labels)
        return np.concatenate([np.asarray(i) for i in items], 0)


class AsyncIterator:
    """Background-thread iterator feeding a bounded queue
    (`parallelism/AsyncIterator.java` — decouples host-side data prep
    from the training loop)."""

    _DONE = object()

    def __init__(self, base: Iterable, buffer_size: int = 8):
        self._queue: queue.Queue = queue.Queue(maxsize=buffer_size)
        self._exc: Optional[BaseException] = None

        def worker():
            try:
                for item in base:
                    self._queue.put(item)
            except BaseException as e:  # propagate to consumer
                self._exc = e
            finally:
                self._queue.put(self._DONE)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        if getattr(self, "_finished", False):
            raise StopIteration
        item = self._queue.get()
        if item is self._DONE:
            self._finished = True
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        return item
