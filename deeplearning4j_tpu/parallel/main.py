"""Data-parallel training CLI.

Parity with the reference's ParallelWrapperMain (reference:
deeplearning4j-scaleout-parallelwrapper/.../parallelism/main/
ParallelWrapperMain.java + DataSetIteratorProviderFactory.java: load a
saved model, obtain an iterator from a named factory class, train
data-parallel, save). The factory here is any ``module:callable``
returning a DataSetIterator — the Python analog of naming a
DataSetIteratorProviderFactory class on the command line.

    python -m deeplearning4j_tpu.parallel.main \\
        --model-path model.zip \\
        --iterator-provider mypkg.data:make_train_iterator \\
        --workers 8 --epochs 2 --model-output trained.zip
"""
from __future__ import annotations

import argparse
import importlib
from typing import Any, Callable


def load_provider(spec: str) -> Callable[[], Any]:
    """Resolve 'module.path:attr' to the iterator factory callable."""
    if ":" not in spec:
        raise ValueError(
            f"iterator provider '{spec}' must be 'module:callable' "
            "(the DataSetIteratorProviderFactory analog)")
    mod_name, attr = spec.split(":", 1)
    mod = importlib.import_module(mod_name)
    factory = getattr(mod, attr)
    if not callable(factory):
        raise TypeError(f"{spec} is not callable")
    return factory


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="Data-parallel training of a saved model "
                    "(ParallelWrapperMain analog)")
    ap.add_argument("--model-path", required=True,
                    help="saved model zip (ModelSerializer format)")
    ap.add_argument("--iterator-provider", required=True,
                    help="module:callable returning a DataSetIterator")
    ap.add_argument("--workers", type=int, default=None,
                    help="data-parallel replicas (default: all devices)")
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--model-output", default=None,
                    help="where to save the trained model "
                         "(default: overwrite --model-path)")
    args = ap.parse_args(argv)

    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    from deeplearning4j_tpu.util.model_guesser import ModelGuesser
    from deeplearning4j_tpu.util.model_serializer import write_model

    net = ModelGuesser.load_model_guess(args.model_path)
    factory = load_provider(args.iterator_provider)
    pw = ParallelWrapper(net, workers=args.workers)
    for epoch in range(args.epochs):
        # fresh iterator per epoch: one-shot providers (generators)
        # would otherwise silently train only epoch 0
        pw.fit(factory())
        print(f"epoch {epoch}: score {float(net.score_value):.6f}")
    out = args.model_output or args.model_path
    write_model(net, out)
    print(f"saved trained model to {out}")


if __name__ == "__main__":
    main()
