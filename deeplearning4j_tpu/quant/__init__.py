"""Quantized inference: int8/fp8 weight-only matmuls + quantized KV.

The continuous-batching engine (PR 4) made serving slot-bound: capacity
is limited by HBM spent on float32 weights at rest and activation-dtype
slot-pool KV caches. On a memory-bound decode path, halving bytes
multiplies tokens/sec — the classic reduced-precision lever (cuDNN,
arxiv 1410.0759). This package is that lever for the flagship LM:

- `quant.core` — `QuantizedTensor` (a pytree of int8/fp8 values +
  per-channel float32 scales), symmetric absmax `quantize` /
  `dequantize`, `fake_quant` for accuracy studies, and
  `quantized_matmul` (dequantize-on-the-fly into the activation
  dtype). The fp8 `e4m3` variant sits behind `fp8_supported()` and
  falls back to int8 on CPU — `resolve_mode` owns that decision.
- `quant.model` — `quantize_params` for transformer checkpoints
  (per-output-channel scales on every W matrix and the embedding;
  norms/biases/positional/router stay float32), spec derivation so a
  quantized tree shards onto a serving mesh, and `param_bytes` for
  HBM accounting.
- `quant.kv` — per-row quantization for the slot-pool KV cache:
  `init_quant_slot_state` allocates int8 caches + per-(layer, slot,
  position, model-rank) float32 scales so the same slot count costs
  ~4x fewer cache bytes.

Integration points: `TransformerConfig.cache_dtype` (bf16 caches with
f32 activations — the non-quantized half-step),
`parallel.serving.make_continuous_{prefill,decode}(kv_mode=...)`,
`serving.InferenceEngine(quantize=..., kv_quantize=...)`, checkpoint
round-trip of QuantizedTensor trees through the manifest, and the
`quant_decode` flagship bench arm. Accuracy envelope and layout:
docs/quantization.md.
"""
from deeplearning4j_tpu.quant.core import (  # noqa: F401
    QuantizedTensor, dequantize, fake_quant, fp8_supported, quantize,
    quantized_matmul, resolve_mode)
from deeplearning4j_tpu.quant.model import (  # noqa: F401
    dequantize_params, param_bytes, quantize_params, quantize_specs)
from deeplearning4j_tpu.quant.kv import (  # noqa: F401
    init_quant_slot_state, quantize_rows, slot_pool_bytes)
