"""Transformer checkpoint quantization: per-output-channel weight trees.

`quantize_params` turns a float `models/transformer.init_params` tree
into a drop-in quantized one: every large matmul operand — Wq/Wk/Wv/Wo,
the MLP W1/W2 (or MoE We1/We2), the embedding table, and the output
projection — becomes a `QuantizedTensor` with per-output-channel
float32 scales, while everything numerically fragile or tiny stays
float32 (layer norms, biases, the positional table, the MoE router:
routing decisions are argmax-over-logits and a mis-rounded router
flips token→expert assignment, the one discrete decision in the
block).

Axis conventions (see quant/core.py for the scales layout contract):

- 2-D mats ``[in, out]`` and stacked ``[L, in, out]`` /
  ``[L, E, in, out]`` quantize over the INPUT axis (``-2``): one scale
  per output channel, so the dequantized column reproduces that
  channel's dynamic range.
- the embedding ``[V, D]`` quantizes over ``-1``: one scale per token
  ROW (a row is the output of the lookup, so the row is the channel).

`quantize_specs` mirrors the same walk over a PartitionSpec tree so a
quantized tree can be placed on a serving mesh: the value keeps the
float weight's spec; the scale drops any sharding on its size-1
(reduced) axis — sharding a size-1 dim is ill-formed — and keeps the
channel axis's placement, which is exactly what keeps each model-rank's
local dequantization self-contained (its channel shard pairs with its
scale shard; no collective touches scales, ever).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.quant.core import (QuantizedTensor, dequantize,
                                           quantize, resolve_mode)

# weight name -> (rank, quantization axis). Rank rides along so spec
# derivation can normalize short PartitionSpecs without a params tree.
_TOP_RULES: Dict[str, tuple] = {"embed": (2, -1), "Wout": (2, -2)}
_BLOCK_RULES: Dict[str, tuple] = {
    "Wq": (3, -2), "Wk": (3, -2), "Wv": (3, -2), "Wo": (3, -2),
    "W1": (3, -2), "W2": (3, -2),
    "We1": (4, -2), "We2": (4, -2),
}


def quantize_params(params: Dict[str, Any],
                    mode: str = "int8") -> Dict[str, Any]:
    """Quantize a float transformer param tree (weights + embedding;
    norms/biases/pos/router untouched). ``mode`` goes through
    `resolve_mode`, so "fp8" silently lands on int8 where fp8 isn't
    supported. Idempotent-hostile by design: feeding an already
    quantized tree raises (re-quantizing quantized values would
    silently compound error)."""
    m = resolve_mode(mode)
    if m is None:
        raise ValueError("quantize_params needs a mode ('int8'/'fp8')")
    out = dict(params)
    for name, (_, ax) in _TOP_RULES.items():
        if name in out:
            if isinstance(out[name], QuantizedTensor):
                raise ValueError(f"param {name!r} is already quantized")
            out[name] = quantize(out[name], axis=ax, mode=m)
    blocks = dict(params["blocks"])
    for name, (_, ax) in _BLOCK_RULES.items():
        if name in blocks:
            if isinstance(blocks[name], QuantizedTensor):
                raise ValueError(f"param blocks.{name!r} is already "
                                 "quantized")
            blocks[name] = quantize(blocks[name], axis=ax, mode=m)
    out["blocks"] = blocks
    return out


def dequantize_params(params: Dict[str, Any],
                      dtype=jnp.float32) -> Dict[str, Any]:
    """Dense float tree from a (possibly partially) quantized one —
    the accuracy-study inverse of `quantize_params`."""
    return jax.tree_util.tree_map(
        lambda leaf: (dequantize(leaf, dtype)
                      if isinstance(leaf, QuantizedTensor) else leaf),
        params,
        is_leaf=lambda x: isinstance(x, QuantizedTensor))


def _scale_spec(spec: P, rank: int, axis: int) -> P:
    """The scale's PartitionSpec: the value's spec normalized to full
    rank, with the reduced (size-1) axis forced unsharded."""
    entries = list(spec) + [None] * (rank - len(spec))
    entries[axis % rank] = None
    return P(*entries)


def quantize_specs(specs: Dict[str, Any],
                   mode: str = "int8") -> Dict[str, Any]:
    """Mirror `quantize_params` over a PartitionSpec tree: quantized
    weight names become `QuantizedTensor(value_spec, scale_spec)`
    nodes (same treedef as the quantized params, including the mode
    aux), everything else passes through. Feed it
    `parallel.serving.serving_param_specs(cfg)` to get the in_specs /
    placement tree for a quantized serving tree."""
    m = resolve_mode(mode)
    if m is None:
        raise ValueError("quantize_specs needs a mode ('int8'/'fp8')")
    out = dict(specs)
    for name, (rank, ax) in _TOP_RULES.items():
        if name in out:
            out[name] = QuantizedTensor(
                out[name], _scale_spec(out[name], rank, ax), m)
    blocks = dict(specs["blocks"])
    for name, (rank, ax) in _BLOCK_RULES.items():
        if name in blocks:
            blocks[name] = QuantizedTensor(
                blocks[name], _scale_spec(blocks[name], rank, ax), m)
    out["blocks"] = blocks
    return out


def shard_quantized_serving_params(params_q: Dict[str, Any], cfg,
                                   mesh: Mesh,
                                   mode: str = "int8"):
    """Place a quantized tree on a serving mesh: the serving layout's
    specs, run through `quantize_specs`, applied leaf-by-leaf (values
    and scales each get their own NamedSharding)."""
    from deeplearning4j_tpu.parallel.serving import serving_param_specs
    specs_q = quantize_specs(serving_param_specs(cfg), mode=mode)
    return jax.tree_util.tree_map(
        lambda p, sp: jax.device_put(p, NamedSharding(mesh, sp)),
        params_q, specs_q)


def draft_tree(params: Dict[str, Any], draft: str, cfg, mesh: Mesh,
               base_mode: Optional[str] = None):
    """Build the DRAFT param tree for self-speculative decoding
    (serving/engine.py `EngineConfig(draft=)`), from the engine's
    live serving tree. Returns (draft_params, draft_quantized,
    draft_layers):

    - ``"int8"`` (the default drafter) — the int8-quantized weight
      tree: quantize the live float tree on the mesh (scales shard
      with their channels via `shard_quantized_serving_params`). When
      the engine is ALREADY weight-quantized the live tree IS the
      cheap drafter — it is shared, not re-quantized (requantizing
      quantized values would compound error), so draft == target and
      greedy acceptance is 100% by construction.
    - ``"self"`` — the target tree itself (zero extra HBM; acceptance
      is 100% at any temperature — the exactness-test drafter, and
      the honest baseline for measuring pure verify-batching wins).
    - ``"layers:N"`` — early-exit self-drafting: the SAME tree run
      through only its first N blocks + the final norm/output head.
      Shallow layers' K/V are bit-identical to the target's own, so
      draft cache writes cost nothing to correctness; draft step cost
      scales ~N/L.
    """
    draft = str(draft)
    if draft == "self":
        return params, base_mode, 0
    if draft.startswith("layers:"):
        try:
            n = int(draft.split(":", 1)[1])
        except ValueError:
            raise ValueError(f"malformed draft spec {draft!r}: "
                             "expected 'layers:<int>'")
        if not 0 < n <= cfg.n_layers:
            raise ValueError(f"draft layers {n} out of "
                             f"(0, {cfg.n_layers}]")
        return params, base_mode, n
    try:
        mode = resolve_mode(draft)
    except ValueError:
        mode = None
    if mode is None:
        raise ValueError(f"unknown draft spec {draft!r}: expected "
                         "'int8'/'fp8', 'self', or 'layers:N'")
    if base_mode is not None:
        # the engine's weights are already quantized — they ARE the
        # cheap drafter; share the tree
        return params, base_mode, 0
    qp = quantize_params(params, mode=mode)
    return (shard_quantized_serving_params(qp, cfg, mesh, mode=mode),
            mode, 0)


def param_bytes(tree) -> int:
    """At-rest bytes of a param tree (quantized or float): the sum of
    every leaf's nbytes — QuantizedTensor nodes contribute values AND
    scales (they flatten to both). The `serving_param_bytes` gauge's
    backing computation."""
    return int(sum(int(leaf.nbytes)
                   for leaf in jax.tree_util.tree_leaves(tree)
                   if hasattr(leaf, "nbytes")))


def max_logit_divergence(cfg, params_f: Dict[str, Any],
                         params_q: Dict[str, Any], tokens,
                         dtype=None) -> float:
    """max |logits_float - logits_quantized| over a token batch — the
    scalar the accuracy tests and the quant_decode bench arm report.
    Runs both trees through the SAME `forward` so the only delta is
    the weights' precision."""
    from deeplearning4j_tpu.models.transformer import forward
    toks = jnp.asarray(tokens, jnp.int32)
    lf = forward(cfg, params_f, toks).astype(jnp.float32)
    lq = forward(cfg, params_q, toks).astype(jnp.float32)
    return float(jnp.max(jnp.abs(lf - lq)))
