"""QuantizedTensor: symmetric absmax weight quantization primitives.

Layout contract
---------------
A `QuantizedTensor` holds `values` (int8, or fp8 `e4m3` where the
backend supports it) and `scales` (float32) with the SAME rank as
`values`: every quantized (reduction) axis is kept as size 1 in
`scales`, so dequantization is a plain broadcast multiply —
``values.astype(f32) * scales`` — with no axis bookkeeping at the use
site. Per-output-channel weight quantization of ``W [in, out]`` stores
``scales [1, out]``; the stacked block weights ``[L, in, out]`` store
``[L, 1, out]`` so `lax.scan` over the leading layer axis slices
values and scales in lockstep.

The class is registered as a pytree WITH KEY PATHS, which is what
makes a quantized tree a drop-in `params` argument everywhere trees
flow: `jax.jit` / `shard_map` trace through it, `lax.scan` scans it,
and `util/checkpointing.py`'s manifest writer flattens it into
addressable leaves (`.../Wq/.values`, `.../Wq/.scales`) that
round-trip through `save_tree`/`restore_tree` with CRC + dtype
verification.

Why symmetric absmax: weights are zero-centered, so a zero-point buys
nothing while costing an add on every dequant; absmax per OUTPUT
channel keeps each channel's quantization step proportional to its own
dynamic range (the per-tensor variant loses whole channels when one
outlier channel stretches the grid). Error bound: for int8 the
round-to-nearest step is ``scale = absmax/127``, so
``|x - dequant(quant(x))| <= scale/2`` elementwise —
tests/test_quant.py asserts exactly that.

fp8: the `e4m3` variant (`mode="fp8"`) maps absmax to ±448 (the e4m3
finite max) and lets the cast do the rounding. It sits behind
`fp8_supported()` — MXU-era TPU/GPU backends only; `resolve_mode`
falls back to int8 elsewhere (CPU ml_dtypes emulation is correct but
defeats the purpose and is painfully slow), so every call site can ask
for "fp8" unconditionally.
"""
from __future__ import annotations

from typing import Any, Tuple, Union

import jax
import jax.numpy as jnp

Array = jax.Array

# quantized-grid endpoints: int8 uses the symmetric [-127, 127] range
# (dropping -128 keeps the grid symmetric so negation is exact); e4m3's
# largest finite value is 448
INT8_QMAX = 127.0
FP8_QMAX = 448.0

_MODES = ("int8", "fp8")


def fp8_supported() -> bool:
    """True when fp8 `e4m3` quantization is worth using: the dtype
    exists in this jax AND the default backend has hardware-ish fp8
    (TPU/GPU). CPU runs e4m3 through ml_dtypes emulation — correct but
    slower than the float path it is supposed to beat — so it reports
    False and `resolve_mode` falls back to int8."""
    if not hasattr(jnp, "float8_e4m3fn"):
        return False
    try:
        backend = jax.default_backend()
    except Exception:  # pragma: no cover - no backend at all
        return False
    return backend in ("tpu", "gpu")


def resolve_mode(mode: Union[str, None]) -> Union[str, None]:
    """Normalize a requested quantization mode against this backend:
    None passes through (no quantization), "int8" is always available,
    "fp8" degrades to "int8" when `fp8_supported()` is False — the
    capability check every integration point routes through."""
    if mode is None or mode == "":
        return None
    if mode not in _MODES:
        raise ValueError(f"unknown quantization mode {mode!r}: "
                         f"expected one of {_MODES} or None")
    if mode == "fp8" and not fp8_supported():
        return "int8"
    return mode


def _qmax(mode: str) -> float:
    return INT8_QMAX if mode == "int8" else FP8_QMAX


def _qdtype(mode: str):
    return jnp.int8 if mode == "int8" else jnp.float8_e4m3fn


@jax.tree_util.register_pytree_with_keys_class
class QuantizedTensor:
    """int8/fp8 ``values`` + broadcast-ready float32 ``scales``.

    Behaves enough like an array for the model code paths that touch
    weights: `.shape`/`.ndim` report the logical (values) geometry,
    `.astype(dt)` DEQUANTIZES into ``dt`` (which is why
    ``jnp.matmul(x, p["Wq"].astype(x.dtype))`` — the idiom every
    forward/decode path already uses — works unchanged on a quantized
    tree), and `qt[i]` slices values and scales in lockstep (the
    per-layer indexing of the unrolled decode loop). ``mode`` rides as
    pytree aux data, so it survives tracing and checkpoint templates.
    """

    __slots__ = ("values", "scales", "mode")

    def __init__(self, values, scales, mode: str = "int8"):
        self.values = values
        self.scales = scales
        self.mode = mode

    # -- array-ish surface -------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self.values.shape)

    @property
    def ndim(self) -> int:
        return len(self.values.shape)

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def nbytes(self) -> int:
        """At-rest bytes (values + scales) — the HBM accounting unit."""
        return int(self.values.nbytes) + int(self.scales.nbytes)

    def astype(self, dt) -> Array:
        """Dequantize into ``dt`` — the on-the-fly path: weights rest
        quantized, each use rebuilds the activation-dtype panel. The
        multiply happens in float32 before the final cast so bf16
        activation dtypes don't round the scale application itself."""
        return (self.values.astype(jnp.float32)
                * self.scales).astype(dt)

    def __getitem__(self, idx) -> "QuantizedTensor":
        return QuantizedTensor(self.values[idx], self.scales[idx],
                               self.mode)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"QuantizedTensor(mode={self.mode!r}, "
                f"values={self.values.shape}@{self.values.dtype}, "
                f"scales={getattr(self.scales, 'shape', ())})")

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten_with_keys(self):
        return (((jax.tree_util.GetAttrKey("values"), self.values),
                 (jax.tree_util.GetAttrKey("scales"), self.scales)),
                self.mode)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)


def quantize(x, axis: Union[int, Tuple[int, ...]] = -2,
             mode: str = "int8") -> QuantizedTensor:
    """Symmetric absmax quantization of ``x`` along ``axis`` (the
    reduction/contraction axes — everything NOT in ``axis`` gets its
    own scale). For a weight ``W [in, out]`` the default ``axis=-2``
    is per-output-channel. All-zero channels get scale 1.0 so
    dequantization never divides by zero."""
    mode = resolve_mode(mode)
    if mode is None:
        raise ValueError("quantize() needs a concrete mode, got None")
    x = jnp.asarray(x)
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis,
                   keepdims=True)
    scale = jnp.where(amax > 0.0, amax / _qmax(mode), 1.0)
    scale = scale.astype(jnp.float32)
    if mode == "int8":
        q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                     -INT8_QMAX, INT8_QMAX).astype(jnp.int8)
    else:
        q = (x.astype(jnp.float32) / scale).astype(_qdtype(mode))
    return QuantizedTensor(q, scale, mode)


def dequantize(qt: QuantizedTensor, dtype=jnp.float32) -> Array:
    """Broadcast-multiply back to a dense array in ``dtype``."""
    return qt.astype(dtype)


def fake_quant(x, axis: Union[int, Tuple[int, ...]] = -2,
               mode: str = "int8") -> Array:
    """quantize → dequantize round trip at the input's dtype: the
    accuracy-study primitive (exactly the numeric error a quantized
    deployment sees, without changing the tree structure)."""
    x = jnp.asarray(x)
    return dequantize(quantize(x, axis=axis, mode=mode), x.dtype)


def quantized_matmul(x: Array, w: Any) -> Array:
    """``x @ w`` where ``w`` may be a QuantizedTensor or a plain
    array: quantized weights are dequantized ON THE FLY into the
    activation dtype (never materialized at rest), plain arrays take
    the ordinary cast — one call site serves mixed trees."""
    return jnp.matmul(x, w.astype(x.dtype))
