"""Quantized slot-pool KV cache: int8 rows + per-row float32 scales.

The continuous-batching pool (parallel/serving.py `init_slot_state`)
keeps [L, Ns, S, D] K/V buffers resident for the engine's lifetime —
at activation dtype that is the second-largest HBM tenant after the
weights, and it scales with `num_slots`. Quantizing it row-wise buys
~4x more slots per byte (int8 values + one f32 scale per D-row ≈
D + 4·tp bytes vs 4·D float32):

- **Granularity: one scale per written K/V ROW** (per layer, slot,
  position — and per model-rank: each rank quantizes its own D_loc
  head shard independently, so no collective ever touches scales).
  A row is written exactly once (position p's K/V never changes), so
  quantize-on-write is a single absmax+round on a [D_loc] vector and
  the scale is final — no requantization, no running maxima.
- **Dequantize-on-read happens in the SCORES, not the cache**: the
  attention consumer folds the K scale into the logits
  (``(q·k_int) * kscale_row``) and the V scale into the probabilities
  (``(p * vscale_row) · v_int``) — algebraically identical to
  dequantizing the cache but touching only [Ns, S]-shaped scale
  vectors instead of rebuilding [Ns, S, D] panels.
- **Scale layout** ``[L, Ns, S, tp]`` with spec
  ``P(None, 'data', None, 'model')``: the trailing axis holds each
  model-rank's independent scale (local shape [L, ns, S, 1]), which
  keeps shard_map's replication checking honest — the scales ARE
  different per rank and the spec says so.

Error shape: per-row absmax int8 keeps relative row error <= 1/254,
uniform across positions — unlike per-tensor scales, where one hot
row would stretch the grid for every cached position. Accuracy
obligations (token fidelity of int8-KV continuous decode vs the float
path) are pinned in tests/test_quant.py.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.quant.core import (FP8_QMAX, INT8_QMAX,
                                           resolve_mode)

Array = jax.Array

# mirrors parallel/serving.py's slot-pool placement
_KV_SPEC = P(None, "data", None, "model")      # [L, Ns, S, D]
_SCALE_SPEC = P(None, "data", None, "model")   # [L, Ns, S, tp]
_VEC_SPEC = P("data")


def kv_cache_dtype(kv_mode: str):
    return jnp.int8 if kv_mode == "int8" else jnp.float8_e4m3fn


def quantize_rows(x: Array, kv_mode: str = "int8"
                  ) -> Tuple[Array, Array]:
    """Quantize ``x [..., D]`` row-wise (absmax over the last axis):
    returns (values [..., D] int8/fp8, scales [...] float32). Zero
    rows (never-written cache slots) get scale 1.0."""
    qmax = INT8_QMAX if kv_mode == "int8" else FP8_QMAX
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0.0, amax / qmax, 1.0)
    if kv_mode == "int8":
        q = jnp.clip(jnp.round(xf / scale),
                     -INT8_QMAX, INT8_QMAX).astype(jnp.int8)
    else:
        q = (xf / scale).astype(kv_cache_dtype(kv_mode))
    return q, scale[..., 0].astype(jnp.float32)


def init_quant_slot_state(cfg, mesh: Mesh, num_slots: int,
                          kv_mode: str = "int8"):
    """Allocate the quantized slot-pool state on the serving mesh:
    (ck, cv) int8/fp8 [L, Ns, S, D] + (kscale, vscale) float32
    [L, Ns, S, tp] + per-slot (pos, tok) — the 6-tuple analog of
    `parallel.serving.init_slot_state`'s 4-tuple, consumed by the
    `kv_mode=...` variants of the continuous prefill/decode programs.
    Same functional contract: every program consumes and returns the
    whole state, so a failed call leaves the pool bit-identical."""
    from deeplearning4j_tpu.models.transformer import slot_cache_shape
    kv_mode = resolve_mode(kv_mode)
    if kv_mode is None:
        raise ValueError("init_quant_slot_state needs kv_mode "
                         "('int8'/'fp8')")
    dp = mesh.shape["data"]
    tp = mesh.shape["model"]
    if num_slots % dp:
        raise ValueError(f"num_slots {num_slots} not divisible by "
                         f"data axis {dp}")
    shape = slot_cache_shape(cfg, num_slots)
    sshape = shape[:3] + (tp,)
    qdt = kv_cache_dtype(kv_mode)
    kv_sh = NamedSharding(mesh, _KV_SPEC)
    sc_sh = NamedSharding(mesh, _SCALE_SPEC)
    vec_sh = NamedSharding(mesh, _VEC_SPEC)
    ck = jax.device_put(jnp.zeros(shape, qdt), kv_sh)
    cv = jax.device_put(jnp.zeros(shape, qdt), kv_sh)
    ksc = jax.device_put(jnp.ones(sshape, jnp.float32), sc_sh)
    vsc = jax.device_put(jnp.ones(sshape, jnp.float32), sc_sh)
    pos = jax.device_put(jnp.zeros((num_slots,), jnp.int32), vec_sh)
    tok = jax.device_put(jnp.zeros((num_slots,), jnp.int32), vec_sh)
    return ck, cv, ksc, vsc, pos, tok


def init_paged_quant_state(cfg, mesh: Mesh, num_slots: int,
                           page_size: int, num_pages: int,
                           kv_mode: str = "int8"):
    """Allocate the quantized PAGED pool state: (kp, vp) int8/fp8
    [L, num_pages, page_size, D] + (kscale, vscale) float32
    [L, num_pages, page_size, tp] + per-slot (pos, tok) — the paged
    analog of `init_quant_slot_state`, consumed by the ``kv_mode=``
    variants of `parallel.serving.make_paged_{prefill,decode}`. The
    per-row scale layout is unchanged — one scale per written K/V row
    per model-rank — it just lives at (page, offset) instead of
    (slot, position), which is exactly why the int8 pool composes with
    paging: a page's rows carry their scales with them through any
    block-table remap, share, or copy-on-write."""
    from deeplearning4j_tpu.models.transformer import page_pool_shape
    kv_mode = resolve_mode(kv_mode)
    if kv_mode is None:
        raise ValueError("init_paged_quant_state needs kv_mode "
                         "('int8'/'fp8')")
    tp = mesh.shape["model"]
    shape = page_pool_shape(cfg, num_pages, page_size)
    sshape = shape[:3] + (tp,)
    qdt = kv_cache_dtype(kv_mode)
    kv_sh = NamedSharding(mesh, _KV_SPEC)
    sc_sh = NamedSharding(mesh, _SCALE_SPEC)
    vec_sh = NamedSharding(mesh, P(None))
    kp = jax.device_put(jnp.zeros(shape, qdt), kv_sh)
    vp = jax.device_put(jnp.zeros(shape, qdt), kv_sh)
    ksc = jax.device_put(jnp.ones(sshape, jnp.float32), sc_sh)
    vsc = jax.device_put(jnp.ones(sshape, jnp.float32), sc_sh)
    pos = jax.device_put(jnp.zeros((num_slots,), jnp.int32), vec_sh)
    tok = jax.device_put(jnp.zeros((num_slots,), jnp.int32), vec_sh)
    return kp, vp, ksc, vsc, pos, tok


def paged_pool_bytes(cfg, num_slots: int, page_size: int,
                     num_pages: int, max_pages: int,
                     kv_mode: Optional[str] = None, tp: int = 1,
                     cache_dtype=None) -> int:
    """Analytic at-rest bytes of one PAGED pool (page caches + scales
    + block tables + per-slot vectors) — the paged branch of the
    `serving_kv_pool_bytes` gauge. The headline capacity lever: the
    pool is sized by ``num_pages`` (actual working set + shared
    prefixes), not ``num_slots * max_len`` (every slot's worst
    case)."""
    L = cfg.n_layers
    d = cfg.d_model
    if kv_mode is not None:
        item = jnp.dtype(kv_cache_dtype(kv_mode)).itemsize
        scales = 2 * L * num_pages * page_size * tp * 4
    else:
        dt = cache_dtype if cache_dtype is not None \
            else cfg.cache_jnp_dtype()
        item = jnp.dtype(dt).itemsize
        scales = 0
    pool = 2 * L * num_pages * page_size * d * item
    bt = num_slots * max_pages * 4
    return pool + scales + bt + 2 * num_slots * 4


def handoff_page_bucket(npages: int, max_pages: int) -> int:
    """Power-of-two page-count bucket for one handoff's row/gather
    geometry (ISSUE-19): the batched adopt/export programs pad their
    row buffers and index vectors to this, so host<->device transfer
    scales with the CHAIN length (within a 2x bucket) while the
    compiled-program count stays log2-bounded at
    ceil(log2(max_pages)) + 1 geometries."""
    b = 1
    while b < max(1, int(npages)):
        b *= 2
    return min(b, int(max_pages))


def handoff_row_buffers(kv, n_layers: int, npages: int,
                        page_size: int, value_dtype) -> list:
    """Pad a `KVHandoff`'s rows — and the per-row scales, which
    TRAVEL WITH their rows — to the bucketed
    [L, npages * page_size, ...] geometry and reshape to page
    granularity: the runtime-data form the engine's batched all-layer
    adopt programs scatter from in ONE launch (ISSUE-19). Unwritten
    value rows are zero; unwritten scale rows are 1.0 (the
    never-written-row convention of the quantized pools), so a
    partially filled tail page adopts cleanly."""
    import numpy as np
    cap = npages * page_size
    if kv.pos > cap:
        raise ValueError(
            f"handoff bucket too small: {kv.pos} rows > "
            f"{npages} pages x {page_size}")
    rows = []
    for src in (kv.k, kv.v):
        buf = np.zeros((n_layers, cap, src.shape[-1]), value_dtype)
        buf[:, :kv.pos] = src
        rows.append(buf.reshape(n_layers, npages, page_size, -1))
    if kv.kv_mode:
        for src in (kv.k_scale, kv.v_scale):
            buf = np.ones((n_layers, cap, src.shape[-1]), np.float32)
            buf[:, :kv.pos] = src
            rows.append(buf.reshape(n_layers, npages, page_size, -1))
    return rows


def handoff_bytes(cfg, tokens: int, kv_mode: Optional[str] = None,
                  tp: int = 1, cache_dtype=None) -> int:
    """Analytic bytes one cross-tier KV handoff moves for a committed
    prefix of ``tokens`` K/V rows (ISSUE-11): K + V values at the pool
    dtype plus — when the pool is quantized — the per-row float32
    scales, which TRAVEL WITH their rows through the host-gather →
    device-put hop exactly as they travel with their page through
    share/COW remaps. Backs `serving_handoff_bytes_total` and is the
    operator's interconnect-budget input when the tiers stop sharing
    a host."""
    L = cfg.n_layers
    d = cfg.d_model
    if kv_mode is not None:
        item = jnp.dtype(kv_cache_dtype(kv_mode)).itemsize
        scales = 2 * L * tokens * tp * 4
    else:
        dt = cache_dtype if cache_dtype is not None \
            else cfg.cache_jnp_dtype()
        item = jnp.dtype(dt).itemsize
        scales = 0
    return 2 * L * tokens * d * item + scales


def slot_pool_bytes(cfg, num_slots: int,
                    kv_mode: Optional[str] = None, tp: int = 1,
                    cache_dtype=None) -> int:
    """Analytic at-rest bytes of one slot pool (caches + scales +
    per-slot vectors) — the `serving_kv_bytes_per_slot` /
    `serving_kv_pool_bytes` gauges' backing computation. Analytic
    rather than measured so operators can size pools BEFORE the lazily
    allocated state exists."""
    from deeplearning4j_tpu.models.transformer import slot_cache_shape
    L, ns, s, d = slot_cache_shape(cfg, num_slots)
    if kv_mode is not None:
        item = jnp.dtype(kv_cache_dtype(kv_mode)).itemsize
        scales = 2 * L * ns * s * tp * 4
    else:
        dt = cache_dtype if cache_dtype is not None \
            else cfg.cache_jnp_dtype()
        item = jnp.dtype(dt).itemsize
        scales = 0
    return 2 * L * ns * s * d * item + scales + 2 * ns * 4
