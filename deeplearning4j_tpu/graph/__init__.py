"""Graph embeddings (reference: deeplearning4j-graph)."""
from deeplearning4j_tpu.graph.graph import (Graph, Vertex, Edge,
                                            RandomWalkIterator,
                                            WeightedRandomWalkIterator,
                                            Node2VecWalkIterator,
                                            load_edge_list)
from deeplearning4j_tpu.graph.deepwalk import DeepWalk
from deeplearning4j_tpu.graph.node2vec import Node2Vec

__all__ = ["Graph", "Vertex", "Edge", "RandomWalkIterator",
           "WeightedRandomWalkIterator", "Node2VecWalkIterator",
           "load_edge_list", "DeepWalk", "Node2Vec"]
