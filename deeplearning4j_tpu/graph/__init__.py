"""Graph embeddings (reference: deeplearning4j-graph)."""
from deeplearning4j_tpu.graph.graph import (Graph, Vertex, Edge,
                                            RandomWalkIterator,
                                            WeightedRandomWalkIterator,
                                            load_edge_list)
from deeplearning4j_tpu.graph.deepwalk import DeepWalk

__all__ = ["Graph", "Vertex", "Edge", "RandomWalkIterator",
           "WeightedRandomWalkIterator", "load_edge_list", "DeepWalk"]
