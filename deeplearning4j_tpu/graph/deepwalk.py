"""DeepWalk graph embeddings.

Parity with the reference (reference:
deeplearning4j-graph/.../models/deepwalk/DeepWalk.java — skip-gram with
hierarchical softmax (GraphHuffman binary tree) over random walks;
models/embeddings/GraphVectors.java query API). Here DeepWalk subclasses
SequenceVectors: walks become token sequences ("vertex ids as words") and
training uses the batched XLA hierarchical-softmax skip-gram step — the
same re-design that replaced the hogwild word2vec (learning.py).
"""
from __future__ import annotations

from typing import Iterable, List, Optional

import numpy as np

from deeplearning4j_tpu.graph.graph import Graph, RandomWalkIterator
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors


class DeepWalk(SequenceVectors):
    """Reference: models/deepwalk/DeepWalk.java (Builder: vectorSize,
    windowSize, learningRate; fit(GraphWalkIterator))."""

    def __init__(self, *, vector_size: int = 100, window_size: int = 5,
                 learning_rate: float = 0.01, walk_length: int = 40,
                 walks_per_vertex: int = 1, seed: int = 12345, **kwargs):
        kwargs.setdefault("negative", 0)
        kwargs.setdefault("use_hierarchic_softmax", True)
        kwargs.setdefault("min_word_frequency", 1)
        super().__init__(layer_size=vector_size, window=window_size,
                         learning_rate=learning_rate, seed=seed, **kwargs)
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.graph: Optional[Graph] = None
        self._walks: List[List[str]] = []

    # SequenceVectors corpus = the collected walks
    def _sequences(self) -> Iterable[List[str]]:
        return self._walks

    def initialize(self, graph: Graph) -> None:
        """Reference: DeepWalk.initialize(graph) — sets up vocab over all
        vertices (every vertex appears, freq from walk occurrences)."""
        self.graph = graph

    def _make_walk_iterator(self, rep: int) -> RandomWalkIterator:
        """Walk-sampling strategy hook — subclasses (Node2Vec) override
        this single factory instead of re-implementing fit_graph."""
        return RandomWalkIterator(self.graph, self.walk_length,
                                  seed=self.seed + rep)

    def fit_graph(self, graph: Optional[Graph] = None,
                  walk_iterator: Optional[RandomWalkIterator] = None
                  ) -> "DeepWalk":
        """Reference: DeepWalk.fit(IGraph) / fit(GraphWalkIterator)."""
        if graph is not None:
            self.graph = graph
        if self.graph is None and walk_iterator is None:
            raise ValueError("need a graph or a walk iterator")
        self._walks = []
        if walk_iterator is None:
            for rep in range(self.walks_per_vertex):
                for walk in self._make_walk_iterator(rep):
                    self._walks.append([str(v) for v in walk])
        else:
            for walk in walk_iterator:
                self._walks.append([str(v) for v in walk])
        self.build_vocab()
        self.fit()
        return self

    # -- GraphVectors query API (reference: embeddings/GraphVectors.java) --
    def get_vertex_vector(self, idx: int) -> Optional[np.ndarray]:
        return self.word_vector(str(idx))

    def similarity_vertices(self, a: int, b: int) -> float:
        return self.similarity(str(a), str(b))

    def verticesNearest(self, idx: int, top_n: int = 10) -> List[int]:
        return [int(w) for w in self.words_nearest(str(idx), top_n)]

    @property
    def vector_size(self) -> int:
        return self.layer_size
