"""Node2Vec graph embeddings.

Parity with the reference's Node2Vec builder (reference:
deeplearning4j-nlp-parent inventory, SURVEY.md §2.5 — "Word2Vec /
ParagraphVectors / Glove / Node2Vec: Builder APIs wrapping
SequenceVectors"). Same re-design as DeepWalk: p/q-biased second-order
walks become token sequences, trained with the batched XLA skip-gram
step (negative sampling by default, matching the node2vec formulation)
instead of hogwild threads.
"""
from __future__ import annotations

from deeplearning4j_tpu.graph.deepwalk import DeepWalk
from deeplearning4j_tpu.graph.graph import Node2VecWalkIterator


class Node2Vec(DeepWalk):
    """DeepWalk with p/q-biased transition sampling. p penalizes
    returning to the previous vertex; q trades breadth-first (q>1 keeps
    walks local) vs depth-first exploration. Only the walk-sampling
    strategy differs from DeepWalk, so only the iterator factory is
    overridden."""

    def __init__(self, *, p: float = 1.0, q: float = 1.0,
                 negative: int = 5, **kwargs):
        kwargs.setdefault("use_hierarchic_softmax", negative == 0)
        super().__init__(negative=negative, **kwargs)
        self.p = p
        self.q = q

    def _make_walk_iterator(self, rep: int) -> Node2VecWalkIterator:
        return Node2VecWalkIterator(self.graph, self.walk_length,
                                    p=self.p, q=self.q,
                                    seed=self.seed + rep)
