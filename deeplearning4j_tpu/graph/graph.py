"""In-memory graph: vertices, edges, loaders.

Parity with the reference's graph core (reference:
deeplearning4j-graph/.../graph/Graph.java, api/Vertex.java, api/Edge.java,
graph/iterator/RandomWalkIterator.java, WeightedRandomWalkIterator.java,
data/GraphLoader.java).
"""
from __future__ import annotations

from typing import Generic, Iterable, Iterator, List, Optional, Sequence, \
    Tuple, TypeVar

import numpy as np

T = TypeVar("T")


class Vertex(Generic[T]):
    """Reference: api/Vertex.java — index + value."""

    def __init__(self, idx: int, value: T = None):
        self.idx = idx
        self.value = value

    def __repr__(self):
        return f"Vertex({self.idx}, {self.value!r})"


class Edge:
    """Reference: api/Edge.java — (from, to, weight, directed)."""

    def __init__(self, frm: int, to: int, weight: float = 1.0,
                 directed: bool = False):
        self.frm = frm
        self.to = to
        self.weight = weight
        self.directed = directed


class Graph(Generic[T]):
    """Adjacency-list graph (reference: graph/Graph.java)."""

    def __init__(self, num_vertices: int, allow_multiple_edges: bool = False):
        self._vertices: List[Vertex] = [Vertex(i) for i in
                                        range(num_vertices)]
        self._adj: List[List[Tuple[int, float]]] = \
            [[] for _ in range(num_vertices)]
        self.allow_multiple_edges = allow_multiple_edges

    def num_vertices(self) -> int:
        return len(self._vertices)

    def get_vertex(self, idx: int) -> Vertex:
        return self._vertices[idx]

    def set_vertex_value(self, idx: int, value) -> None:
        self._vertices[idx].value = value

    def add_edge(self, frm: int, to: int, weight: float = 1.0,
                 directed: bool = False) -> None:
        if not self.allow_multiple_edges and \
                any(t == to for t, _ in self._adj[frm]):
            return
        self._adj[frm].append((to, weight))
        if not directed:
            self._adj[to].append((frm, weight))

    def get_connected_vertex_indices(self, idx: int) -> List[int]:
        return [t for t, _ in self._adj[idx]]

    def degree(self, idx: int) -> int:
        return len(self._adj[idx])

    def weights_of(self, idx: int) -> np.ndarray:
        return np.array([w for _, w in self._adj[idx]], np.float64)


class RandomWalkIterator:
    """Uniform random walks of fixed length from every vertex (reference:
    graph/iterator/RandomWalkIterator.java; NoEdgeHandling modes
    SELF_LOOP_ON_DISCONNECTED / EXCEPTION_ON_DISCONNECTED)."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 12345,
                 no_edge_handling: str = "self_loop"):
        self.graph = graph
        self.walk_length = walk_length
        self.rng = np.random.default_rng(seed)
        self.no_edge_handling = no_edge_handling
        self._order = self.rng.permutation(graph.num_vertices())
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._order)

    def next(self) -> List[int]:
        start = int(self._order[self._pos])
        self._pos += 1
        walk = [start]
        cur = start
        for _ in range(self.walk_length - 1):
            neigh = self.graph.get_connected_vertex_indices(cur)
            if not neigh:
                if self.no_edge_handling == "exception":
                    raise ValueError(
                        f"Vertex {cur} has no edges (NoEdgeHandling."
                        "EXCEPTION_ON_DISCONNECTED)")
                walk.append(cur)  # self loop
                continue
            cur = int(neigh[self.rng.integers(0, len(neigh))])
            walk.append(cur)
        return walk

    def reset(self) -> None:
        self._order = self.rng.permutation(self.graph.num_vertices())
        self._pos = 0

    def __iter__(self) -> Iterator[List[int]]:
        self.reset()
        while self.has_next():
            yield self.next()


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional walks (reference:
    WeightedRandomWalkIterator.java)."""

    def next(self) -> List[int]:
        start = int(self._order[self._pos])
        self._pos += 1
        walk = [start]
        cur = start
        for _ in range(self.walk_length - 1):
            neigh = self.graph.get_connected_vertex_indices(cur)
            if not neigh:
                walk.append(cur)
                continue
            w = self.graph.weights_of(cur)
            p = w / w.sum()
            cur = int(neigh[self.rng.choice(len(neigh), p=p)])
            walk.append(cur)
        return walk


class Node2VecWalkIterator(RandomWalkIterator):
    """Second-order p/q-biased walks (node2vec; reference API surface:
    deeplearning4j-nlp models/node2vec/Node2Vec — builder wrapping
    SequenceVectors, SURVEY.md §2.5). Transition weight from walk step
    (prev → cur) to neighbor x: 1/p if x == prev (return), 1 if x is a
    neighbor of prev (BFS-like), else 1/q (DFS-like)."""

    def __init__(self, graph: Graph, walk_length: int, *, p: float = 1.0,
                 q: float = 1.0, seed: int = 12345,
                 no_edge_handling: str = "self_loop"):
        super().__init__(graph, walk_length, seed=seed,
                         no_edge_handling=no_edge_handling)
        self.p = p
        self.q = q
        self._neigh_sets = [set(graph.get_connected_vertex_indices(v))
                            for v in range(graph.num_vertices())]

    def next(self) -> List[int]:
        start = int(self._order[self._pos])
        self._pos += 1
        walk = [start]
        prev: Optional[int] = None
        cur = start
        for _ in range(self.walk_length - 1):
            neigh = self.graph.get_connected_vertex_indices(cur)
            if not neigh:
                if self.no_edge_handling == "exception":
                    raise ValueError(f"Vertex {cur} has no edges")
                walk.append(cur)
                continue
            if prev is None:
                nxt = int(neigh[self.rng.integers(0, len(neigh))])
            else:
                w = np.empty(len(neigh))
                prev_neigh = self._neigh_sets[prev]
                for i, x in enumerate(neigh):
                    if x == prev:
                        w[i] = 1.0 / self.p
                    elif x in prev_neigh:
                        w[i] = 1.0
                    else:
                        w[i] = 1.0 / self.q
                nxt = int(neigh[self.rng.choice(len(neigh),
                                                p=w / w.sum())])
            prev, cur = cur, nxt
            walk.append(cur)
        return walk


def load_edge_list(path: str, num_vertices: Optional[int] = None,
                   directed: bool = False, delimiter: Optional[str] = None
                   ) -> Graph:
    """Edge-list file loader (reference: data/GraphLoader.java
    loadUndirectedGraphEdgeListFile)."""
    edges = []
    max_idx = -1
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(delimiter)
            frm, to = int(parts[0]), int(parts[1])
            w = float(parts[2]) if len(parts) > 2 else 1.0
            edges.append((frm, to, w))
            max_idx = max(max_idx, frm, to)
    g = Graph(num_vertices or max_idx + 1)
    for frm, to, w in edges:
        g.add_edge(frm, to, w, directed)
    return g
