"""Small shared helpers."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def promote_score(x: jax.Array) -> jax.Array:
    """Promote a loss value to at least float32 (bfloat16 training still
    accumulates scores in f32; float64 gradient-check mode stays f64)."""
    return x.astype(jnp.promote_types(x.dtype, jnp.float32))


def render_summary_table(rows, total_params: int) -> str:
    """Shared renderer for MultiLayerNetwork/ComputationGraph.summary():
    header+rows (tuples of str) -> aligned table + total line."""
    widths = [max(len(r[c]) for r in rows) for c in range(len(rows[0]))]
    lines = ["  ".join(v.ljust(widths[c]) for c, v in enumerate(r))
             for r in rows]
    lines.append(f"Total parameters: {total_params:,}")
    return "\n".join(lines)


def count_params(tree) -> int:
    import jax
    import numpy as np
    return int(sum(np.prod(np.asarray(v).shape)
                   for v in jax.tree_util.tree_leaves(tree)))
