"""Small shared helpers."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def promote_score(x: jax.Array) -> jax.Array:
    """Promote a loss value to at least float32 (bfloat16 training still
    accumulates scores in f32; float64 gradient-check mode stays f64)."""
    return x.astype(jnp.promote_types(x.dtype, jnp.float32))
